"""Online anomaly detection over flight-record / history streams.

Per watched field the detector keeps a trailing window and an EWMA and
scores each new observation with a **robust z**:

    z = 0.6745 * |x - median(window)| / MAD(window)

(median absolute deviation, scaled so z is comparable to a normal
z-score). Medians and MADs shrug off the very outliers being hunted,
so a single tick spike cannot drag the baseline after it. A detection
fires when the window holds at least ``min_samples`` points and
``z > threshold``. Optional **floors** encode pinned steady-state
expectations (the fused tick's dispatches/host_syncs per tick): the
field is flagged the moment it exceeds its floor, no warmup — a fused
tick that silently grew a host round-trip trips the floor on the first
bad tick.

Everything is plain Python arithmetic over ``sorted()`` — bit-stable
across runs, which is what lets chaos verdicts embed windowed detector
output and stay byte-identical under seeded replay. ``observe``
consumes live records (server tick loop); ``scan`` replays a record
list (history segments, chaos rings) with dotted-path field access
(``"admission.s0.level"``).

Detections are plain dicts; the server turns them into
``detect.anomaly`` trace instants, an ``anomalies`` chrome-overlay
counter track, and a machine-readable SLO verdict via
``detector_anomaly_spec``.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Dict, List, Optional, Sequence

__all__ = ["AnomalyDetector", "DEFAULT_FIELDS", "robust_z"]

# The server streams watched by default: tick wall time, the fused
# tick's dispatch accounting (vs pinned floors when given), the scoped
# solve's per-tick scope, and the admission level.
DEFAULT_FIELDS = (
    "wall_ms",
    "dispatches",
    "host_syncs",
    "scoped_rows",
    "admission_level",
)

# Normal-consistency constant: MAD * 1.4826 estimates sigma, so
# 0.6745/MAD-scaled deviations read like z-scores.
_MAD_SCALE = 0.6745


def _median(ordered: Sequence[float]) -> float:
    n = len(ordered)
    mid = n // 2
    if n % 2:
        return ordered[mid]
    return 0.5 * (ordered[mid - 1] + ordered[mid])


def robust_z(value: float, window: Sequence[float]) -> float:
    """Robust z-score of ``value`` against ``window`` (which need not
    contain it). Zero MAD (constant window) scores any deviation as
    +inf and an exact match as 0."""
    ordered = sorted(window)
    if not ordered:
        return 0.0
    med = _median(ordered)
    mad = _median(sorted(abs(v - med) for v in ordered))
    dev = abs(value - med)
    if mad == 0.0:
        return 0.0 if dev == 0.0 else float("inf")
    return _MAD_SCALE * dev / mad


class _FieldState:
    __slots__ = ("window", "ewma", "detections")

    def __init__(self, capacity: int):
        self.window: deque = deque(maxlen=capacity)
        self.ewma: Optional[float] = None
        self.detections = 0


def _field_value(rec: dict, field: str):
    """Dotted-path field access: "admission.s0.level" walks nested
    dicts (the chaos runner's per-tick admission blocks)."""
    cur = rec
    for part in field.split("."):
        if not isinstance(cur, dict):
            return None
        cur = cur.get(part)
    return cur


class AnomalyDetector:
    """Windowed robust-z + EWMA detector over named record fields."""

    def __init__(
        self,
        fields: Sequence[str] = DEFAULT_FIELDS,
        *,
        window: int = 64,
        min_samples: int = 16,
        threshold: float = 6.0,
        ewma_alpha: float = 0.25,
        floors: Optional[Dict[str, float]] = None,
    ):
        if window <= 1:
            raise ValueError("window must be > 1")
        self.fields = tuple(fields)
        self.window = int(window)
        self.min_samples = max(2, int(min_samples))
        self.threshold = float(threshold)
        self.ewma_alpha = float(ewma_alpha)
        self.floors = dict(floors or {})
        self._lock = threading.Lock()
        self._state: Dict[str, _FieldState] = {
            f: _FieldState(self.window) for f in self.fields
        }
        self.anomalies = 0

    # -- online ---------------------------------------------------------

    def observe(self, rec: dict) -> List[dict]:
        """Score one record; returns the detections it fired (possibly
        empty). Updates window/EWMA state either way."""
        out: List[dict] = []
        with self._lock:
            for field in self.fields:
                v = _field_value(rec, field)
                if isinstance(v, bool) or not isinstance(v, (int, float)):
                    continue
                x = float(v)
                st = self._state[field]
                det = self._score_locked(field, st, x, rec)
                if det is not None:
                    out.append(det)
                # The anomaly does NOT poison the baseline: a flagged
                # point still enters the window (median/MAD absorb it),
                # and the EWMA tracks it so a level *shift* stops
                # firing once the window catches up.
                st.window.append(x)
                st.ewma = (
                    x
                    if st.ewma is None
                    else st.ewma + self.ewma_alpha * (x - st.ewma)
                )
        return out

    def _score_locked(
        self, field: str, st: _FieldState, x: float, rec: dict
    ) -> Optional[dict]:
        floor = self.floors.get(field)
        reasons = []
        z = robust_z(x, st.window)
        if len(st.window) >= self.min_samples and z > self.threshold:
            reasons.append("robust_z")
        if floor is not None and x > floor:
            reasons.append("floor")
        if not reasons:
            return None
        st.detections += 1
        self.anomalies += 1
        ordered = sorted(st.window)
        det = {
            "field": field,
            "value": x,
            "z": (round(z, 4) if z != float("inf") else "inf"),
            "median": _median(ordered) if ordered else None,
            "ewma": None if st.ewma is None else round(st.ewma, 6),
            "floor": floor,
            "reasons": reasons,
            "window": len(st.window),
        }
        for key in ("tick", "hseq", "t", "seq"):
            if key in rec:
                det[key] = rec[key]
        return det

    # -- batch ----------------------------------------------------------

    @classmethod
    def scan_records(
        cls,
        records: Sequence[dict],
        fields: Sequence[str] = DEFAULT_FIELDS,
        **kwargs,
    ) -> dict:
        """Replay a record list through a fresh detector (chaos
        verdicts, cmd.obs): returns {"anomalies": n, "detections":
        [...], "per_field": {field: n}} — deterministic for a
        deterministic record list."""
        det = cls(fields, **kwargs)
        detections: List[dict] = []
        for rec in records:
            detections.extend(det.observe(rec))
        return {
            "anomalies": det.anomalies,
            "detections": detections,
            "per_field": {
                f: det._state[f].detections
                for f in det.fields
                if det._state[f].detections
            },
        }

    def status(self) -> dict:
        with self._lock:
            return {
                "fields": list(self.fields),
                "window": self.window,
                "threshold": self.threshold,
                "anomalies": self.anomalies,
                "floors": dict(self.floors),
                "per_field": {
                    f: {
                        "n": len(st.window),
                        "ewma": st.ewma,
                        "detections": st.detections,
                    }
                    for f, st in self._state.items()
                },
            }
