"""Per-phase tick telemetry: one recorder that fans each measured phase
lap out to every consumer at once.

The solvers used to keep private write-only `phase_s` dicts that only
bench.py ever read; a tick's time breakdown (upload vs. solve vs.
download vs. apply) was invisible at runtime. A PhaseRecorder keeps the
cumulative dict (bench.py and /debug/status still read it) and
additionally publishes every lap as:

  * a histogram sample in the DEFAULT metrics registry
    (`doorman_tick_phase_seconds{component,phase}`) — scrape /metrics
    for per-phase distributions;
  * a last-tick gauge (`doorman_tick_phase_last_seconds{component,
    phase}`) — the most recent tick's breakdown at a glance;
  * a span in the trace ring (category `phase`) when the tracer is
    enabled, parented to whatever span is current (the server's tick
    span propagates into the executor thread via copy_context), so a
    Perfetto timeline shows the tick with its phase children.

Buckets are tuned for sub-tick phases (tens of microseconds to one
second); the default request buckets start at 5 ms and would flatten
every phase into the first bucket.
"""

from __future__ import annotations

import time
from typing import Dict, Tuple

from doorman_tpu.obs import metrics as metrics_mod
from doorman_tpu.obs import trace as trace_mod

__all__ = ["PHASE_BUCKETS", "PhaseRecorder", "last_shard_bytes"]

# (component, direction) -> per-shard payload bytes of the most recent
# mesh-sharded tick. The gauges carry the same numbers for /metrics;
# this plain snapshot lets the flight recorder embed them in its
# per-tick records without reparsing the registry.
_last_shard_bytes: Dict[Tuple[str, str], Tuple[int, ...]] = {}


def last_shard_bytes() -> Dict[Tuple[str, str], Tuple[int, ...]]:
    """Most recent per-shard payload bytes, keyed (component, direction)."""
    return dict(_last_shard_bytes)

PHASE_BUCKETS = (
    1e-5, 2.5e-5, 5e-5, 1e-4, 2.5e-4, 5e-4,
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 1.0,
)


def _phase_metrics() -> Tuple[metrics_mod.Histogram, metrics_mod.Gauge]:
    reg = metrics_mod.default_registry()
    hist = reg.histogram(
        "doorman_tick_phase_seconds",
        "Duration of one tick phase (upload/solve/download/apply, ...).",
        labels=("component", "phase"),
        buckets=PHASE_BUCKETS,
    )
    last = reg.gauge(
        "doorman_tick_phase_last_seconds",
        "Most recent tick's duration per phase.",
        labels=("component", "phase"),
    )
    return hist, last


def _shard_metrics() -> Tuple[metrics_mod.Gauge, metrics_mod.Gauge]:
    """Per-shard host-link traffic of one mesh-resident tick, for
    spotting an unbalanced delivery. The bytes reported are the REAL
    payloads (dirty slots, delivered rows); the wire additionally ships
    each shard's block padded to the max shard's bucketed width, so the
    skew ratio also reads as that padding's waste."""
    reg = metrics_mod.default_registry()
    per = reg.gauge(
        "doorman_tick_shard_bytes",
        "Per-shard host-link payload bytes of the last mesh-sharded "
        "tick (direction: upload/download).",
        labels=("component", "direction", "shard"),
    )
    skew = reg.gauge(
        "doorman_tick_shard_skew",
        "max/mean ratio of per-shard payload bytes for the last "
        "mesh-sharded tick (1.0 = perfectly balanced).",
        labels=("component", "direction"),
    )
    return per, skew


class PhaseRecorder:
    """Times consecutive laps of one tick for one component.

    `totals` is the solver's cumulative phase_s dict (seconds); lap()
    measures since the previous lap (or construction) and record()
    takes an externally measured duration. Construction reads the
    clock, so build it right where the first phase starts.
    """

    __slots__ = ("_component", "_totals", "_hist", "_last", "_t0")

    def __init__(self, component: str, totals: Dict[str, float]):
        self._component = component
        self._totals = totals
        self._hist, self._last = _phase_metrics()
        self._t0 = time.perf_counter()

    def reset(self) -> None:
        """Restart the lap clock without recording (rare resyncs, e.g.
        after a rebuild that is timed as its own phase)."""
        self._t0 = time.perf_counter()

    def lap(self, phase: str) -> float:
        t1 = time.perf_counter()
        dt = t1 - self._t0
        self._t0 = t1
        self._record(phase, dt, t1)
        return dt

    def record(self, phase: str, seconds: float) -> None:
        """Record an interval that ended now (measured by the caller)."""
        self._record(phase, seconds, time.perf_counter())

    def shard_bytes(self, direction: str, per_shard) -> None:
        """Per-shard payload bytes of one mesh-sharded tick. Lands as
        `doorman_tick_shard_bytes{component,direction,shard}` gauges
        plus a skew gauge (max/mean), and — when the tracer is on — a
        `shard.<direction>` instant on the timeline, so an unbalanced
        delivery shows up in /debug/traces right next to the tick's
        phase spans."""
        per = [int(b) for b in per_shard]
        if not per:
            return
        _last_shard_bytes[(self._component, direction)] = tuple(per)
        per_g, skew_g = _shard_metrics()
        for d, b in enumerate(per):
            per_g.set(b, self._component, direction, str(d))
        mean = sum(per) / len(per)
        skew = (max(per) / mean) if mean > 0 else 1.0
        skew_g.set(skew, self._component, direction)
        tracer = trace_mod.default_tracer()
        if tracer.enabled:
            tracer.instant(
                f"shard.{direction}",
                cat=f"phase:{self._component}",
                args={"bytes": per, "skew": round(skew, 3)},
            )

    def _record(self, phase: str, seconds: float, end: float) -> None:
        self._totals[phase] = self._totals.get(phase, 0.0) + seconds
        self._hist.observe(seconds, self._component, phase)
        self._last.set(seconds, self._component, phase)
        tracer = trace_mod.default_tracer()
        if tracer.enabled:
            tracer.add_complete(
                phase,
                ts_us=trace_mod.perf_to_us(end - seconds),
                dur_us=seconds * 1e6,
                cat=f"phase:{self._component}",
            )
