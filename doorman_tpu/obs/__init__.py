"""Observability: metrics registry (Prometheus text exposition), the
debug HTTP server with /debug/status, /debug/resources, /debug/traces,
/debug/slo, /debug/flightrec and /metrics, the zero-dependency span
tracer (obs.trace) with Chrome trace-event export, the declarative SLO
engine (obs.slo) and the per-tick flight recorder (obs.flightrec).

Capability parity with the reference's go/status/status.go (composable
status parts), go/cmd/doorman/resourcez.go (per-lease table), and the
Prometheus instrumentation in go/server/doorman/server.go:92-121,501-517.
"""

from doorman_tpu.obs.metrics import (
    Counter,
    Gauge,
    Histogram,
    Registry,
    default_registry,
    instrument_server,
)
from doorman_tpu.obs.debug import DebugServer, add_status_part
from doorman_tpu.obs.flightrec import FlightRecorder, store_digest
from doorman_tpu.obs.slo import (
    SloEngine,
    SloInputs,
    SloSpec,
    TrajectoryComparator,
    server_slos,
)
from doorman_tpu.obs.trace import Tracer, default_tracer

__all__ = [
    "Counter",
    "FlightRecorder",
    "Gauge",
    "Histogram",
    "Registry",
    "SloEngine",
    "SloInputs",
    "SloSpec",
    "Tracer",
    "TrajectoryComparator",
    "default_registry",
    "default_tracer",
    "instrument_server",
    "server_slos",
    "store_digest",
    "DebugServer",
    "add_status_part",
]
