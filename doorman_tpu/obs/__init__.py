"""Observability: metrics registry (Prometheus text exposition), the
debug HTTP server with /debug/status, /debug/resources, /debug/traces,
/debug/slo, /debug/flightrec, /debug/history and /metrics, the
zero-dependency span tracer (obs.trace) with Chrome trace-event export,
the declarative SLO engine (obs.slo), the per-tick flight recorder
(obs.flightrec), its durable multi-resolution history (obs.history),
the shadow-oracle auditor (obs.audit) and the online anomaly detector
(obs.detect).

Capability parity with the reference's go/status/status.go (composable
status parts), go/cmd/doorman/resourcez.go (per-lease table), and the
Prometheus instrumentation in go/server/doorman/server.go:92-121,501-517.
"""

from doorman_tpu.obs.metrics import (
    Counter,
    Gauge,
    Histogram,
    Registry,
    default_registry,
    instrument_server,
)
from doorman_tpu.obs.audit import ShadowAuditor
from doorman_tpu.obs.debug import DebugServer, add_status_part
from doorman_tpu.obs.detect import AnomalyDetector
from doorman_tpu.obs.flightrec import FlightRecorder, store_digest
from doorman_tpu.obs.history import HistoryStore
from doorman_tpu.obs.slo import (
    SloEngine,
    SloInputs,
    SloSpec,
    TrajectoryComparator,
    audit_divergence_spec,
    detector_anomaly_spec,
    server_slos,
)
from doorman_tpu.obs.trace import Tracer, default_tracer

__all__ = [
    "AnomalyDetector",
    "Counter",
    "FlightRecorder",
    "Gauge",
    "Histogram",
    "HistoryStore",
    "Registry",
    "ShadowAuditor",
    "SloEngine",
    "SloInputs",
    "SloSpec",
    "Tracer",
    "TrajectoryComparator",
    "audit_divergence_spec",
    "default_registry",
    "default_tracer",
    "detector_anomaly_spec",
    "instrument_server",
    "server_slos",
    "store_digest",
    "DebugServer",
    "add_status_part",
]
