"""Declarative SLOs with a small evaluation engine and a round-over-round
trajectory comparator.

The paper's contract is behavioral, not just fast: leases obey the
capacity window, top-band clients keep goodput under overload, masters
reconverge after flaps. Until now only tick wall-time reached the BENCH
artifacts — everything else lived in prose. This module turns the
contract into machine-readable verdicts:

  * `SloSpec` — one declarative objective: a name, a bound kind
    ("max": observed <= target, "min": observed >= target), the target,
    and a SOURCE descriptor naming the stream the observation comes
    from — a named sample stream (flight-recorder tick wall times), a
    histogram in a metrics Registry (RPC latency quantiles via
    Prometheus-style bucket interpolation), a scalar (reconvergence
    ticks, restore staleness), or the admission per-band tallies (the
    top-band goodput floor).
  * `SloEngine.evaluate(inputs)` — every spec against one `SloInputs`
    bundle, producing verdict dicts with status "pass" / "fail" /
    "no_data" (a missing stream is reported, never silently dropped:
    the r04/r05 lesson is that absent data must be loud).
  * `TrajectoryComparator` — reads the prior rounds' BENCH_r*.json
    artifacts committed at the repo root and computes deltas for metric
    rows (`delta`) and embedded SLO verdicts (`slo_delta`), so every
    new measurement states how it moved against the last round that
    measured the same thing. Diagnostics rows (unit == "error", e.g.
    the r05 `backend_unreachable` entry) are never ingested as metrics.

Consumers: CapacityServer.evaluate_slos() (the /debug/slo page and
status()), the chaos runner's verdict (reconvergence + top-band floor
over the deterministic tallies), and bench.py (every emitted metric row
carries a verdict and its delta vs the previous round).
"""

from __future__ import annotations

import json
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

from doorman_tpu.obs import metrics as metrics_mod

__all__ = [
    "SloEngine",
    "SloInputs",
    "SloSpec",
    "TrajectoryComparator",
    "audit_divergence_spec",
    "bench_verdict",
    "detector_anomaly_spec",
    "histogram_quantile",
    "population_scaling_verdict",
    "predictive_goodput_verdict",
    "reconvergence_spec",
    "sample_quantile",
    "server_slos",
    "storm_slo_verdicts",
    "top_band_goodput_spec",
    "tpu_tick_budget_spec",
    "tpu_tick_verdict",
    "workload_slos",
]

# The north-star tick budget (BASELINE.md): recompute every lease of the
# 1M x 10k table in under 100 ms.
TICK_BUDGET_MS = 100.0

# The one-chip accelerator target (ROADMAP "Sub-10 ms TPU tick"): the
# fused one-launch tick at the 1M-lease bench shape, p50, on real TPU
# hardware. A STANDING spec: bench.py attaches its verdict to the fused
# server-tick row whenever the round runs on an accelerator, so the
# next hardware round reports pass/fail automatically instead of
# re-deriving the target (CPU-fallback rounds record it as no_data —
# the target is a hardware claim, and a fail verdict from a CPU box
# would poison the trajectory comparator's deltas).
TPU_TICK_BUDGET_MS = 10.0


@dataclass(frozen=True)
class SloSpec:
    """One declarative objective.

    `source` describes where the observation comes from:
      {"type": "samples",   "stream": name, "quantile": q, "scale": s}
      {"type": "histogram", "metric": name, "labels": (...),
                            "quantile": q, "scale": s}
      {"type": "scalar",    "key": name, "scale": s}
      {"type": "band_goodput"}   # admitted/(admitted+shed) of the top
                                 # band in SloInputs.band_tallies
    `scale` multiplies the raw observation (1000.0 turns histogram
    seconds into ms targets). `kind` is "max" (observed <= target) or
    "min" (observed >= target).
    """

    name: str
    kind: str  # "max" | "min"
    target: float
    source: Dict
    unit: str = ""
    description: str = ""

    def __post_init__(self):
        if self.kind not in ("max", "min"):
            raise ValueError(f"unknown SLO kind {self.kind!r}")


@dataclass
class SloInputs:
    """Everything one evaluation pass may observe. All fields optional;
    a spec whose stream is absent yields a "no_data" verdict."""

    registry: Optional[metrics_mod.Registry] = None
    # name -> sample list (e.g. "tick_ms" from the flight recorder ring)
    samples: Dict[str, Sequence[float]] = field(default_factory=dict)
    # name -> scalar observation (reconvergence ticks, restore age, ...)
    scalars: Dict[str, float] = field(default_factory=dict)
    # priority band -> {"admitted": n, "shed": n, "fast_fail": n}
    # (admission's deterministic GetCapacity tallies)
    band_tallies: Dict[int, Dict[str, int]] = field(default_factory=dict)


def sample_quantile(values: Sequence[float], q: float) -> Optional[float]:
    """Nearest-rank quantile of a sample stream (None when empty); the
    same rule loadtest.storm reports, so verdicts and storm stats agree."""
    if not values:
        return None
    ordered = sorted(values)
    idx = min(
        len(ordered) - 1,
        max(0, int(round(q * (len(ordered) - 1)))),
    )
    return float(ordered[idx])


def histogram_quantile(
    hist: metrics_mod.Histogram, q: float, label_values: Sequence[str] = ()
) -> Optional[float]:
    """Prometheus-style quantile from a Histogram's cumulative buckets:
    linear interpolation inside the bucket the rank lands in; a rank in
    the +Inf bucket reports the highest finite bound (the histogram
    cannot resolve beyond it). None when the series has no samples."""
    key = tuple(str(v) for v in label_values)
    with hist._lock:
        counts = list(hist._counts.get(key, ()))
        total = hist._totals.get(key, 0)
    if total <= 0 or not counts:
        return None
    rank = q * total
    prev_cum, prev_bound = 0, 0.0
    for cum, bound in zip(counts, hist.buckets):
        if cum >= rank and cum > prev_cum:
            frac = (rank - prev_cum) / (cum - prev_cum)
            return prev_bound + max(0.0, min(frac, 1.0)) * (bound - prev_bound)
        prev_cum, prev_bound = cum, bound
    return float(hist.buckets[-1])


class SloEngine:
    """Evaluates a spec list against one SloInputs bundle."""

    def __init__(self, specs: Sequence[SloSpec]):
        self.specs = list(specs)

    def evaluate(self, inputs: SloInputs) -> List[dict]:
        return [self._one(spec, inputs) for spec in self.specs]

    # ------------------------------------------------------------------

    def _one(self, spec: SloSpec, inputs: SloInputs) -> dict:
        observed, detail = self._observe(spec, inputs)
        verdict = {
            "slo": spec.name,
            "kind": spec.kind,
            "target": spec.target,
            "unit": spec.unit,
            "observed": None if observed is None else round(observed, 6),
            "status": "no_data",
            "margin": None,
        }
        if spec.description:
            verdict["description"] = spec.description
        if observed is not None:
            ok = (
                observed <= spec.target
                if spec.kind == "max"
                else observed >= spec.target
            )
            verdict["status"] = "pass" if ok else "fail"
            # Positive margin = headroom, negative = by how much it blew.
            margin = (
                spec.target - observed
                if spec.kind == "max"
                else observed - spec.target
            )
            verdict["margin"] = round(margin, 6)
        if detail:
            verdict["detail"] = detail
        return verdict

    def _observe(
        self, spec: SloSpec, inputs: SloInputs
    ) -> Tuple[Optional[float], Optional[dict]]:
        src = spec.source
        kind = src.get("type")
        scale = float(src.get("scale", 1.0))
        if kind == "scalar":
            v = inputs.scalars.get(src["key"])
            return (None if v is None else float(v) * scale), None
        if kind == "samples":
            values = inputs.samples.get(src["stream"]) or ()
            v = sample_quantile(values, float(src.get("quantile", 0.5)))
            if v is None:
                return None, None
            return v * scale, {"samples": len(values)}
        if kind == "histogram":
            if inputs.registry is None:
                return None, None
            metric = next(
                (
                    m
                    for m in inputs.registry.metrics()
                    if m.name == src["metric"]
                ),
                None,
            )
            if not isinstance(metric, metrics_mod.Histogram):
                return None, None
            labels = tuple(src.get("labels", ()))
            v = histogram_quantile(
                metric, float(src.get("quantile", 0.99)), labels
            )
            if v is None:
                return None, None
            return v * scale, {"count": metric.count(*labels)}
        if kind == "band_goodput":
            tallies = inputs.band_tallies
            if not tallies:
                return None, None
            top = max(tallies)
            counts = tallies[top]
            detail = {
                "band": top,
                "per_band": {
                    str(b): dict(c) for b, c in sorted(tallies.items())
                },
            }
            total = counts.get("admitted", 0) + counts.get("shed", 0)
            if total == 0:
                return None, detail
            return counts.get("admitted", 0) / total, detail
        raise ValueError(f"unknown SLO source type {kind!r}")


# ----------------------------------------------------------------------
# Standard spec sets
# ----------------------------------------------------------------------


def top_band_goodput_spec(
    target: float = 0.99, name: str = "top_band_goodput"
) -> SloSpec:
    """The overload contract's floor: the highest priority band's
    admitted ratio. The chaos invariant pins the stronger form (zero
    shed while lower bands exist); the SLO keeps a numeric trajectory."""
    return SloSpec(
        name=name,
        kind="min",
        target=target,
        source={"type": "band_goodput"},
        unit="ratio",
        description=(
            "admitted/(admitted+shed) for the top priority band under "
            "overload — shedding walks up from the bottom band"
        ),
    )


def reconvergence_spec(budget_ticks: float, name: str = "reconverge_ticks"
                       ) -> SloSpec:
    """Post-heal reconvergence bound, in ticks (the chaos runner's
    converged-after-heal measurement vs the plan's budget)."""
    return SloSpec(
        name=name,
        kind="max",
        target=float(budget_ticks),
        source={"type": "scalar", "key": "reconverge_ticks"},
        unit="ticks",
        description="ticks after heal until allocations match baseline",
    )


def server_slos(
    *,
    tick_p50_ms: float = TICK_BUDGET_MS,
    tick_p99_ms: float = 2.5 * TICK_BUDGET_MS,
    rpc_p99_ms: float = 50.0,
    top_band_target: float = 0.99,
    restore_staleness_s: float = 60.0,
) -> List[SloSpec]:
    """The standing server-side spec set evaluated by
    CapacityServer.evaluate_slos() over the flight-recorder ring
    (tick_ms samples), a metrics registry (RPC histograms), the
    admission tallies, and the last restore summary."""
    return [
        SloSpec(
            "tick_budget_p50_ms", "max", tick_p50_ms,
            {"type": "samples", "stream": "tick_ms", "quantile": 0.5},
            unit="ms",
            description="north-star tick budget over the recorder window",
        ),
        SloSpec(
            "tick_budget_p99_ms", "max", tick_p99_ms,
            {"type": "samples", "stream": "tick_ms", "quantile": 0.99},
            unit="ms",
            description="tick tail over the recorder window",
        ),
        SloSpec(
            "get_capacity_p99_ms", "max", rpc_p99_ms,
            {
                "type": "histogram",
                "metric": "doorman_server_requests_durations",
                "labels": ("GetCapacity",),
                "quantile": 0.99,
                "scale": 1000.0,
            },
            unit="ms",
            description="GetCapacity p99 from the request histograms",
        ),
        top_band_goodput_spec(top_band_target),
        SloSpec(
            "restore_staleness_s", "max", restore_staleness_s,
            {"type": "scalar", "key": "restore_staleness_s"},
            unit="s",
            description=(
                "age of the state a warm takeover restored (journal "
                "freshness; bounded by the lease window)"
            ),
        ),
    ]


def audit_divergence_spec(name: str = "audit_divergence") -> SloSpec:
    """The standing shadow-oracle audit gate: the sampled fixpoint
    replay of every store through the numpy host oracles
    (obs/audit.py) must have found ZERO divergences. Any nonzero count
    is a live bit-identity violation — the production form of the
    per-lane parity pins."""
    return SloSpec(
        name=name,
        kind="max",
        target=0.0,
        source={"type": "scalar", "key": "audit_divergence"},
        unit="divergences",
        description=(
            "shadow-oracle audit divergences (store of record vs numpy "
            "oracle fixpoint, two-strike confirmed) — must stay zero"
        ),
    )


def detector_anomaly_spec(
    target: float = 0.0, name: str = "detector_anomalies"
) -> SloSpec:
    """The online anomaly detector's gate (obs/detect.py): robust-z /
    pinned-floor detections over the watched history streams. Default
    target zero — a steady run should not trip the detector."""
    return SloSpec(
        name=name,
        kind="max",
        target=float(target),
        source={"type": "scalar", "key": "detector_anomalies"},
        unit="detections",
        description=(
            "EWMA+MAD robust-z and pinned-floor detections over the "
            "flight-record history streams"
        ),
    )


def bench_verdict(row: dict) -> Optional[dict]:
    """The standing per-row bench SLO: any *_wall_ms metric is held to
    the north-star tick budget. Returns a verdict dict or None when the
    row has no applicable SLO (qps rows carry storm verdicts instead)."""
    metric = row.get("metric", "")
    value = row.get("value")
    if not metric.endswith("_wall_ms") or not isinstance(value, (int, float)):
        return None
    spec = SloSpec(
        f"{metric}:tick_budget", "max", TICK_BUDGET_MS,
        {"type": "scalar", "key": "v"}, unit="ms",
        description="north-star: <100 ms per tick",
    )
    return SloEngine([spec]).evaluate(
        SloInputs(scalars={"v": float(value)})
    )[0]


def tpu_tick_budget_spec(name: str = "tpu_tick_p50_ms") -> SloSpec:
    """The standing <10 ms one-chip accelerator target for the fused
    1M-lease server tick (see TPU_TICK_BUDGET_MS)."""
    return SloSpec(
        name=name,
        kind="max",
        target=TPU_TICK_BUDGET_MS,
        source={"type": "scalar", "key": "tick_p50_ms"},
        unit="ms",
        description=(
            "fused 1M-lease tick p50 on one accelerator chip — the "
            "ROADMAP 'Sub-10 ms TPU tick' target"
        ),
    )


def tpu_tick_verdict(p50_ms: float, *, cpu_fallback: bool) -> dict:
    """Evaluate the standing TPU tick budget for one bench round.
    CPU-fallback rounds yield an honest no_data verdict (the scalar is
    withheld — the target is a hardware claim); accelerator rounds
    report pass/fail automatically."""
    spec = tpu_tick_budget_spec()
    scalars = {} if cpu_fallback else {"tick_p50_ms": float(p50_ms)}
    verdict = SloEngine([spec]).evaluate(SloInputs(scalars=scalars))[0]
    if cpu_fallback:
        verdict["detail"] = {
            "reason": "cpu_fallback: hardware target not measurable",
            "cpu_p50_ms": round(float(p50_ms), 3),
        }
    return verdict


def storm_slo_verdicts(
    off: dict,
    on: dict,
    *,
    goodput_floor_ratio: float = 0.7,
    top_band_target: float = 0.99,
    p99_headroom: float = 1.25,
    name_prefix: str = "server_rpc_storm",
) -> List[dict]:
    """SLO verdicts for an admission off/on storm pair (loadtest.storm
    stats dicts): the top-band goodput floor over the admission-on
    tallies, per-band p99 ceilings (the admission-on tail must stay
    within `p99_headroom` of the admission-off tail for that band), and
    the goodput floor (on-goodput >= floor_ratio x off-goodput, the
    budget the controller was given to defend)."""
    bands = sorted(
        {int(b) for b in on.get("ok_by_band", {})}
        | {int(b) for b in on.get("shed_by_band", {})}
    )
    tallies = {
        b: {
            "admitted": int(on.get("ok_by_band", {}).get(b, 0)),
            "shed": int(on.get("shed_by_band", {}).get(b, 0)),
            "fast_fail": 0,
        }
        for b in bands
    }
    scalars: Dict[str, float] = {"goodput_qps": float(on["goodput_qps"])}
    specs = [
        top_band_goodput_spec(
            top_band_target, name=f"{name_prefix}:top_band_goodput"
        ),
        SloSpec(
            f"{name_prefix}:goodput_floor", "min",
            round(float(off["goodput_qps"]) * goodput_floor_ratio, 1),
            {"type": "scalar", "key": "goodput_qps"}, unit="qps",
            description=(
                f"admission-on goodput >= {goodput_floor_ratio:.0%} of "
                "admission-off"
            ),
        ),
    ]
    off_p99 = off.get("p99_s_by_band", {})
    on_p99 = on.get("p99_s_by_band", {})
    for b in bands:
        if b in off_p99 and b in on_p99:
            key = f"p99_ms_band{b}"
            scalars[key] = float(on_p99[b]) * 1000.0
            specs.append(SloSpec(
                f"{name_prefix}:{key}", "max",
                round(float(off_p99[b]) * 1000.0 * p99_headroom, 3),
                {"type": "scalar", "key": key}, unit="ms",
                description=(
                    "admission-on p99 must not exceed the admission-off "
                    f"tail for band {b} (x{p99_headroom:g} headroom)"
                ),
            ))
    return SloEngine(specs).evaluate(
        SloInputs(scalars=scalars, band_tallies=tallies)
    )


# The workload harness's gate vocabulary: gate name -> how it is
# observed. Each entry is (kind, source, unit, description); the
# scenario's spec.gates mapping picks gates by name and sets targets.
_WORKLOAD_GATES: Dict[str, tuple] = {
    "top_band_satisfaction": (
        "min",
        {"type": "scalar", "key": "top_band_satisfaction"},
        "ratio",
        "mean granted/wanted for the top band over measured ticks",
    ),
    "stress_satisfaction": (
        "min",
        {"type": "scalar", "key": "top_band_satisfaction_stress"},
        "ratio",
        "top-band satisfaction over the scenario's stress ticks "
        "(e.g. later flash-crowd windows)",
    ),
    "satisfaction": (
        "min",
        {"type": "scalar", "key": "satisfaction_overall"},
        "ratio",
        "mean granted/wanted across all bands over measured ticks",
    ),
    "top_band_goodput": (
        "min",
        {"type": "band_goodput"},
        "ratio",
        "admitted/(admitted+shed) of the top band (admission tallies)",
    ),
    "get_capacity_p99_ms": (
        "max",
        {"type": "samples", "stream": "get_capacity_wall_ms",
         "quantile": 0.99},
        "ms",
        "wall-clock GetCapacity p99 over the run (loopback)",
    ),
    "refresh_virtual_p99_ms": (
        "max",
        {"type": "samples", "stream": "refresh_virtual_ms",
         "quantile": 0.99},
        "ms",
        "virtual refresh latency p99 incl. the region RTT model",
    ),
    "reconverge_ticks": (
        "max",
        {"type": "scalar", "key": "reconverge_ticks"},
        "ticks",
        "ticks after the disturbance ends until base-client "
        "allocations match their baseline snapshot",
    ),
    "completions": (
        "min",
        {"type": "scalar", "key": "completions"},
        "jobs",
        "elastic jobs that reached total_work",
    ),
    "preemptions": (
        "min",
        {"type": "scalar", "key": "preemptions"},
        "jobs",
        "elastic preemption events (the scenario must exercise them)",
    ),
    "peak_population": (
        "min",
        {"type": "scalar", "key": "peak_population"},
        "clients",
        "max concurrent client population (the curve visibly moved)",
    ),
    "master_changes": (
        "min",
        {"type": "scalar", "key": "master_changes"},
        "changes",
        "mastership handovers observed (deploys visibly happened)",
    ),
    "refresh_ok_ratio": (
        "min",
        {"type": "scalar", "key": "refresh_ok_ratio"},
        "ratio",
        "successful refreshes / attempted, whole run",
    ),
    "fed_capacity_violations": (
        "max",
        {"type": "scalar", "key": "fed_capacity_violations"},
        "violations",
        "federated capacity-sum invariant violations (must be 0)",
    ),
    "epoch_changes": (
        "min",
        {"type": "scalar", "key": "epoch_changes"},
        "changes",
        "fleet routing-epoch changes applied (resharding visibly "
        "happened)",
    ),
    "stream_pushes": (
        "min",
        {"type": "scalar", "key": "stream_pushes"},
        "pushes",
        "lease deltas pushed to WatchCapacity subscribers",
    ),
    "frontend_frames": (
        "min",
        {"type": "scalar", "key": "frontend_frames"},
        "frames",
        "ring frames pumped through the frontend worker pool "
        "(the serving plane visibly carried the stream traffic)",
    ),
    "frontend_held": (
        "min",
        {"type": "scalar", "key": "frontend_held"},
        "streams",
        "WatchCapacity streams held by frontend workers at run end",
    ),
}


def workload_slos(
    gates: Dict[str, float], *, name_prefix: str
) -> List[SloSpec]:
    """Build the spec list for a workload scenario from its gate map
    (gate name -> target). Unknown gate names raise — a typo'd gate
    must fail the scenario author, not silently pass the run."""
    specs = []
    for gate, target in sorted(gates.items()):
        if gate not in _WORKLOAD_GATES:
            raise ValueError(
                f"unknown workload gate {gate!r} "
                f"(known: {sorted(_WORKLOAD_GATES)})"
            )
        kind, source, unit, description = _WORKLOAD_GATES[gate]
        specs.append(SloSpec(
            name=f"{name_prefix}:{gate}",
            kind=kind,
            target=float(target),
            source=dict(source),
            unit=unit,
            description=description,
        ))
    return specs


def predictive_goodput_verdict(
    predictive: float,
    reactive: float,
    *,
    name: str = "workload:flash_crowd_predictive:predictive_over_reactive",
) -> dict:
    """The standing predictive-vs-reactive head-to-head verdict: the
    predictive run's stressed top-band satisfaction must be at least
    the reactive run's (same scenario, same seed, forecaster on/off).
    The reactive observation IS the target, so the verdict and its
    round-over-round delta track the predictive margin directly."""
    spec = SloSpec(
        name=name,
        kind="min",
        target=round(float(reactive), 6),
        source={"type": "scalar", "key": "predictive"},
        unit="ratio",
        description=(
            "predictive top-band satisfaction over the stressed flash-"
            "crowd windows vs the reactive controller's (the target)"
        ),
    )
    verdict = SloEngine([spec]).evaluate(
        SloInputs(scalars={"predictive": float(predictive)})
    )[0]
    verdict["detail"] = {
        "predictive": round(float(predictive), 6),
        "reactive": round(float(reactive), 6),
    }
    return verdict


def population_scaling_verdict(
    exponent: float,
    *,
    target: float = 0.3,
    name: str = "workload_population_scaling:sublinear",
) -> dict:
    """The vector population engine's driver-cost SLO: the log-log
    slope of per-tick driver wall time vs population size over the
    measured tiers must stay under ``target`` (0.3 — near-flat, since
    the refresh spread holds the due set per tick roughly constant
    while the resident population grows three orders of magnitude).
    An exponent of 1.0 is the per-client path's linear walk; the array
    engine's whole point is that parked rows cost nothing."""
    spec = SloSpec(
        name=name,
        kind="max",
        target=float(target),
        source={"type": "scalar", "key": "exponent"},
        unit="exponent",
        description=(
            "log-log slope of per-tick vector-population driver wall "
            "time vs resident population size"
        ),
    )
    verdict = SloEngine([spec]).evaluate(
        SloInputs(scalars={"exponent": float(exponent)})
    )[0]
    return verdict


# ----------------------------------------------------------------------
# Trajectory comparator over the committed BENCH_r*.json rounds
# ----------------------------------------------------------------------

# Numeric row fields the comparator diffs when both rounds carry them.
_DELTA_FIELDS = (
    "value", "best_ms", "median_ms", "mean_ms", "p50_ms", "p90_ms",
    "p99_ms",
)

_ROUND_RE = re.compile(r"BENCH_r(\d+)\.json$")


class TrajectoryComparator:
    """Reads the prior rounds' BENCH_r*.json artifacts and answers
    "how did this metric / this SLO move since the last round that
    measured it?". Each artifact is {"n": round, "tail": "<stdout
    tail>", ...}; metric rows are the tail's JSON lines carrying
    numeric "metric"/"value" pairs (diagnostics — unit "error" — are
    excluded, the r05 backend_unreachable trap). Rows that embed "slo"
    verdicts are indexed by verdict name too, so verdict-level deltas
    start flowing the round after verdicts first ship."""

    def __init__(self, root: Optional[str] = None):
        base = Path(root) if root else self.default_root()
        # round -> {metric: row}; rounds ascending.
        self.rounds: List[Tuple[int, Dict[str, dict]]] = []
        paths = sorted(base.glob("BENCH_r*.json")) if base.is_dir() else []
        for path in paths:
            m = _ROUND_RE.search(path.name)
            if not m:
                continue
            try:
                data = json.loads(path.read_text())
            except (OSError, ValueError):
                continue
            n = int(data.get("n", int(m.group(1))))
            rows = self._parse_rows(data)
            if rows:
                self.rounds.append((n, rows))
        self.rounds.sort(key=lambda kv: kv[0])

    @staticmethod
    def default_root() -> Path:
        """The repo root (BENCH artifacts live beside bench.py)."""
        return Path(__file__).resolve().parents[2]

    @staticmethod
    def _parse_rows(data: dict) -> Dict[str, dict]:
        rows: Dict[str, dict] = {}
        candidates: List[dict] = []
        for line in str(data.get("tail", "")).splitlines():
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                obj = json.loads(line)
            except ValueError:
                continue
            if isinstance(obj, dict):
                candidates.append(obj)
        if isinstance(data.get("parsed"), dict):
            candidates.append(data["parsed"])
        for obj in candidates:
            metric = obj.get("metric")
            if (
                isinstance(metric, str)
                and isinstance(obj.get("value"), (int, float))
                and obj.get("unit") != "error"
                and "diagnostic" not in obj
            ):
                rows.setdefault(metric, obj)
        return rows

    # ------------------------------------------------------------------

    def previous(self, metric: str) -> Optional[Tuple[int, dict]]:
        """The LATEST prior round carrying this metric (rounds that
        degraded to diagnostics simply don't carry it)."""
        for n, rows in reversed(self.rounds):
            if metric in rows:
                return n, rows[metric]
        return None

    def delta(self, row: dict) -> Optional[dict]:
        """Field-by-field deltas of a metric row vs the last round that
        measured it; None when no prior round did."""
        prev = self.previous(str(row.get("metric", "")))
        if prev is None:
            return None
        n, prow = prev
        out: dict = {"round": n}
        for f in _DELTA_FIELDS:
            a, b = row.get(f), prow.get(f)
            if isinstance(a, (int, float)) and isinstance(b, (int, float)):
                out[f] = {
                    "prev": b,
                    "delta": round(a - b, 6),
                    "ratio": round(a / b, 4) if b else None,
                }
        return out

    def slo_delta(self, verdict: dict) -> Optional[dict]:
        """Delta of one SLO verdict vs the last round that embedded a
        verdict of the same name in any metric row."""
        name = verdict.get("slo")
        observed = verdict.get("observed")
        for n, rows in reversed(self.rounds):
            for prow in rows.values():
                embedded = prow.get("slo")
                if isinstance(embedded, dict):
                    embedded = [embedded]
                if not isinstance(embedded, list):
                    continue
                for pv in embedded:
                    if not (
                        isinstance(pv, dict) and pv.get("slo") == name
                    ):
                        continue
                    out = {"round": n, "prev_status": pv.get("status")}
                    pobs = pv.get("observed")
                    if isinstance(observed, (int, float)) and isinstance(
                        pobs, (int, float)
                    ):
                        out["prev_observed"] = pobs
                        out["delta_observed"] = round(observed - pobs, 6)
                    return out
        return None
