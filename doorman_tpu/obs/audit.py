"""Shadow-oracle audit: the bit-identity discipline as a production
invariant.

Every device lane in this repo is pinned bit-identical to a numpy host
oracle — at test time. This module enforces the same pin on a *live*
server: every K ticks (and on every ``solve_mode`` transition) the
tick loop snapshots each resource's staged solve inputs (kind,
capacity, static parameter, and the store's has/wants/subclients rows
— cheap host copies, no device sync) and replays them through
:func:`doorman_tpu.algorithms.tick.oracle_row` **off the hot path** in
a single-thread executor, comparing the oracle's grants against the
store of record.

The comparison leans on a fixpoint property of the lanes: at a
converged, delivered row one more oracle tick is idempotent —
``oracle_row(..., wants, has, sub) == has``. Lanes whose output is
has-independent (NO_ALGORITHM, STATIC, FAIR_SHARE, MAX_MIN_FAIR,
BALANCED_FAIRNESS, PROPORTIONAL_FAIRNESS) reach that fixpoint one
delivered tick after a wants change; the proportional lanes
(PROPORTIONAL_SHARE, PROPORTIONAL_TOPUP) converge toward it under
constant wants. Mid-convergence and delivery-lag states are absorbed
by the **two-strike rule**: a resource is flagged only when it
mismatches at two consecutive audit samples with an *identical* input
digest — a legitimately converging or lag-delayed row changes ``has``
between samples, so its digest moves; a corrupted-but-stable grant
does not. Each offending digest is flagged once, so divergence counts
are deterministic.

Tolerance is bit-exact by default; the iterative fairness lanes
(MAX_MIN_FAIR, BALANCED_FAIRNESS, PROPORTIONAL_FAIRNESS) get a few-ulp
relative bound because their oracles re-run an iteration whose
floating-point reassociation is not replayed exactly by the fixpoint
check. Resources in learning mode, empty stores, and lanes without a
scalar oracle (PRIORITY_BANDS) are skipped.

On divergence the auditor invokes its ``on_divergence`` hook (the
server wires this to a flight-recorder error record + auto-dump, the
``doorman_audit_divergence`` counter, and an ``audit.divergence``
trace instant) and keeps a standing nonzero ``divergences`` count that
``evaluate_slos`` turns into a failing audit gate.
"""

from __future__ import annotations

import hashlib
import logging
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, List, Optional

import numpy as np

from doorman_tpu.algorithms.kinds import AlgoKind
from doorman_tpu.algorithms.tick import oracle_row
from doorman_tpu.core.resource import algo_kind_for, static_param

log = logging.getLogger(__name__)

__all__ = ["ShadowAuditor", "ITERATIVE_LANES", "ITERATIVE_REL_BOUND"]

# Lanes audited against a relative bound instead of bit-exactly (see
# module docstring). Everything else must match to the bit.
ITERATIVE_LANES = frozenset(
    {
        AlgoKind.MAX_MIN_FAIR,
        AlgoKind.BALANCED_FAIRNESS,
        AlgoKind.PROPORTIONAL_FAIRNESS,
    }
)
# "A few ulps" at f64: the iterative oracles reassociate sums across
# rounds; anything beyond this is a real divergence, not rounding.
ITERATIVE_REL_BOUND = 4 * np.finfo(np.float64).eps

# Lanes with no scalar oracle: skipped (learning-mode resources are
# skipped separately — their grants echo wants by design).
_SKIP_LANES = frozenset({AlgoKind.PRIORITY_BANDS})


class ShadowAuditor:
    """Sampled fixpoint audit of a server's stores against the host
    oracles. ``sample`` is K (audit every K ticks); ``inline`` runs the
    comparison synchronously on the caller's thread — the chaos runner
    uses it so verdicts are byte-stable, the live server leaves it off
    so the compare rides the executor."""

    def __init__(
        self,
        *,
        sample: int = 8,
        inline: bool = False,
        on_divergence: Optional[Callable[[dict], None]] = None,
        max_details: int = 32,
        clock=time.time,
    ):
        if sample <= 0:
            raise ValueError("sample interval must be positive")
        self.sample = int(sample)
        self.inline = bool(inline)
        self.on_divergence = on_divergence
        self._clock = clock
        self._lock = threading.Lock()
        self._executor: Optional[ThreadPoolExecutor] = (
            None
            if inline
            else ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="shadow-audit"
            )
        )
        self._last_solve_mode: Optional[str] = None
        # rid -> digest of the inputs that mismatched at the previous
        # sample (strike one); guarded-by: _lock
        self._pending: Dict[str, str] = {}
        # digests already flagged — each offending state counts once,
        # so divergence totals are deterministic; guarded-by: _lock
        self._flagged: set = set()
        self.samples = 0
        self.compared_resources = 0
        self.divergences = 0
        self.details: List[dict] = []  # bounded; guarded-by: _lock
        self._max_details = int(max_details)

    # -- sampling (hot path: snapshot only) -----------------------------

    def should_sample(self, tick: int, solve_mode: Optional[str]) -> bool:
        transition = (
            self._last_solve_mode is not None
            and solve_mode != self._last_solve_mode
        )
        self._last_solve_mode = solve_mode
        return transition or (tick % self.sample == 0)

    def snapshot(self, resources: Dict[str, object], tick: int
                 ) -> List[dict]:
        """Host-side copies of every auditable resource's staged solve
        inputs. O(rows) numpy copies off the store dump — no device
        work, safe inside the tick lock."""
        out: List[dict] = []
        for rid, res in sorted(resources.items()):
            if res.in_learning_mode:
                continue
            try:
                kind = algo_kind_for(res.template)
            except Exception:
                continue
            if kind in _SKIP_LANES:
                continue
            rows = res.store.dump_rows()
            if not rows:
                continue
            out.append(
                {
                    "rid": rid,
                    "tick": tick,
                    "kind": int(kind),
                    "capacity": float(res.capacity),
                    "static": float(static_param(res.template)),
                    "clients": [r[0] for r in rows],
                    "has": np.array([r[3] for r in rows], np.float64),
                    "wants": np.array([r[4] for r in rows], np.float64),
                    "sub": np.array([r[5] for r in rows], np.float64),
                }
            )
        return out

    def maybe_sample(
        self,
        tick: int,
        solve_mode: Optional[str],
        resources: Dict[str, object],
    ) -> bool:
        """The server's per-tick hook: cheap predicate, snapshot when
        due, compare off-thread (or inline). Returns whether a sample
        was taken."""
        if not self.should_sample(tick, solve_mode):
            return False
        snap = self.snapshot(resources, tick)
        self.samples += 1
        if self.inline or self._executor is None:
            self._compare(snap)
        else:
            self._executor.submit(self._compare_safe, snap)
        return True

    # -- comparison (off the hot path) ----------------------------------

    def _compare_safe(self, snap: List[dict]) -> None:
        try:
            self._compare(snap)
        except Exception:
            log.exception("shadow audit comparison failed")

    @staticmethod
    def _digest(entry: dict) -> str:
        h = hashlib.sha256()
        h.update(
            f"{entry['rid']}|{entry['kind']}|{entry['capacity']!r}|"
            f"{entry['static']!r}".encode()
        )
        h.update(entry["has"].tobytes())
        h.update(entry["wants"].tobytes())
        h.update(entry["sub"].tobytes())
        return h.hexdigest()[:16]

    def _compare(self, snap: List[dict]) -> None:
        with self._lock:
            self.compared_resources += len(snap)
        for entry in snap:
            expect = oracle_row(
                entry["kind"],
                entry["capacity"],
                entry["static"],
                entry["wants"],
                entry["has"],
                entry["sub"],
            )
            has = entry["has"]
            if entry["kind"] in ITERATIVE_LANES:
                scale = np.maximum(np.abs(has), np.abs(expect))
                bad = np.abs(expect - has) > ITERATIVE_REL_BOUND * np.maximum(
                    scale, 1.0
                )
            else:
                bad = expect != has
            rid = entry["rid"]
            if not bool(np.any(bad)):
                with self._lock:
                    self._pending.pop(rid, None)
                continue
            digest = self._digest(entry)
            detail = None
            with self._lock:
                prev = self._pending.get(rid)
                self._pending[rid] = digest
                if prev != digest or digest in self._flagged:
                    # Strike one (inputs moved since the last sample:
                    # convergence/delivery lag, not corruption) — or a
                    # state already flagged once.
                    continue
                self._flagged.add(digest)
                self.divergences += 1
                idx = [int(i) for i in np.nonzero(bad)[0][:8]]
                detail = {
                    "rid": rid,
                    "tick": entry["tick"],
                    "kind": int(entry["kind"]),
                    "digest": digest,
                    "rows": idx,
                    "clients": [entry["clients"][i] for i in idx],
                    "has": [float(has[i]) for i in idx],
                    "expected": [float(expect[i]) for i in idx],
                    "at": self._clock(),
                }
                if len(self.details) < self._max_details:
                    self.details.append(detail)
            log.error(
                "shadow-oracle divergence on %s (lane %d): store %s vs "
                "oracle %s",
                rid, entry["kind"], detail["has"], detail["expected"],
            )
            if self.on_divergence is not None:
                try:
                    self.on_divergence(detail)
                except Exception:
                    log.exception("audit on_divergence hook failed")

    # -- lifecycle / status ---------------------------------------------

    def drain(self) -> None:
        """Block until queued comparisons have run (tests, chaos)."""
        if self._executor is not None:
            self._executor.submit(lambda: None).result()

    def close(self) -> None:
        if self._executor is not None:
            self._executor.shutdown(wait=True)
            self._executor = None
            self.inline = True

    def status(self) -> dict:
        with self._lock:
            return {
                "sample": self.sample,
                "inline": self.inline,
                "samples": self.samples,
                "compared_resources": self.compared_resources,
                "divergences": self.divergences,
                "pending": len(self._pending),
                "details": [dict(d) for d in self.details],
            }
