"""A small metrics registry with Prometheus text exposition.

Zero-dependency equivalent of the prometheus instrumentation the reference
wires through its server and client:

- request-duration histograms and error counters per RPC method
  (reference go/server/doorman/server.go:92-121 and
  go/client/doorman/client.go:87-99);
- a custom collector exporting per-resource has/wants/count gauges,
  gathered at scrape time from live server state
  (reference go/server/doorman/server.go:501-517,558-573).

Metric values are collected under a mutex so the asyncio event loop and the
debug HTTP thread can both touch the registry.
"""

from __future__ import annotations

import math
import threading
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "Registry",
    "default_registry",
    "instrument_server",
    "instrument_client",
]

LabelValues = Tuple[str, ...]


def call_on_loop(loop, fn, timeout: float = 5.0):
    """Run fn on an asyncio loop from another thread (atomic w.r.t. the
    loop's coroutines) when the loop is running; else call directly."""
    if loop is not None and loop.is_running():
        import asyncio

        async def grab():
            return fn()

        return asyncio.run_coroutine_threadsafe(grab(), loop).result(timeout)
    return fn()

DEFAULT_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


def _format_labels(names: Sequence[str], values: LabelValues) -> str:
    if not names:
        return ""
    inner = ",".join(
        f'{n}="{_escape(v)}"' for n, v in zip(names, values)
    )
    return "{" + inner + "}"


def _escape(value: str) -> str:
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _format_value(v: float) -> str:
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


class Metric:
    """Base class: a named family of (labels -> value) series."""

    kind = "untyped"

    def __init__(self, name: str, help_text: str = "",
                 labels: Sequence[str] = ()):
        self.name = name
        self.help = help_text
        self.label_names = tuple(labels)
        self._lock = threading.Lock()

    def expose(self) -> Iterable[str]:  # pragma: no cover - abstract
        raise NotImplementedError


class Counter(Metric):
    kind = "counter"

    def __init__(self, name: str, help_text: str = "",
                 labels: Sequence[str] = ()):
        super().__init__(name, help_text, labels)
        self._values: Dict[LabelValues, float] = {}

    def inc(self, *label_values: str, by: float = 1.0) -> None:
        key = tuple(str(v) for v in label_values)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + by

    def value(self, *label_values: str) -> float:
        return self._values.get(tuple(str(v) for v in label_values), 0.0)

    def expose(self) -> Iterable[str]:
        with self._lock:
            items = sorted(self._values.items())
        for labels, v in items:
            yield (
                f"{self.name}"
                f"{_format_labels(self.label_names, labels)}"
                f" {_format_value(v)}"
            )


class Gauge(Metric):
    kind = "gauge"

    def __init__(self, name: str, help_text: str = "",
                 labels: Sequence[str] = ()):
        super().__init__(name, help_text, labels)
        self._values: Dict[LabelValues, float] = {}

    def set(self, value: float, *label_values: str) -> None:
        key = tuple(str(v) for v in label_values)
        with self._lock:
            self._values[key] = float(value)

    def value(self, *label_values: str) -> float:
        return self._values.get(tuple(str(v) for v in label_values), 0.0)

    def expose(self) -> Iterable[str]:
        with self._lock:
            items = sorted(self._values.items())
        for labels, v in items:
            yield (
                f"{self.name}"
                f"{_format_labels(self.label_names, labels)}"
                f" {_format_value(v)}"
            )


class Histogram(Metric):
    kind = "histogram"

    def __init__(self, name: str, help_text: str = "",
                 labels: Sequence[str] = (),
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(name, help_text, labels)
        self.buckets = tuple(sorted(buckets))
        self._counts: Dict[LabelValues, List[int]] = {}
        self._sums: Dict[LabelValues, float] = {}
        self._totals: Dict[LabelValues, int] = {}

    def observe(self, value: float, *label_values: str) -> None:
        key = tuple(str(v) for v in label_values)
        with self._lock:
            counts = self._counts.setdefault(key, [0] * len(self.buckets))
            for i, bound in enumerate(self.buckets):
                if value <= bound:
                    counts[i] += 1
            self._sums[key] = self._sums.get(key, 0.0) + value
            self._totals[key] = self._totals.get(key, 0) + 1

    def count(self, *label_values: str) -> int:
        return self._totals.get(tuple(str(v) for v in label_values), 0)

    def sum(self, *label_values: str) -> float:
        return self._sums.get(tuple(str(v) for v in label_values), 0.0)

    def expose(self) -> Iterable[str]:
        with self._lock:
            keys = sorted(self._totals)
            counts = {k: list(self._counts[k]) for k in keys}
            sums = dict(self._sums)
            totals = dict(self._totals)
        bucket_names = self.label_names + ("le",)
        for key in keys:
            for i, bound in enumerate(self.buckets):
                labels = key + (_format_value(bound),)
                yield (
                    f"{self.name}_bucket"
                    f"{_format_labels(bucket_names, labels)}"
                    f" {counts[key][i]}"
                )
            yield (
                f"{self.name}_bucket"
                f"{_format_labels(bucket_names, key + ('+Inf',))}"
                f" {totals[key]}"
            )
            yield (
                f"{self.name}_sum{_format_labels(self.label_names, key)}"
                f" {_format_value(sums[key])}"
            )
            yield (
                f"{self.name}_count{_format_labels(self.label_names, key)}"
                f" {totals[key]}"
            )


class Registry:
    """Holds metric families plus scrape-time collector callbacks."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: Dict[str, Metric] = {}
        self._collectors: List[Callable[[], Iterable[Metric]]] = []

    def register(self, metric: Metric) -> Metric:
        with self._lock:
            existing = self._metrics.get(metric.name)
            if existing is not None:
                return existing
            self._metrics[metric.name] = metric
            return metric

    def counter(self, name: str, help_text: str = "",
                labels: Sequence[str] = ()) -> Counter:
        return self.register(Counter(name, help_text, labels))  # type: ignore

    def gauge(self, name: str, help_text: str = "",
              labels: Sequence[str] = ()) -> Gauge:
        return self.register(Gauge(name, help_text, labels))  # type: ignore

    def histogram(self, name: str, help_text: str = "",
                  labels: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        return self.register(  # type: ignore
            Histogram(name, help_text, labels, buckets)
        )

    def metrics(self) -> List[Metric]:
        """The registered metric families (live objects). Lets one
        registry re-export another's families at scrape time:
        `reg.add_collector(other.metrics)` — cmd/server chains the
        process-global default registry (tick-phase histograms, chaos
        counters) into its per-serve registry this way."""
        with self._lock:
            return list(self._metrics.values())

    def add_collector(
        self, collector: Callable[[], Iterable[Metric]]
    ) -> Callable[[], None]:
        """Register a callback producing metrics at scrape time (the
        equivalent of a custom prometheus.Collector,
        reference server.go:501-517). Returns an unregister callable."""
        with self._lock:
            self._collectors.append(collector)

        def unregister() -> None:
            with self._lock:
                try:
                    self._collectors.remove(collector)
                except ValueError:
                    pass

        return unregister

    def expose(self) -> str:
        """Render the whole registry in Prometheus text format."""
        with self._lock:
            metrics = list(self._metrics.values())
            collectors = list(self._collectors)
        for collector in collectors:
            try:
                metrics.extend(collector())
            except Exception:  # a broken collector must not kill /metrics
                continue
        lines: List[str] = []
        for m in sorted(metrics, key=lambda m: m.name):
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            lines.extend(m.expose())
        return "\n".join(lines) + "\n"


_default_registry = Registry()


def default_registry() -> Registry:
    return _default_registry


def instrument_server(server, registry: Optional[Registry] = None) -> Registry:
    """Wire a CapacityServer's request hook and a per-resource collector
    into a registry (reference server.go:92-121,501-517,737-745)."""
    registry = registry or default_registry()
    durations = registry.histogram(
        "doorman_server_requests_durations",
        "Duration of different requests in seconds.",
        labels=("method",),
    )
    errors = registry.counter(
        "doorman_server_requests_error_count",
        "Number of requests that returned an error.",
        labels=("method",),
    )
    requests = registry.counter(
        "doorman_server_requests_count",
        "Number of requests received.",
        labels=("method",),
    )

    def on_request(method: str, duration: float, error: bool) -> None:
        requests.inc(method)
        durations.observe(duration, method)
        if error:
            errors.inc(method)

    server.on_request = on_request

    def collect() -> Iterable[Metric]:
        # Snapshot live server state on its asyncio loop when one is
        # running (atomic w.r.t. RPC handlers), mirroring the debug pages.
        try:
            return call_on_loop(
                getattr(server, "_loop", None),
                lambda: _collect_now(server),
            )
        except Exception:
            return []

    registry.add_collector(collect)
    return registry


def _collect_now(server) -> List[Metric]:
    is_master = Gauge(
        "doorman_server_is_master",
        "1 if this server is currently the master.",
    )
    is_master.set(1.0 if server.is_master else 0.0)
    has = Gauge(
        "doorman_server_resource_has",
        "Capacity currently leased out per resource.",
        labels=("resource",),
    )
    wants = Gauge(
        "doorman_server_resource_wants",
        "Capacity currently wanted per resource.",
        labels=("resource",),
    )
    count = Gauge(
        "doorman_server_resource_clients",
        "Number of clients holding a lease per resource.",
        labels=("resource",),
    )
    subclients = Gauge(
        "doorman_server_resource_subclients",
        "Number of subclients per resource.",
        labels=("resource",),
    )
    for rid, res in list(server.resources.items()):
        store = res.store
        has.set(store.sum_has, rid)
        wants.set(store.sum_wants, rid)
        count.set(len(store), rid)
        subclients.set(store.count, rid)
    return [is_master, has, wants, count, subclients]


def instrument_client(client, registry: Optional[Registry] = None) -> Registry:
    """Wire a doorman client's request hook into a registry
    (reference client.go:87-99,493-500)."""
    registry = registry or default_registry()
    durations = registry.histogram(
        "doorman_client_requests_durations",
        "Duration of client capacity requests in seconds.",
        labels=("method",),
    )
    errors = registry.counter(
        "doorman_client_requests_error_count",
        "Number of client requests that returned an error.",
        labels=("method",),
    )

    def on_request(method: str, duration: float, error: bool) -> None:
        durations.observe(duration, method)
        if error:
            errors.inc(method)

    client.on_request = on_request
    return registry
