"""Per-tick flight recorder: the black box that explains a failure.

A bounded ring of structured per-tick records — phase laps, admission
level and per-band shed tallies, per-shard transfer bytes, persist
journal sequence, mastership epoch, a store digest — cheap enough to
run always-on next to the serving path. When something goes wrong (a
chaos invariant violation, an unhandled server-tick exception) the ring
is dumped: JSON that replays the last N ticks record by record, plus a
Chrome-trace overlay so the same window drops straight into Perfetto
next to the span tracer's timeline. `/debug/flightrec` serves the same
view on demand.

Two producers share this ring type:

  * CapacityServer records one entry per tick_once (wall-clock phase
    laps included) and auto-dumps on a tick exception;
  * ChaosRunner records one entry per VIRTUAL tick — deterministic
    fields only (virtual time, masters, admission tallies, digests), so
    a violation dump is byte-stable across two runs of the same seeded
    plan and lands in the verdict as the replay artifact.

Dumps write to ``dump_dir`` when set, else to ``$DOORMAN_FLIGHTREC_DIR``
when that is set (CI points it at a scratch dir and uploads whatever
landed there as artifacts on test failure), else nowhere — the dump
dict is returned either way.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import re
import threading
import time
from collections import deque
from typing import Dict, Iterable, List, Optional

log = logging.getLogger(__name__)

__all__ = ["ENV_DUMP_DIR", "FlightRecorder", "store_digest"]

ENV_DUMP_DIR = "DOORMAN_FLIGHTREC_DIR"

_REASON_SAFE = re.compile(r"[^A-Za-z0-9._-]+")


def store_digest(resources: Dict[str, object]) -> str:
    """A 16-hex-char digest of the lease-store aggregates (capacity,
    sum_has, sum_wants, lease count per resource). O(#resources): the
    stores maintain running sums, so this never walks leases. Two
    states that diverge in aggregate grant mass diverge here — the
    cheap "did the stores move?" pin a dump reader diffs first."""
    items = [
        (
            rid,
            round(float(res.capacity), 6),
            round(float(res.store.sum_has), 6),
            round(float(res.store.sum_wants), 6),
            len(res.store),
        )
        for rid, res in sorted(resources.items())
    ]
    payload = json.dumps(items, separators=(",", ":")).encode()
    return hashlib.sha256(payload).hexdigest()[:16]


class FlightRecorder:
    """Bounded ring of per-tick dicts with monotone sequence numbers.

    Thread-safe: the server records from its event loop / executor
    while the debug HTTP thread reads. Records are plain dicts; the
    producer decides the schema (see module docstring), the recorder
    only stamps ``seq``.
    """

    def __init__(
        self,
        capacity: int = 512,
        *,
        component: str = "server",
        clock=time.time,
        dump_dir: Optional[str] = None,
    ):
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        self.capacity = int(capacity)
        self.component = component
        self._clock = clock
        self._ring: deque = deque(maxlen=self.capacity)  # guarded-by: self._lock
        self._seq = 0  # guarded-by: self._lock
        self._lock = threading.Lock()
        self.dump_dir = (
            dump_dir
            if dump_dir is not None
            else (os.environ.get(ENV_DUMP_DIR) or None)
        )
        # Summary of the most recent dump (status pages); never the
        # records themselves.
        self.last_dump: Optional[dict] = None

    # -- recording ------------------------------------------------------

    def record(self, **fields) -> int:
        """Append one record; returns its sequence number."""
        with self._lock:
            self._seq += 1
            fields["seq"] = self._seq
            self._ring.append(fields)
            return self._seq

    @property
    def head_seq(self) -> int:
        # Lock-free racy read, deliberately: a monotone int for status
        # pages; CPython int loads are atomic and staleness is harmless.
        return self._seq  # doorman: allow[lock-discipline]

    @property
    def occupancy(self) -> int:
        # Same benign racy read as head_seq (deque len is atomic).
        return len(self._ring)  # doorman: allow[lock-discipline]

    def snapshot(self) -> List[dict]:
        with self._lock:
            return [dict(r) for r in self._ring]

    def status(self) -> dict:
        return {
            "head_seq": self.head_seq,
            "occupancy": self.occupancy,
            "capacity": self.capacity,
            "last_dump": self.last_dump,
        }

    # -- dumping --------------------------------------------------------

    def view(self, reason: str = "on_demand", extra: Optional[dict] = None
             ) -> dict:
        """The dump structure without side effects (no files, no
        last_dump update) — what /debug/flightrec serves."""
        records = self.snapshot()
        out = {
            "component": self.component,
            "reason": reason,
            "at": self._clock(),
            "head_seq": self.head_seq,
            "capacity": self.capacity,
            "records": records,
        }
        if extra:
            out["extra"] = extra
        return out

    def dump(self, reason: str, extra: Optional[dict] = None) -> dict:
        """Dump the ring: returns the JSON-able dict, notes it as the
        last dump, and — when a dump directory is configured — writes
        the JSON plus its Chrome-trace overlay there. File trouble
        never raises: the black box must not take down the plane."""
        out = self.view(reason, extra)
        self.last_dump = {
            "reason": reason,
            "at": out["at"],
            "head_seq": out["head_seq"],
            "records": len(out["records"]),
        }
        if self.dump_dir:
            try:
                os.makedirs(self.dump_dir, exist_ok=True)
                safe = _REASON_SAFE.sub(
                    "_", f"{self.component}-{reason}-{out['head_seq']}"
                )
                base = os.path.join(self.dump_dir, f"flightrec-{safe}")
                with open(base + ".json", "w") as f:
                    json.dump(out, f, indent=1, sort_keys=True)
                    f.write("\n")
                with open(base + ".trace.json", "w") as f:
                    f.write(self.chrome_overlay(out["records"]))
                self.last_dump["path"] = base + ".json"
            except Exception:
                log.exception(
                    "flight-recorder dump to %s failed", self.dump_dir
                )
        return out

    # -- Chrome-trace overlay ------------------------------------------

    def chrome_overlay(
        self, records: Optional[Iterable[dict]] = None, pid: int = 1
    ) -> str:
        """Render records as Chrome trace-event JSON: one complete
        event per recorded tick (phase laps laid out sequentially
        inside it), counter tracks for admission level / persist seq /
        shed totals, and instants for errors and violations. Time axis
        is the records' own ``t`` (wall for the server, virtual for
        chaos), relative to the first record."""
        recs = list(records) if records is not None else self.snapshot()
        events: List[dict] = [
            {
                "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
                "args": {"name": f"flightrec:{self.component}"},
            }
        ]
        if not recs:
            return json.dumps(
                {"traceEvents": events, "displayTimeUnit": "ms"}
            )
        t0 = float(recs[0].get("t", 0.0))
        for rec in recs:
            ts = (float(rec.get("t", t0)) - t0) * 1e6
            args = {
                k: rec[k]
                for k in ("seq", "tick", "digest", "epoch", "is_master",
                          "masters", "resources", "persist_seq")
                if k in rec
            }
            wall_ms = rec.get("wall_ms")
            if isinstance(wall_ms, (int, float)) and wall_ms > 0:
                events.append({
                    "name": "tick", "cat": "flightrec", "ph": "X",
                    "pid": pid, "tid": 0,
                    "ts": ts, "dur": wall_ms * 1000.0, "args": args,
                })
                offset = ts
                for phase, ms in (rec.get("phases") or {}).items():
                    if not isinstance(ms, (int, float)) or ms <= 0:
                        continue
                    events.append({
                        "name": phase, "cat": "flightrec.phase",
                        "ph": "X", "pid": pid, "tid": 0,
                        "ts": offset, "dur": ms * 1000.0, "args": {},
                    })
                    offset += ms * 1000.0
            else:
                events.append({
                    "name": "tick", "cat": "flightrec", "ph": "i",
                    "pid": pid, "tid": 0, "ts": ts, "s": "t",
                    "args": args,
                })
            # straddle_capacity / straddle_updates / upstream_rpcs are
            # the federation beat (server records stamp them per tick
            # when the server is a shard — doc/federation.md);
            # dispatches / host_syncs are the per-tick dispatch
            # accounting deltas (utils.dispatch via the server's tick
            # records) — the fused-tick triage counters;
            # scoped_rows / scoped_resources are the churn-
            # proportional solve's per-tick scope (the compact table
            # the tick actually solved — a counter stuck at the table
            # size means solve_mode is stuck at full, doc/
            # operations.md).
            # population / offered / forecast_rps are the workload
            # harness's per-tick beat (doorman_tpu/workload): live
            # client count, offered refreshes this tick, and the
            # forecaster's next-tick demand prediction — overlaying
            # forecast_rps on offered shows the predictive-admission
            # lead time directly.
            # audit_divergence / anomalies are the continuous-telemetry
            # beat (obs/audit.py, obs/detect.py): cumulative confirmed
            # shadow-oracle divergences and online anomaly detections —
            # both flatline at zero on a healthy server, so any step in
            # these tracks is the moment to scrub to.
            for counter in ("admission_level", "persist_seq",
                            "straddle_capacity", "straddle_updates",
                            "upstream_rpcs", "dispatches",
                            "host_syncs", "scoped_rows",
                            "scoped_resources", "population",
                            "offered", "forecast_rps",
                            "audit_divergence", "anomalies"):
                v = rec.get(counter)
                if isinstance(v, (int, float)):
                    events.append({
                        "name": counter, "ph": "C", "pid": pid,
                        "ts": ts, "args": {counter: v},
                    })
            shed = rec.get("shed_by_band")
            if isinstance(shed, dict) and shed:
                events.append({
                    "name": "shed_by_band", "ph": "C", "pid": pid,
                    "ts": ts,
                    "args": {str(k): v for k, v in sorted(shed.items())},
                })
            for key in ("error", "violations"):
                v = rec.get(key)
                if v:
                    events.append({
                        "name": key, "cat": "flightrec", "ph": "i",
                        "pid": pid, "tid": 0, "ts": ts, "s": "g",
                        "args": {key: v},
                    })
        return json.dumps({"traceEvents": events, "displayTimeUnit": "ms"})
