"""Durable multi-resolution history for per-tick flight records.

The flight recorder (flightrec.py) is a bounded in-process ring: it
explains the last N ticks and then forgets. This module is its memory.
A :class:`HistoryStore` keeps

  * a **raw ring** of the most recent records (tier 0, one dict per
    tick, same schema the flight recorder stamps), and
  * **decimated tiers** — for each decimation factor F, one bucket per
    F consecutive records carrying min/max/mean/last of every numeric
    field, so hours and days of history stay queryable at bounded
    memory long after the raw ring has wrapped.

When constructed with a directory it is also **durable**: records
append to checksummed segment files using the persist backend's
journal discipline — append-only lines, each prefixed with the first 8
hex chars of its payload's sha256, flushed on every append and fsynced
on rotation.  A torn tail (half-written final line after a crash) is
tolerated on open: replay stops at the first corrupt line and new
appends start a fresh segment, so a torn tail can never be appended
to.  Each process generation stamps its records with a monotone
``run`` number; ``run_delta`` compares a field's quantile across the
current and previous runs, which is what lets ``TrajectoryComparator``
-style deltas and SLO windows span process lifetimes.

Queries: ``records(start, end, tier, fields)`` by history-sequence
range, ``series(field, tier, run)`` as a flat float list (SLO sample
streams), ``view()``/``chrome()`` for /debug/history, ``status()`` for
status pages. All methods are thread-safe: the server appends from its
tick loop while the debug HTTP thread and the cmd.obs CLI read.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
import time
from collections import deque
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

log = logging.getLogger(__name__)

__all__ = ["HistoryStore", "SEGMENT_PREFIX"]

SEGMENT_PREFIX = "history-seg-"

# Fields that are bookkeeping, not signal: excluded from tier
# aggregation (they are reconstructible or meaningless to average).
_SKIP_TIER_FIELDS = frozenset({"seq", "hseq", "run", "tier"})


def _checksum(payload: bytes) -> str:
    return hashlib.sha256(payload).hexdigest()[:8]


def _encode(rec: dict) -> bytes:
    payload = json.dumps(
        rec, separators=(",", ":"), sort_keys=True, default=str
    ).encode()
    return _checksum(payload).encode() + b" " + payload + b"\n"


def _decode(line: bytes) -> Optional[dict]:
    """One journal line back to a record; None on any corruption
    (truncation, bit rot, bad JSON) — the torn-tail contract."""
    if not line.endswith(b"\n"):
        return None
    body = line[:-1]
    if len(body) < 10 or body[8:9] != b" ":
        return None
    digest, payload = body[:8], body[9:]
    if _checksum(payload).encode() != digest:
        return None
    try:
        rec = json.loads(payload)
    except ValueError:
        return None
    return rec if isinstance(rec, dict) else None


class _TierBucket:
    """Aggregation state for one in-progress decimation bucket."""

    __slots__ = ("start", "run", "n", "fields")

    def __init__(self, start: int, run: int):
        self.start = start
        self.run = run
        self.n = 0
        # field -> [min, max, sum, last]
        self.fields: Dict[str, List[float]] = {}

    def add(self, rec: dict) -> None:
        self.n += 1
        for k, v in rec.items():
            if k in _SKIP_TIER_FIELDS:
                continue
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                continue
            f = float(v)
            st = self.fields.get(k)
            if st is None:
                self.fields[k] = [f, f, f, f]
            else:
                if f < st[0]:
                    st[0] = f
                if f > st[1]:
                    st[1] = f
                st[2] += f
                st[3] = f

    def finalize(self) -> dict:
        return {
            "hseq": self.start,
            "run": self.run,
            "n": self.n,
            "fields": {
                k: {
                    "min": st[0],
                    "max": st[1],
                    "mean": st[2] / self.n,
                    "last": st[3],
                }
                for k, st in sorted(self.fields.items())
            },
        }


class HistoryStore:
    """Raw ring + decimated tiers, optionally durable (see module doc).

    ``tiers`` are decimation factors; bucket boundaries are exact:
    bucket ``b`` of factor ``F`` aggregates records with
    ``hseq in [b*F, (b+1)*F)`` and is emitted the moment the first
    record of the next bucket arrives (or at close/flush replay time
    for the partial tail, which stays pending and is NOT emitted —
    boundary exactness is part of the contract tests pin).
    """

    def __init__(
        self,
        dir: Optional[str] = None,
        *,
        ring: int = 4096,
        tiers: Sequence[int] = (10, 100),
        tier_buckets: int = 4096,
        segment_records: int = 1024,
        max_segments: int = 64,
        component: str = "server",
        clock=time.time,
    ):
        if ring <= 0:
            raise ValueError("ring must be positive")
        for f in tiers:
            if f <= 1:
                raise ValueError("tier factors must be > 1")
        self.dir = dir
        self.component = component
        self._clock = clock
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=int(ring))  # guarded-by: self._lock
        self._tiers: Dict[int, deque] = {  # factor -> finalized buckets
            int(f): deque(maxlen=int(tier_buckets)) for f in tiers
        }
        self._pending: Dict[int, Optional[_TierBucket]] = {
            int(f): None for f in tiers
        }
        self._seq = 0  # last stamped hseq, guarded-by: self._lock
        self.run = 1
        self._segment_records = max(1, int(segment_records))
        self._max_segments = max(1, int(max_segments))
        self._fh = None  # current segment file handle
        self._fh_records = 0
        self._seg_index = 0
        if dir is not None:
            self._open_durable(dir)

    # -- durability -----------------------------------------------------

    def _segment_paths(self) -> List[str]:
        try:
            names = sorted(
                n
                for n in os.listdir(self.dir)
                if n.startswith(SEGMENT_PREFIX) and n.endswith(".log")
            )
        except FileNotFoundError:
            return []
        return [os.path.join(self.dir, n) for n in names]

    def _open_durable(self, dir: str) -> None:
        os.makedirs(dir, exist_ok=True)
        max_run = 0
        for path in self._segment_paths():
            name = os.path.basename(path)
            try:
                idx = int(name[len(SEGMENT_PREFIX):-4])
            except ValueError:
                continue
            self._seg_index = max(self._seg_index, idx)
            try:
                with open(path, "rb") as f:
                    lines = f.readlines()
            except OSError:
                log.exception("history segment %s unreadable", path)
                continue
            for line in lines:
                rec = _decode(line)
                if rec is None:
                    # Torn tail: everything after the first corrupt
                    # line in a segment is untrusted — stop replaying
                    # this segment (appends go to a fresh one).
                    break
                self._ingest(rec)
                self._seq = max(self._seq, int(rec.get("hseq", 0)))
                max_run = max(max_run, int(rec.get("run", 0)))
        self.run = max_run + 1
        # Appends always start a new segment: a torn tail is never
        # appended to, and each process generation's records are
        # physically contiguous.
        self._seg_index += 1

    def _rotate_locked(self) -> None:
        if self._fh is not None:
            try:
                self._fh.flush()
                os.fsync(self._fh.fileno())
                self._fh.close()
            except OSError:
                log.exception("history segment close failed")
            self._fh = None
        self._seg_index += 1
        self._fh_records = 0
        # Retention: drop oldest segments beyond the cap.
        paths = self._segment_paths()
        for stale in paths[: max(0, len(paths) - self._max_segments)]:
            try:
                os.remove(stale)
            except OSError:
                log.exception("history segment retention failed")

    def _append_durable_locked(self, rec: dict) -> None:
        if self._fh is None:
            path = os.path.join(
                self.dir, f"{SEGMENT_PREFIX}{self._seg_index:08d}.log"
            )
            self._fh = open(path, "ab")
        self._fh.write(_encode(rec))
        self._fh.flush()
        self._fh_records += 1
        if self._fh_records >= self._segment_records:
            self._rotate_locked()

    # -- ingest ---------------------------------------------------------

    def _ingest(self, rec: dict) -> None:  # holds-lock: self._lock
        """Ring + tier bookkeeping for one stamped record (no I/O)."""
        self._ring.append(rec)
        hseq = int(rec.get("hseq", 0))
        for factor, finalized in self._tiers.items():
            bucket_start = (hseq // factor) * factor
            pending = self._pending[factor]
            if pending is not None and pending.start != bucket_start:
                finalized.append(pending.finalize())
                pending = None
            if pending is None:
                pending = _TierBucket(
                    bucket_start, int(rec.get("run", self.run))
                )
                self._pending[factor] = pending
            pending.add(rec)

    def append(self, rec: dict) -> int:
        """Stamp ``hseq``/``run`` onto a copy of ``rec`` and store it;
        returns the history sequence number. Never raises on disk
        trouble — history must not take down the tick loop."""
        with self._lock:
            self._seq += 1
            stamped = dict(rec)
            stamped["hseq"] = self._seq
            stamped["run"] = self.run
            self._ingest(stamped)
            if self.dir is not None:
                try:
                    self._append_durable_locked(stamped)
                except OSError:
                    log.exception("history append to %s failed", self.dir)
            return self._seq

    def flush(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.flush()
                    os.fsync(self._fh.fileno())
                except OSError:
                    log.exception("history flush failed")

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.flush()
                    os.fsync(self._fh.fileno())
                    self._fh.close()
                except OSError:
                    log.exception("history close failed")
                self._fh = None

    # -- queries --------------------------------------------------------

    @property
    def head_hseq(self) -> int:
        # Benign racy read (monotone int) for status pages.
        return self._seq  # doorman: allow[lock-discipline]

    def records(
        self,
        start: Optional[int] = None,
        end: Optional[int] = None,
        tier: int = 0,
        fields: Optional[Sequence[str]] = None,
    ) -> List[dict]:
        """Records (tier 0: raw ring) or finalized buckets (tier = a
        decimation factor) with ``start <= hseq <= end``, optionally
        projected to ``fields`` (+ hseq/run always)."""
        with self._lock:
            if tier == 0:
                rows = [dict(r) for r in self._ring]
            else:
                if tier not in self._tiers:
                    raise KeyError(f"no history tier with factor {tier}")
                rows = [dict(b) for b in self._tiers[tier]]
        if start is not None:
            rows = [r for r in rows if r["hseq"] >= start]
        if end is not None:
            rows = [r for r in rows if r["hseq"] <= end]
        if fields is not None:
            keep = set(fields) | {"hseq", "run", "n"}
            if tier == 0:
                rows = [
                    {k: v for k, v in r.items() if k in keep} for r in rows
                ]
            else:
                rows = [
                    {
                        **{k: v for k, v in r.items() if k != "fields"},
                        "fields": {
                            k: v
                            for k, v in r["fields"].items()
                            if k in keep
                        },
                    }
                    for r in rows
                ]
        return rows

    def series(
        self,
        field: str,
        tier: int = 0,
        run: Optional[int] = None,
        agg: str = "mean",
    ) -> List[float]:
        """One field as a flat float list (skipping records where it is
        absent or non-numeric). Tier 0 reads the raw value; decimated
        tiers read the ``agg`` aggregate (min|max|mean|last)."""
        out: List[float] = []
        for r in self.records(tier=tier):
            if run is not None and r.get("run") != run:
                continue
            if tier == 0:
                v = r.get(field)
            else:
                v = (r.get("fields") or {}).get(field, {}).get(agg)
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                continue
            out.append(float(v))
        return out

    def runs(self) -> List[int]:
        with self._lock:
            seen = {int(r.get("run", 0)) for r in self._ring}
        return sorted(seen)

    def run_delta(
        self, field: str, q: float = 0.5, tier: int = 0
    ) -> Optional[dict]:
        """Restart-spanning trajectory delta: the ``q`` quantile of
        ``field`` in the newest run vs the newest prior run that also
        carries it. None until two runs have data (i.e. until history
        has actually survived a restart)."""
        from doorman_tpu.obs.slo import sample_quantile

        runs = self.runs()
        cur = None
        for r in reversed(runs):
            vals = self.series(field, tier=tier, run=r)
            v = sample_quantile(vals, q)
            if v is None:
                continue
            if cur is None:
                cur = (r, v, len(vals))
            else:
                delta = cur[1] - v
                return {
                    "field": field,
                    "q": q,
                    "run": cur[0],
                    "previous_run": r,
                    "current": cur[1],
                    "previous": v,
                    "delta": delta,
                    "ratio": (cur[1] / v) if v else None,
                    "samples": cur[2],
                    "previous_samples": len(vals),
                }
        return None

    # -- export ---------------------------------------------------------

    def view(
        self,
        start: Optional[int] = None,
        end: Optional[int] = None,
        tier: int = 0,
        fields: Optional[Sequence[str]] = None,
    ) -> dict:
        """The /debug/history JSON body (no side effects)."""
        return {
            "component": self.component,
            "at": self._clock(),
            "run": self.run,
            "head_hseq": self.head_hseq,
            "tier": tier,
            "tiers": sorted(self._tiers),
            "records": self.records(start, end, tier, fields),
        }

    def chrome(self) -> str:
        """Raw-ring records as a Chrome-trace overlay — same renderer
        the flight recorder uses, so history drops into Perfetto next
        to a live trace."""
        from doorman_tpu.obs.flightrec import FlightRecorder

        fr = FlightRecorder(
            capacity=1,
            component=f"history:{self.component}",
            clock=self._clock,
        )
        return fr.chrome_overlay(self.records())

    def status(self) -> dict:
        with self._lock:
            tier_occupancy = {
                str(f): len(buckets) for f, buckets in self._tiers.items()
            }
            ring_len = len(self._ring)
            ring_cap = self._ring.maxlen
        return {
            "component": self.component,
            "dir": self.dir,
            "run": self.run,
            "head_hseq": self.head_hseq,
            "ring": ring_len,
            "ring_capacity": ring_cap,
            "tiers": tier_occupancy,
            "segments": len(self._segment_paths()) if self.dir else 0,
        }
