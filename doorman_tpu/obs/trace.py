"""Zero-dependency span tracer with Chrome trace-event export.

One process-global tracer records SPANS (named, timed, nested) from every
layer of a tick — the client refresh loop, the server RPC handlers, the
tick pipeline, and the solver phases — into a fixed-size ring buffer, and
exports them in the Chrome trace-event JSON format that Perfetto
(https://ui.perfetto.dev) and chrome://tracing load directly.

Design constraints, in order:

  * disabled means FREE: the tracer ships enabled on nobody. `span()` on
    a disabled tracer returns one shared no-op context manager — no
    allocation, no clock read — so instrumentation can stay inline in
    hot paths (RPC handlers, per-tick solver phases).
  * enabled means CHEAP: one perf_counter read on enter, one on exit,
    one deque append (the ring drops oldest on overflow). Budget is
    single-digit microseconds per span; tests/test_trace.py pins it
    loosely.
  * context propagates where the work goes: a contextvars.ContextVar
    carries the current (trace_id, span_id) through asyncio awaits, and
    `grpc_metadata()` / `parent_from_grpc_context()` carry it across the
    GetCapacity / GetServerCapacity gRPC hop (metadata key
    `doorman-trace`), so a client's refresh span is the parent of the
    server's handler span even across processes. Executor-thread work
    inherits it via contextvars.copy_context (the server's tick loop
    does this), so solver phase spans nest under the tick span.
  * one time axis: all timestamps are microseconds of time.perf_counter
    relative to one process epoch — monotonic and comparable across
    threads, which wall clocks are not. (Cross-process traces align by
    span parentage, not by ts.)

Unclosed spans are tracked: `open_spans()` returns whatever entered but
never exited, and the tier-1 tests assert every instrumented path leaves
it empty — a leaked span means a code path skipped its __exit__.
"""

from __future__ import annotations

import contextlib
import contextvars
import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Dict, Iterable, List, NamedTuple, Optional, Tuple

__all__ = [
    "KNOWN_INSTANT_NAMES",
    "KNOWN_SPAN_NAMES",
    "TRACE_METADATA_KEY",
    "Span",
    "SpanContext",
    "Tracer",
    "default_tracer",
    "grpc_metadata",
    "jax_capture",
    "now_us",
    "parent_from_grpc_context",
    "parent_from_metadata",
    "perf_to_us",
]

# gRPC metadata key carrying "trace_id.span_id" (lowercase hex) on the
# client -> server and intermediate -> parent hops. Keys must be
# lowercase ASCII for gRPC.
TRACE_METADATA_KEY = "doorman-trace"

# The span/instant vocabularies. Trace consumers join on these names —
# Perfetto overlays, /debug/traces summaries, test assertions, and the
# route tables in doc/observability.md — so an unregistered name records
# into a stream nobody reads. doormanlint (trace-phase-hygiene) checks
# every `.span(...)`/`.instant(...)` literal against these sets; a
# `prefix.*` entry admits computed suffixes (f"server.{method}").
# Phase-lap names live next to the stage skeleton instead
# (solver/engine.py PHASES).
KNOWN_SPAN_NAMES = frozenset({
    "server.tick",
    "server.parent_refresh",
    "server.*",  # per-RPC handler spans: server.GetCapacity, ...
    "client.refresh",
    "client.GetCapacity",
    "client.WatchCapacity",  # stream establishment + read loop
    "admission.window",
    "persist.snapshot",
    "persist.restore",
    "stream.fanout",  # tick-edge lease push (server/streams.py)
    "stream.shard",  # one shard's slice of the fanout (StreamShard)
    # Federated capacity tree (doorman_tpu/federation): the straddle
    # reconciliation beat and the intermediate's device aggregation
    # tick; federation.* admits computed suffixes.
    "federation.reconcile",
    "federation.aggregate",
    "federation.*",
    # Workload harness (doorman_tpu/workload): one span per scenario
    # run, wrapping the whole stepped drive.
    "workload.scenario",
    # Serving-plane listener workers (doorman_tpu/frontend/worker.py):
    # one pump lap (ring drain + deadline wheel) and one held
    # WatchCapacity stream's serve loop.
    "frontend.pump",
    "frontend.stream",
    # Fleet runtime (doorman_tpu/fleet): one reconcile beat — the
    # controller's pull sweep, or one shard report folding into the
    # head's BeatCore on the wire deployment.
    "fleet.beat",
})
KNOWN_INSTANT_NAMES = frozenset({
    "election.transition",
    "shard.*",  # per-direction mesh transfer instants: shard.upload, ...
    "federation.*",  # e.g. federation.partition from the chaos seam
    # Workload event-log entries mirrored onto the trace timeline:
    # workload.crowd_start, workload.deploy, workload.elastic_preempt,
    # ... (harness.note stamps workload.<kind>).
    "workload.*",
    # Continuous telemetry (obs/audit.py, obs/detect.py): a confirmed
    # shadow-oracle divergence and an online anomaly detection, both
    # stamped by the server's tick loop off the hot path.
    "audit.divergence",
    "detect.anomaly",
    # A frontend worker declaring a held stream stalled (its ring
    # frame overdue past the stall margin) before resetting it.
    "frontend.stall",
    # Fleet runtime: a published routing epoch (live reshard) and one
    # shard-side beat report installing its returned shares.
    "fleet.epoch",
    "fleet.report",
})

# The process time axis: perf_counter at import. Chrome trace `ts` must
# be monotonic; wall clocks step and skew.
_EPOCH = time.perf_counter()


def now_us() -> float:
    """Microseconds on the tracer's monotonic axis."""
    return (time.perf_counter() - _EPOCH) * 1e6


def perf_to_us(perf_counter_value: float) -> float:
    """Map a raw time.perf_counter() reading onto the tracer's axis."""
    return (perf_counter_value - _EPOCH) * 1e6


class SpanContext(NamedTuple):
    trace_id: int
    span_id: int


# The current span, propagated through awaits within a task and into
# copied contexts (contextvars.copy_context for executor threads).
_current: contextvars.ContextVar = contextvars.ContextVar(
    "doorman_trace_span", default=None
)


def current_context() -> Optional[SpanContext]:
    return _current.get()


class Span:
    """One recorded event. ph 'X' = complete span (ts+dur), 'i' = instant."""

    __slots__ = (
        "name", "cat", "ph", "trace_id", "span_id", "parent_id",
        "ts", "dur", "tid", "args",
    )

    def __init__(self, name, cat, ph, trace_id, span_id, parent_id,
                 ts, dur, tid, args):
        self.name = name
        self.cat = cat
        self.ph = ph
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.ts = ts
        self.dur = dur
        self.tid = tid
        self.args = args

    def as_chrome(self, pid: int) -> dict:
        args = dict(self.args) if self.args else {}
        args["trace_id"] = f"{self.trace_id:x}"
        args["span_id"] = f"{self.span_id:x}"
        if self.parent_id:
            args["parent_span_id"] = f"{self.parent_id:x}"
        ev = {
            "name": self.name,
            "cat": self.cat or "default",
            "ph": self.ph,
            "ts": round(self.ts, 3),
            "pid": pid,
            "tid": self.tid,
            "args": args,
        }
        if self.ph == "X":
            ev["dur"] = round(self.dur or 0.0, 3)
        else:
            ev["s"] = "p"  # instant scope: process
        return ev


class _NoopSpan:
    """Shared no-op context manager for the disabled tracer."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NOOP = _NoopSpan()
# Public alias: instrumentation that builds span args lazily can return
# this directly on the disabled path instead of paying for the args.
NOOP_SPAN = _NOOP


class _ActiveSpan:
    """Context manager for one live span (enabled tracer only)."""

    __slots__ = ("_tracer", "_name", "_cat", "_args", "_parent",
                 "_rec", "_token")

    def __init__(self, tracer, name, cat, args, parent):
        self._tracer = tracer
        self._name = name
        self._cat = cat
        self._args = args
        self._parent = parent
        self._rec = None
        self._token = None

    def __enter__(self):
        tr = self._tracer
        parent = self._parent if self._parent is not None else _current.get()
        span_id = next(tr._ids)
        if parent is not None:
            trace_id, parent_id = parent.trace_id, parent.span_id
        else:
            trace_id, parent_id = span_id, 0
        rec = Span(
            self._name, self._cat, "X", trace_id, span_id, parent_id,
            now_us(), None, tr._tid(), self._args,
        )
        self._rec = rec
        with tr._open_lock:
            tr._open[span_id] = rec
        self._token = _current.set(SpanContext(trace_id, span_id))
        return rec

    def __exit__(self, exc_type, exc, tb):
        rec = self._rec
        rec.dur = now_us() - rec.ts
        if exc_type is not None:
            args = dict(rec.args) if rec.args else {}
            args["error"] = exc_type.__name__
            rec.args = args
        tr = self._tracer
        with tr._open_lock:
            tr._open.pop(rec.span_id, None)
        tr._events.append(rec)
        _current.reset(self._token)
        return False


class Tracer:
    """A ring buffer of spans plus the enable switch.

    Thread-safe: the ring is a deque (atomic appends), open-span
    tracking takes a small lock, ids come from itertools.count (atomic
    in CPython). Append paths never block each other for long.
    """

    def __init__(self, capacity: int = 65536):
        self.enabled = False
        self.capacity = capacity
        self._events: deque = deque(maxlen=capacity)
        self._open: Dict[int, Span] = {}
        self._open_lock = threading.Lock()
        self._ids = itertools.count(1)
        self._tids: Dict[int, int] = {}
        self._tnames: Dict[int, str] = {}

    # -- lifecycle -----------------------------------------------------

    def enable(self, capacity: Optional[int] = None) -> "Tracer":
        if capacity is not None and capacity != self.capacity:
            self.capacity = capacity
            self._events = deque(self._events, maxlen=capacity)
        self.enabled = True
        return self

    def disable(self) -> None:
        self.enabled = False

    def clear(self) -> None:
        self._events.clear()
        with self._open_lock:
            self._open.clear()

    # -- recording -----------------------------------------------------

    def span(self, name: str, cat: str = "", args: Optional[dict] = None,
             parent: Optional[SpanContext] = None):
        """Context manager timing a block. No-op (and no allocation)
        while disabled. `parent` overrides the ambient context — pass
        the remote parent extracted from gRPC metadata on the server
        side of a hop."""
        if not self.enabled:
            return _NOOP
        return _ActiveSpan(self, name, cat, args, parent)

    def instant(self, name: str, cat: str = "",
                args: Optional[dict] = None) -> None:
        """A zero-duration marker (election flips, fault injections)."""
        if not self.enabled:
            return
        parent = _current.get()
        span_id = next(self._ids)
        if parent is not None:
            trace_id, parent_id = parent.trace_id, parent.span_id
        else:
            trace_id, parent_id = span_id, 0
        self._events.append(Span(
            name, cat, "i", trace_id, span_id, parent_id,
            now_us(), None, self._tid(), args,
        ))

    def add_complete(self, name: str, ts_us: float, dur_us: float,
                     cat: str = "", args: Optional[dict] = None,
                     parent: Optional[SpanContext] = None) -> None:
        """Record an already-measured interval (solver phase laps time
        themselves with perf_counter and report here afterwards)."""
        if not self.enabled:
            return
        ctx = parent if parent is not None else _current.get()
        span_id = next(self._ids)
        if ctx is not None:
            trace_id, parent_id = ctx.trace_id, ctx.span_id
        else:
            trace_id, parent_id = span_id, 0
        self._events.append(Span(
            name, cat, "X", trace_id, span_id, parent_id,
            ts_us, dur_us, self._tid(), args,
        ))

    # -- inspection / export -------------------------------------------

    def snapshot(self) -> List[Span]:
        return list(self._events)

    def open_spans(self) -> List[Span]:
        """Spans entered but never exited — an instrumented path that
        leaks one has a bug (tier-1 asserts this list stays empty)."""
        with self._open_lock:
            return list(self._open.values())

    def chrome_trace(self, extra_events: Iterable[dict] = ()) -> dict:
        """The whole ring as a Chrome trace-event JSON object (load in
        Perfetto or chrome://tracing). `extra_events` are pre-built
        trace-event dicts merged onto the same timeline."""
        pid = os.getpid()
        meta: List[dict] = [{
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": f"doorman:{pid}"},
        }]
        for ident, tid in list(self._tids.items()):
            meta.append({
                "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
                "args": {"name": self._tnames.get(tid, f"thread-{tid}")},
            })
        events = [rec.as_chrome(pid) for rec in self._events]
        events.extend(extra_events)
        events.sort(key=lambda e: e.get("ts", 0.0))
        return {"traceEvents": meta + events, "displayTimeUnit": "ms"}

    def chrome_json(self, extra_events: Iterable[dict] = ()) -> str:
        return json.dumps(self.chrome_trace(extra_events))

    # -- internals -----------------------------------------------------

    def _tid(self) -> int:
        ident = threading.get_ident()
        tid = self._tids.get(ident)
        if tid is None:
            with self._open_lock:
                tid = self._tids.setdefault(ident, len(self._tids))
                self._tnames.setdefault(
                    tid, threading.current_thread().name
                )
        return tid


_default = Tracer()


def default_tracer() -> Tracer:
    return _default


# ----------------------------------------------------------------------
# gRPC hop propagation
# ----------------------------------------------------------------------


def grpc_metadata() -> Tuple:
    """Metadata tuple carrying the current span context (empty when the
    tracer is disabled or no span is active) — pass as `metadata=` on
    the stub call."""
    if not _default.enabled:
        return ()
    ctx = _current.get()
    if ctx is None:
        return ()
    return ((TRACE_METADATA_KEY, f"{ctx.trace_id:x}.{ctx.span_id:x}"),)


def parent_from_metadata(md) -> Optional[SpanContext]:
    """Parse a SpanContext out of invocation metadata (a sequence of
    (key, value) pairs or objects with .key/.value)."""
    if not md:
        return None
    for item in md:
        key = getattr(item, "key", None)
        if key is None:
            key, value = item[0], item[1]
        else:
            value = item.value
        if key != TRACE_METADATA_KEY:
            continue
        try:
            t, s = str(value).split(".", 1)
            return SpanContext(int(t, 16), int(s, 16))
        except (ValueError, TypeError):
            return None
    return None


def parent_from_grpc_context(context) -> Optional[SpanContext]:
    """Extract the remote parent from a servicer context; tolerates
    context=None (tests drive handlers directly) and non-gRPC contexts."""
    if context is None:
        return None
    getter = getattr(context, "invocation_metadata", None)
    if getter is None:
        return None
    try:
        return parent_from_metadata(getter())
    except Exception:
        return None


# ----------------------------------------------------------------------
# Device-side timeline (opt-in)
# ----------------------------------------------------------------------


@contextlib.contextmanager
def jax_capture(out_dir: Optional[str]):
    """Opt-in jax.profiler.trace capture around a measured solve: wraps
    the block in a device-side profiler trace written to `out_dir`
    (viewable with xprof / tensorboard / Perfetto). A falsy out_dir is a
    no-op; capture trouble (another trace active, no backend) degrades
    to a no-op rather than failing the measured work."""
    if not out_dir:
        yield
        return
    started = False
    try:
        import jax

        jax.profiler.start_trace(out_dir)
        started = True
    except Exception:
        pass
    try:
        yield
    finally:
        if started:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
