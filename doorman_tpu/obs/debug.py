"""Debug HTTP server: /debug/status, /debug/resources, /debug/traces,
/metrics, /healthz — with a /debug index listing every route.

Capability parity with the reference's composable status page
(go/status/status.go:129-192 — named template "parts" contributed by any
subsystem, rendered on one page) and the per-lease resource table
(go/cmd/doorman/resourcez.go:62-172 — all resources, or one resource's
leases with ?resource=<id>).

The page handlers run on a plain threaded HTTP server; state is read from
the owning asyncio loop via run_coroutine_threadsafe when one is attached,
so reads are atomic with respect to the RPC handlers.
"""

from __future__ import annotations

import asyncio
import html
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional
from urllib.parse import parse_qs, urlparse

from doorman_tpu.obs import metrics as metrics_mod
from doorman_tpu.obs import trace as trace_mod

__all__ = ["DebugServer", "add_status_part", "status_parts"]

# Every route the handler serves, with a one-line description — the
# /debug index page renders this, so a new route only needs one entry
# here to be discoverable.
ROUTES = (
    ("/debug/status", "server overview: mastership, resources, config, "
                      "tick phase totals"),
    ("/debug/resources", "per-lease tables (?resource=<id> for one)"),
    ("/debug/requests", "recent RPC samples (?limit=N)"),
    ("/debug/admission", "admission control: AIMD level, per-band "
                         "admit probabilities, shed tallies, coalescing "
                         "windows (?format=json)"),
    ("/debug/frontend", "serving-plane pool: worker liveness, held "
                        "streams, ring publish/pump counters, control "
                        "surface tallies (?format=json)"),
    ("/debug/traces", "span tracer summary; ?format=chrome downloads a "
                      "Perfetto-loadable trace"),
    ("/debug/slo", "SLO verdicts per server (tick budget, RPC p99, "
                   "top-band goodput floor, restore staleness); "
                   "?format=json"),
    ("/debug/flightrec", "per-tick flight recorder: ring summary; "
                         "?format=json dumps the last N tick records, "
                         "?format=chrome the overlay trace"),
    ("/debug/history", "durable flight-record history: run/occupancy "
                       "summary; ?format=json dumps records "
                       "(&tier=F for a decimated tier, &start=/&end= "
                       "by hseq), ?format=chrome the overlay trace"),
    ("/debug/vars", "expvar-style JSON snapshot"),
    ("/metrics", "Prometheus text exposition"),
    ("/healthz", "liveness probe"),
)

_parts_lock = threading.Lock()
_parts: Dict[str, Callable[[], str]] = {}  # guarded-by: _parts_lock
# doorman: allow[seeded-determinism] process uptime is wall-clock by design
_start_time = time.time()


def add_status_part(name: str, fragment: Callable[[], str]) -> None:
    """Contribute a named HTML fragment to /debug/status
    (reference status.go:129-158). The callable runs at page-render time."""
    with _parts_lock:
        _parts[name] = fragment


def status_parts() -> List[str]:
    with _parts_lock:
        items = sorted(_parts.items())
    out = []
    for name, fragment in items:
        try:
            out.append(f"<h2>{html.escape(name)}</h2>\n{fragment()}")
        except Exception as e:  # one broken part must not kill the page
            out.append(
                f"<h2>{html.escape(name)}</h2>\n"
                f"<pre>error rendering part: {html.escape(str(e))}</pre>"
            )
    return out


_PAGE = """<!DOCTYPE html>
<html><head><title>{title}</title>
<style>
body {{ font-family: monospace; margin: 2em; }}
table {{ border-collapse: collapse; }}
th, td {{ border: 1px solid #999; padding: 2px 8px; text-align: left; }}
th {{ background: #eee; }}
.master {{ color: #070; }} .notmaster {{ color: #a00; }}
</style></head>
<body><h1>{title}</h1>
{body}
</body></html>"""


def _fmt_ts(ts: float) -> str:
    if ts <= 0:
        return "-"
    return time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(ts))


class DebugServer:
    """Serves the debug pages for zero or more CapacityServers."""

    def __init__(self, host: str = "", port: int = 0,
                 registry: Optional[metrics_mod.Registry] = None):
        self.registry = registry or metrics_mod.default_registry()
        self._servers: List[tuple] = []  # (capacity_server, loop-or-None)
        handler = self._make_handler()
        self._httpd = ThreadingHTTPServer((host or "", port), handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def add_server(self, server,
                   loop: Optional[asyncio.AbstractEventLoop] = None) -> None:
        """Expose a CapacityServer on the pages (resourcez.go:54). If a
        loop is given, its state is snapshotted on that loop."""
        self._servers.append((server, loop))

    def start(self) -> int:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="debug-http", daemon=True
        )
        self._thread.start()
        return self.port

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    # ------------------------------------------------------------------

    def _call(self, loop, fn):
        """Run fn on the server's asyncio loop (atomic w.r.t. RPC handlers)
        when one is attached and running; else directly."""
        return metrics_mod.call_on_loop(loop, fn)

    def _snapshot(self, server, loop) -> dict:
        return self._call(loop, server.status)

    def _statuses(self) -> List[dict]:
        return [self._snapshot(s, l) for s, l in self._servers]

    def _status_page(self) -> str:
        sections = []
        for st in self._statuses():
            cls = "master" if st["is_master"] else "notmaster"
            rows = "".join(
                f"<tr><td>{html.escape(rid)}</td>"
                f"<td>{r['capacity']:g}</td>"
                f"<td>{html.escape(r['algorithm'])}</td>"
                f"<td>{r['sum_has']:g}</td>"
                f"<td>{r['sum_wants']:g}</td>"
                f"<td>{r['count']}</td>"
                f"<td>{'yes' if r['in_learning_mode'] else 'no'}</td></tr>"
                for rid, r in sorted(st["resources"].items())
            )
            sections.append(
                f"<h2>server {html.escape(st['id'])}</h2>"
                f"<p class={cls!r}>is_master: {st['is_master']}</p>"
                f"<p>current master: "
                f"{html.escape(st['current_master'] or '(unknown)')}<br>"
                f"election: {html.escape(st['election'])}<br>"
                f"mode: {html.escape(st['mode'])} | "
                f"backend: {html.escape(st.get('backend') or '(no tick yet)')} | "
                f"ticks: {st.get('ticks', 0)} "
                f"(idle: {st.get('idle_ticks', 0)}) | "
                f"last tick: {st.get('last_tick_ms', 0):g} ms</p>"
                + self._status_obs_line(st)
                + (
                    "<p>tick phases (total ms): "
                    + html.escape(
                        ", ".join(
                            f"{k}={v:g}"
                            for k, v in st["tick_phase_total_ms"].items()
                        )
                    )
                    + "</p>"
                    if st.get("tick_phase_total_ms")
                    else ""
                )
                + f"<table><tr><th>resource</th><th>capacity</th>"
                f"<th>algorithm</th><th>has</th>"
                f"<th>wants</th><th>subclients</th><th>learning</th></tr>"
                f"{rows}</table>"
                f"<h3>config</h3><pre>{html.escape(st['config'])}</pre>"
            )
        # doorman: allow[seeded-determinism] uptime display, wall-clock by design
        uptime = time.time() - _start_time
        body = (
            f"<p>uptime: {uptime:.0f}s</p>"
            + "".join(sections)
            + "".join(status_parts())
            + "<p><a href='/debug'>index</a> | "
            "<a href='/debug/resources'>resources</a> | "
            "<a href='/debug/requests'>requests</a> | "
            "<a href='/debug/traces'>traces</a> | "
            "<a href='/debug/slo'>slo</a> | "
            "<a href='/debug/flightrec'>flightrec</a> | "
            "<a href='/metrics'>metrics</a> | "
            "<a href='/debug/vars'>vars</a></p>"
        )
        return _PAGE.format(title="/debug/status", body=body)

    @staticmethod
    def _status_obs_line(st: dict) -> str:
        """Flight-recorder head/occupancy and the last SLO verdict on
        the status overview (the satellite surface: one glance says
        whether the black box is rolling and whether the SLOs hold)."""
        parts = []
        fr = st.get("flightrec")
        if fr:
            last = fr.get("last_dump")
            parts.append(
                f"flight recorder: head seq {fr.get('head_seq', 0)}, "
                f"ring {fr.get('occupancy', 0)}/{fr.get('capacity', 0)}"
                + (
                    f", last dump {html.escape(str(last.get('reason')))}"
                    if last
                    else ""
                )
                + " (<a href='/debug/flightrec'>flightrec</a>)"
            )
        slo = st.get("slo")
        if slo:
            failed = [
                v["slo"]
                for v in slo.get("verdicts", [])
                if v.get("status") == "fail"
            ]
            parts.append(
                "last SLO verdict: "
                + (
                    "pass"
                    if slo.get("ok")
                    else "FAIL (" + html.escape(", ".join(failed)) + ")"
                )
                + " (<a href='/debug/slo'>slo</a>)"
            )
        dispatch = st.get("dispatch")
        if dispatch:
            # The fused-tick launch-tax counters (cumulative; per-tick
            # deltas ride the flight recorder as dispatches/host_syncs).
            parts.append(
                f"fused tick: {'on' if st.get('fused_tick') else 'OFF'}"
                f" | dispatches: {dispatch.get('dispatches', 0)}"
                f", host syncs: {dispatch.get('host_syncs', 0)}"
            )
        scope = st.get("solve_scope") or {}
        live = {k: v for k, v in scope.items() if v}
        if live:
            # The churn-proportional solve at a glance: per path, the
            # last tick's mode (with the forced-full reason when the
            # scope escalated) and the compact scope it covered.
            def _fmt(name, s):
                mode = s.get("last_mode", "?")
                if mode == "full" and s.get("last_full_reason"):
                    mode = f"full:{s['last_full_reason']}"
                return (
                    f"{name} {mode}"
                    f" {s.get('last_scope_rows', 0)}r"
                    f"/{s.get('last_scope_resources', 0)}res"
                    f" frontier={s.get('frontier', 0)}"
                )
            parts.append(
                "solve scope: "
                + ("on" if st.get("scoped_solve") else "OFF")
                + " | "
                + ", ".join(_fmt(k, v) for k, v in sorted(live.items()))
            )
        return f"<p>{' | '.join(parts)}</p>" if parts else ""

    def _index_page(self) -> str:
        rows = "".join(
            f"<tr><td><a href={path!r}>{html.escape(path)}</a></td>"
            f"<td>{html.escape(desc)}</td></tr>"
            for path, desc in ROUTES
        )
        return _PAGE.format(
            title="/debug",
            body=f"<table><tr><th>route</th><th>what</th></tr>{rows}"
                 f"</table>",
        )

    def _traces_page(self) -> str:
        """Span tracer summary: per-(category, name) counts and totals,
        leaked (unclosed) spans, and the Chrome-export download link."""
        tracer = trace_mod.default_tracer()
        events = tracer.snapshot()
        by_key: Dict[tuple, List[float]] = {}
        for ev in events:
            by_key.setdefault((ev.cat, ev.name), []).append(ev.dur or 0.0)
        rows = "".join(
            f"<tr><td>{html.escape(cat or 'default')}</td>"
            f"<td>{html.escape(name)}</td>"
            f"<td>{len(durs)}</td>"
            f"<td>{sum(durs) / 1000.0:.3f}</td>"
            f"<td>{max(durs) / 1000.0:.3f}</td></tr>"
            for (cat, name), durs in sorted(by_key.items())
        )
        open_spans = tracer.open_spans()
        leaked = (
            "<p>open spans: "
            + html.escape(
                ", ".join(s.name for s in open_spans) or "(none)"
            )
            + "</p>"
        )
        state = "enabled" if tracer.enabled else "disabled"
        body = (
            f"<p>tracer {state}; {len(events)} spans buffered "
            f"(ring capacity {tracer.capacity})</p>"
            + leaked
            + "<table><tr><th>category</th><th>span</th><th>count</th>"
            "<th>total ms</th><th>max ms</th></tr>"
            + rows
            + "</table>"
            "<p><a href='/debug/traces?format=chrome'>download Chrome "
            "trace</a> — open at https://ui.perfetto.dev or "
            "chrome://tracing</p>"
        )
        return _PAGE.format(title="/debug/traces", body=body)

    def _requests_page(self, limit: int) -> str:
        """Recent-RPC samples per server (the reference exposes gRPC's
        /debug/requests sampling on its debug port,
        doc/loadtest/README.md:322-324)."""
        sections = []
        for server, _loop in self._servers:
            log_ = getattr(server, "request_log", None)
            if log_ is None:
                continue
            rows = "".join(
                f"<tr><td>{_fmt_ts(s.when)}</td>"
                f"<td>{html.escape(s.method)}</td>"
                f"<td>{html.escape(s.caller)}</td>"
                f"<td>{html.escape(', '.join(s.resources))}</td>"
                f"<td>{s.wants:g}</td>"
                f"<td>{s.duration * 1000:.2f}</td>"
                f"<td>{'ERROR' if s.error else 'ok'}</td></tr>"
                for s in log_.snapshot(limit)
            )
            sections.append(
                f"<h2>{html.escape(server.id)}</h2>"
                f"<table><tr><th>when</th><th>method</th><th>caller</th>"
                f"<th>resources</th><th>wants</th><th>ms</th>"
                f"<th>outcome</th></tr>{rows}</table>"
            )
        if not sections:
            sections.append("<p>no request samples</p>")
        return _PAGE.format(
            title="/debug/requests", body="".join(sections)
        )

    def _admission_statuses(self) -> Dict[str, Optional[dict]]:
        """server id -> admission status dict (None when the server has
        no admission front-end), snapshotted on each owning loop."""
        out: Dict[str, Optional[dict]] = {}
        for server, loop in self._servers:
            adm = getattr(server, "_admission", None)
            out[server.id] = (
                self._call(loop, adm.status) if adm is not None else None
            )
        return out

    def _admission_page(self) -> str:
        sections = []
        for sid, st in self._admission_statuses().items():
            if st is None:
                sections.append(
                    f"<h2>server {html.escape(sid)}</h2>"
                    "<p>admission control disabled</p>"
                )
                continue
            ctl = st.get("controller") or {}
            bands = ctl.get("bands", {})
            band_rows = "".join(
                f"<tr><td>{html.escape(b)}</td><td>{p:g}</td></tr>"
                for b, p in sorted(
                    bands.items(), key=lambda kv: -int(kv[0])
                )
            )
            tally_rows = "".join(
                f"<tr><td>{html.escape(key)}</td>"
                f"<td>{v['admitted']}</td><td>{v['shed']}</td>"
                f"<td>{v['fast_fail']}</td></tr>"
                for key, v in sorted(st.get("tallies", {}).items())
            )
            co = st.get("coalescer") or {}
            sections.append(
                f"<h2>server {html.escape(sid)}</h2>"
                f"<p>level: {ctl.get('level', 1.0):g} | "
                f"pressure: {ctl.get('pressure', 0.0):g} | "
                f"offered rps: "
                f"{ctl.get('offered_rps_last_window', 0.0):g} | "
                f"windows: {ctl.get('windows', 0)} "
                f"(overloaded: {ctl.get('overloaded_windows', 0)})</p>"
                f"<p>latency ewma: {ctl.get('latency_ewma_s', 0.0):g}s | "
                f"queue ewma: {ctl.get('queue_ewma', 0.0):g} | "
                f"tick lag ewma: {ctl.get('tick_lag_ewma', 0.0):g}</p>"
                f"<p>coalescing: window {co.get('window_s', 0.0):g}s, "
                f"{co.get('flushes', 0)} flushes, "
                f"{co.get('coalesced_requests', 0)} coalesced requests, "
                f"max occupancy {co.get('max_occupancy', 0)}</p>"
                "<table><tr><th>band</th><th>admit probability</th></tr>"
                f"{band_rows}</table>"
                "<table><tr><th>method/band</th><th>admitted</th>"
                f"<th>shed</th><th>fast-fail</th></tr>{tally_rows}</table>"
            )
        if not sections:
            sections.append("<p>no servers</p>")
        return _PAGE.format(
            title="/debug/admission", body="".join(sections)
        )

    def _frontend_statuses(self) -> Dict[str, Optional[dict]]:
        """server id -> serving-plane pool status (None when no
        frontend pool is attached), snapshotted on each owning loop
        (the inline pool's status reads live ring control words)."""
        out: Dict[str, Optional[dict]] = {}
        for server, loop in self._servers:
            pool = getattr(server, "_frontend", None)
            out[server.id] = (
                self._call(loop, pool.status)
                if pool is not None else None
            )
        return out

    def _frontend_page(self) -> str:
        sections = []
        for sid, st in self._frontend_statuses().items():
            if st is None:
                sections.append(
                    f"<h2>server {html.escape(sid)}</h2>"
                    "<p>no frontend pool attached</p>"
                )
                continue
            pub = st.get("publisher") or {}
            live = st.get("live", [])
            parts = [
                f"mode: {html.escape(str(st.get('mode', '?')))}",
                f"workers live: {len(live)}/{st.get('workers', 0)}",
                f"published: {pub.get('published_frames', 0)} frames"
                f" / {pub.get('published_bytes', 0)} bytes"
                f" ({pub.get('terminals', 0)} terminals)",
            ]
            if "held" in st:
                parts.append(f"held: {st['held']}")
            if "crashes" in st:
                parts.append(
                    f"crashes: {st['crashes']} "
                    f"(restores: {st.get('restores', 0)})"
                )
            if st.get("public_addr"):
                parts.append(
                    "public: " + html.escape(str(st["public_addr"]))
                )
            body = [f"<h2>server {html.escape(sid)}</h2>"
                    f"<p>{' | '.join(parts)}</p>"]
            per_worker = st.get("per_worker") or []
            if per_worker:
                # Inline pool: the in-process worker cores expose the
                # full pump/stall counters.
                rows = "".join(
                    f"<tr><td>{w.get('worker')}</td>"
                    f"<td>{w.get('held', 0)}</td>"
                    f"<td>{w.get('frames', 0)}</td>"
                    f"<td>{w.get('pushes', 0)}</td>"
                    f"<td>{w.get('terminals', 0)}</td>"
                    f"<td>{w.get('stalls', 0)}</td>"
                    f"<td>{w.get('desyncs', 0)}</td>"
                    f"<td>{w.get('parked', 0)}</td></tr>"
                    for w in per_worker
                )
                body.append(
                    "<table><tr><th>worker</th><th>held</th>"
                    "<th>frames</th><th>pushes</th><th>terminals</th>"
                    "<th>stalls</th><th>desyncs</th><th>parked</th>"
                    f"</tr>{rows}</table>"
                )
            control = st.get("control") or {}
            if control:
                # Process pool: the control surface's view (heartbeats
                # are the workers' own reports).
                held_rows = "".join(
                    f"<tr><td>{html.escape(w)}</td><td>{n}</td></tr>"
                    for w, n in sorted(
                        (control.get("worker_held") or {}).items()
                    )
                )
                body.append(
                    f"<p>establishments: "
                    f"{control.get('establishments', 0)} | drops: "
                    f"{control.get('drops', 0)} | heartbeats: "
                    f"{control.get('heartbeats', 0)}</p>"
                    "<table><tr><th>worker</th><th>held (last "
                    f"heartbeat)</th></tr>{held_rows}</table>"
                )
            sections.append("".join(body))
        if not sections:
            sections.append("<p>no servers</p>")
        return _PAGE.format(
            title="/debug/frontend", body="".join(sections)
        )

    def _slo_statuses(self) -> Dict[str, Optional[dict]]:
        """server id -> last_slo dict (a fresh evaluation per request;
        None when the server has no SLO support), each snapshotted on
        its owning loop."""
        out: Dict[str, Optional[dict]] = {}
        for server, loop in self._servers:
            if not hasattr(server, "evaluate_slos"):
                out[getattr(server, "id", "?")] = None
                continue

            def evaluate(server=server):
                server.evaluate_slos(registry=self.registry)
                return server.last_slo

            out[server.id] = self._call(loop, evaluate)
        return out

    def _slo_page(self) -> str:
        sections = []
        for sid, st in self._slo_statuses().items():
            if st is None:
                sections.append(
                    f"<h2>server {html.escape(sid)}</h2>"
                    "<p>no SLO support</p>"
                )
                continue
            rows = ""
            for v in st.get("verdicts", []):
                observed = v.get("observed")
                obs_txt = "-" if observed is None else f"{observed:g}"
                rows += (
                    f"<tr><td>{html.escape(v['slo'])}</td>"
                    f"<td>{html.escape(v['status'])}</td>"
                    f"<td>{obs_txt}</td>"
                    f"<td>{v['kind']} {v['target']:g}</td>"
                    f"<td>{html.escape(v.get('unit', ''))}</td>"
                    f"<td>{html.escape(v.get('description', ''))}</td>"
                    "</tr>"
                )
            ok = st.get("ok")
            cls = "master" if ok else "notmaster"
            sections.append(
                f"<h2>server {html.escape(sid)}</h2>"
                f"<p class={cls!r}>overall: "
                f"{'pass' if ok else 'FAIL'}</p>"
                "<table><tr><th>slo</th><th>status</th><th>observed</th>"
                f"<th>target</th><th>unit</th><th>what</th></tr>{rows}"
                "</table>"
            )
        if not sections:
            sections.append("<p>no servers</p>")
        return _PAGE.format(title="/debug/slo", body="".join(sections))

    def _flightrec_views(self) -> Dict[str, Optional[dict]]:
        """server id -> on-demand flight-recorder view (no side
        effects), snapshotted on each owning loop."""
        out: Dict[str, Optional[dict]] = {}
        for server, loop in self._servers:
            fr = getattr(server, "flightrec", None)
            out[getattr(server, "id", "?")] = (
                self._call(loop, fr.view) if fr is not None else None
            )
        return out

    def _flightrec_chrome(self) -> str:
        """Overlay trace of the first server with a recorder."""
        for server, loop in self._servers:
            fr = getattr(server, "flightrec", None)
            if fr is not None:
                records = self._call(loop, fr.snapshot)
                return fr.chrome_overlay(records)
        return json.dumps({"traceEvents": []})

    def _flightrec_page(self) -> str:
        sections = []
        for server, loop in self._servers:
            fr = getattr(server, "flightrec", None)
            sid = getattr(server, "id", "?")
            if fr is None:
                sections.append(
                    f"<h2>server {html.escape(sid)}</h2>"
                    "<p>flight recorder disabled</p>"
                )
                continue
            st = fr.status()
            last = st.get("last_dump")
            last_txt = (
                f"{last['reason']} at head seq {last['head_seq']} "
                f"({last['records']} records)"
                if last
                else "(none)"
            )
            recent = self._call(loop, fr.snapshot)[-5:]
            recent_rows = "".join(
                f"<tr><td>{r.get('seq')}</td><td>{r.get('tick', '-')}</td>"
                f"<td>{r.get('wall_ms', '-')}</td>"
                f"<td>{html.escape(str(r.get('digest', '-')))}</td>"
                f"<td>{html.escape(str(r.get('error', '')))}</td></tr>"
                for r in recent
            )
            sections.append(
                f"<h2>server {html.escape(sid)}</h2>"
                f"<p>head seq: {st['head_seq']} | occupancy: "
                f"{st['occupancy']}/{st['capacity']} | last dump: "
                f"{html.escape(last_txt)}</p>"
                "<table><tr><th>seq</th><th>tick</th><th>wall ms</th>"
                f"<th>digest</th><th>error</th></tr>{recent_rows}</table>"
                "<p><a href='/debug/flightrec?format=json'>dump JSON</a>"
                " | <a href='/debug/flightrec?format=chrome'>overlay "
                "trace</a></p>"
            )
        if not sections:
            sections.append("<p>no servers</p>")
        return _PAGE.format(
            title="/debug/flightrec", body="".join(sections)
        )

    def _history_views(
        self,
        start: Optional[int] = None,
        end: Optional[int] = None,
        tier: int = 0,
    ) -> Dict[str, Optional[dict]]:
        """server id -> history view (records by hseq range/tier).
        The store is thread-safe, so no loop hop is needed."""
        out: Dict[str, Optional[dict]] = {}
        for server, _loop in self._servers:
            hs = getattr(server, "history", None)
            out[getattr(server, "id", "?")] = (
                hs.view(start=start, end=end, tier=tier)
                if hs is not None
                else None
            )
        return out

    def _history_chrome(self) -> str:
        """Overlay trace of the first server with a history store."""
        for server, _loop in self._servers:
            hs = getattr(server, "history", None)
            if hs is not None:
                return hs.chrome()
        return json.dumps({"traceEvents": []})

    def _history_page(self) -> str:
        sections = []
        for server, _loop in self._servers:
            hs = getattr(server, "history", None)
            sid = getattr(server, "id", "?")
            if hs is None:
                sections.append(
                    f"<h2>server {html.escape(sid)}</h2>"
                    "<p>history disabled (--history-dir)</p>"
                )
                continue
            st = hs.status()
            tier_txt = ", ".join(
                f"x{f}: {n} buckets" for f, n in sorted(
                    st["tiers"].items(), key=lambda kv: int(kv[0])
                )
            )
            recent = hs.records()[-5:]
            recent_rows = "".join(
                f"<tr><td>{r.get('hseq')}</td><td>{r.get('run')}</td>"
                f"<td>{r.get('tick', '-')}</td>"
                f"<td>{r.get('wall_ms', '-')}</td>"
                f"<td>{html.escape(str(r.get('solve_mode', '-')))}</td>"
                f"<td>{r.get('audit_divergence', '-')}</td></tr>"
                for r in recent
            )
            sections.append(
                f"<h2>server {html.escape(sid)}</h2>"
                f"<p>run: {st['run']} | head hseq: {st['head_hseq']} | "
                f"ring: {st['ring']}/{st['ring_capacity']} | segments: "
                f"{st['segments']} ({html.escape(str(st['dir']))}) | "
                f"tiers: {html.escape(tier_txt or '(none)')}</p>"
                "<table><tr><th>hseq</th><th>run</th><th>tick</th>"
                "<th>wall ms</th><th>solve mode</th>"
                f"<th>audit div</th></tr>{recent_rows}</table>"
                "<p><a href='/debug/history?format=json'>dump JSON</a>"
                " | <a href='/debug/history?format=chrome'>overlay "
                "trace</a></p>"
            )
        if not sections:
            sections.append("<p>no servers</p>")
        return _PAGE.format(
            title="/debug/history", body="".join(sections)
        )

    def _resources_page(self, only: Optional[str]) -> str:
        sections = []
        for (server, loop), st in zip(self._servers, self._statuses()):
            for rid in sorted(st["resources"]):
                if only is not None and rid != only:
                    continue
                lease_st = self._call(
                    loop, lambda: server.resource_lease_status(rid)
                )
                if lease_st is None:
                    continue
                rows = "".join(
                    f"<tr><td>{html.escape(cs.client_id)}</td>"
                    f"<td>{cs.lease.has:g}</td>"
                    f"<td>{cs.lease.wants:g}</td>"
                    f"<td>{cs.lease.subclients}</td>"
                    f"<td>{_fmt_ts(cs.lease.expiry)}</td></tr>"
                    for cs in lease_st.leases
                )
                sections.append(
                    f"<h2>{html.escape(rid)} @ {html.escape(st['id'])}</h2>"
                    f"<p>sum_has: {lease_st.sum_has:g} / "
                    f"sum_wants: {lease_st.sum_wants:g}</p>"
                    f"<table><tr><th>client</th><th>has</th><th>wants</th>"
                    f"<th>subclients</th><th>expires</th></tr>"
                    f"{rows}</table>"
                )
        if not sections:
            sections.append("<p>no resources</p>")
        return _PAGE.format(
            title="/debug/resources", body="".join(sections)
        )

    def _vars(self) -> str:
        """expvar-style JSON snapshot (the reference blank-imports expvar,
        doorman_server.go:43-45)."""
        return json.dumps(
            {
                # doorman: allow[seeded-determinism] wall-clock uptime
                "uptime_seconds": time.time() - _start_time,
                "servers": self._statuses(),
            },
            indent=2,
            default=str,
        )

    def _make_handler(self):
        debug = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # quiet
                pass

            def do_GET(self):
                url = urlparse(self.path)
                try:
                    if url.path == "/metrics":
                        body, ctype = (
                            debug.registry.expose(),
                            "text/plain; version=0.0.4",
                        )
                    elif url.path in ("/", "/debug/status"):
                        body, ctype = debug._status_page(), "text/html"
                    elif url.path in ("/debug", "/debug/"):
                        body, ctype = debug._index_page(), "text/html"
                    elif url.path == "/debug/traces":
                        q = parse_qs(url.query)
                        if q.get("format", [""])[0] == "chrome":
                            body, ctype = (
                                trace_mod.default_tracer().chrome_json(),
                                "application/json",
                            )
                        else:
                            body, ctype = debug._traces_page(), "text/html"
                    elif url.path == "/debug/resources":
                        q = parse_qs(url.query)
                        only = q.get("resource", [None])[0]
                        body, ctype = (
                            debug._resources_page(only),
                            "text/html",
                        )
                    elif url.path == "/debug/admission":
                        q = parse_qs(url.query)
                        if q.get("format", [""])[0] == "json":
                            body, ctype = (
                                json.dumps(
                                    debug._admission_statuses(),
                                    indent=2, default=str,
                                ),
                                "application/json",
                            )
                        else:
                            body, ctype = (
                                debug._admission_page(),
                                "text/html",
                            )
                    elif url.path == "/debug/frontend":
                        q = parse_qs(url.query)
                        if q.get("format", [""])[0] == "json":
                            body, ctype = (
                                json.dumps(
                                    debug._frontend_statuses(),
                                    indent=2, default=str,
                                ),
                                "application/json",
                            )
                        else:
                            body, ctype = (
                                debug._frontend_page(),
                                "text/html",
                            )
                    elif url.path == "/debug/slo":
                        q = parse_qs(url.query)
                        if q.get("format", [""])[0] == "json":
                            body, ctype = (
                                json.dumps(
                                    debug._slo_statuses(),
                                    indent=2, default=str,
                                ),
                                "application/json",
                            )
                        else:
                            body, ctype = debug._slo_page(), "text/html"
                    elif url.path == "/debug/flightrec":
                        q = parse_qs(url.query)
                        fmt = q.get("format", [""])[0]
                        if fmt == "json":
                            body, ctype = (
                                json.dumps(
                                    debug._flightrec_views(),
                                    indent=1, default=str,
                                ),
                                "application/json",
                            )
                        elif fmt == "chrome":
                            body, ctype = (
                                debug._flightrec_chrome(),
                                "application/json",
                            )
                        else:
                            body, ctype = (
                                debug._flightrec_page(),
                                "text/html",
                            )
                    elif url.path == "/debug/history":
                        q = parse_qs(url.query)
                        fmt = q.get("format", [""])[0]
                        if fmt == "json":

                            def _int(key):
                                try:
                                    return int(q[key][0])
                                except (KeyError, ValueError):
                                    return None

                            body, ctype = (
                                json.dumps(
                                    debug._history_views(
                                        start=_int("start"),
                                        end=_int("end"),
                                        tier=_int("tier") or 0,
                                    ),
                                    indent=1, default=str,
                                ),
                                "application/json",
                            )
                        elif fmt == "chrome":
                            body, ctype = (
                                debug._history_chrome(),
                                "application/json",
                            )
                        else:
                            body, ctype = (
                                debug._history_page(),
                                "text/html",
                            )
                    elif url.path == "/debug/requests":
                        q = parse_qs(url.query)
                        try:
                            limit = max(0, int(q.get("limit", ["100"])[0]))
                        except ValueError:
                            limit = 100
                        body, ctype = (
                            debug._requests_page(limit),
                            "text/html",
                        )
                    elif url.path == "/debug/vars":
                        body, ctype = debug._vars(), "application/json"
                    elif url.path == "/healthz":
                        body, ctype = "ok\n", "text/plain"
                    else:
                        self.send_error(404)
                        return
                except Exception as e:
                    self.send_error(500, str(e))
                    return
                data = body.encode()
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        return Handler
