"""In-memory request sampling: the /debug/requests page.

The reference gets request sampling for free from gRPC's /debug/requests
on the debug port (reference doc/loadtest/README.md:322-324); here a
small ring buffer per server records the most recent RPCs — method,
caller, resources touched, total wants, duration, outcome — and the
debug server renders them. Cheap enough to be always on (a deque append
per RPC), like the reference's sampling."""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Deque, List, Sequence


@dataclass(frozen=True)
class RequestSample:
    when: float  # wall-clock seconds
    method: str
    caller: str
    resources: Sequence[str]
    wants: float
    duration: float  # seconds
    error: bool


@dataclass
class RequestLog:
    """Fixed-size ring of recent requests; thread-safe.

    `clock` is the injectable time seam (the server hands in its own,
    so chaos-driven servers stamp samples in virtual time)."""

    capacity: int = 256
    clock: Callable[[], float] = time.time
    _entries: Deque[RequestSample] = field(init=False)
    _lock: threading.Lock = field(init=False)

    def __post_init__(self) -> None:
        self._entries = deque(maxlen=self.capacity)  # guarded-by: self._lock
        self._lock = threading.Lock()

    def record(
        self,
        method: str,
        caller: str,
        resources: Sequence[str],
        wants: float,
        duration: float,
        error: bool,
        when: float | None = None,
    ) -> None:
        sample = RequestSample(
            when=self.clock() if when is None else when,
            method=method,
            caller=caller,
            resources=tuple(resources),
            wants=wants,
            duration=duration,
            error=error,
        )
        with self._lock:
            self._entries.append(sample)

    def snapshot(self, limit: int = 0) -> List[RequestSample]:
        """Most recent first."""
        with self._lock:
            entries = list(self._entries)
        entries.reverse()
        return entries[:limit] if limit else entries
