"""AIMD overload controller: per-priority-band admit probabilities.

The front door's counterpart of the tick's "solve in aggregate" lesson:
instead of letting a client storm grind the event loop, the controller
watches cheap aggregate signals and sheds whole bands of traffic before
the queues melt. Signals (each scaled by its configured target, the max
of the ratios is the *pressure*):

  * offered arrival rate vs ``max_rps`` (when configured) — the only
    signal that reacts *within* the window it spikes in: arrivals past
    the window's budget are shed on the spot (the hard cap), so a storm
    cannot land a full window of damage before the EWMAs notice;
  * admission decision latency EWMA vs ``target_latency_s``;
  * coalescing queue depth EWMA vs ``target_queue``;
  * tick lag EWMA (tick duration / tick interval) vs ``target_tick_lag``
    — a device solve falling behind its cadence is overload even when
    the RPC path still looks healthy.

At each window boundary the admit *level* moves AIMD-style: pressure
above 1.0 multiplies it by ``md_factor``; a healthy window adds
``ai_step`` back (clamped to [``min_level``, 1]). The level maps onto
per-band admit probabilities so the LOWEST bands shed first: with B
bands sorted ascending and band rank j (0 = lowest priority),

    p_j = clamp((level - (B - 1 - j) / B) * B, 0, 1)

i.e. the level sweeps band segments from the bottom of the priority
order — at level 1 everything is admitted, each 1/B of level lost
extinguishes one more band from the bottom. The top band is NEVER shed
while lower bands exist (the goodput floor the chaos `client_storm`
plan pins); a single-band population has no lower band to sacrifice and
degrades to uniform level-probability shedding instead.

Determinism: the clock and RNG are injectable. The chaos harness passes
its virtual clock and the plan's seeded RNG, so a storm replay makes
byte-identical shed decisions.
"""

from __future__ import annotations

import bisect
import random
import time
from typing import Callable, Dict, List, Optional, Tuple

__all__ = ["AimdController"]


class _Ewma:
    """Exponentially-weighted moving average that decays toward zero on
    idle control windows (a stale latency spike must not keep shedding
    an hour later)."""

    def __init__(self, alpha: float):
        self.alpha = alpha
        self.value = 0.0

    def observe(self, x: float) -> None:
        self.value += self.alpha * (x - self.value)

    def decay(self) -> None:
        self.value *= 1.0 - self.alpha


class AimdController:
    def __init__(
        self,
        *,
        window: float = 1.0,
        clock: Callable[[], float] = time.time,
        rng: Optional[random.Random] = None,
        max_rps: Optional[float] = None,
        target_latency_s: float = 0.1,
        target_queue: float = 256.0,
        target_tick_lag: float = 3.0,
        md_factor: float = 0.6,
        ai_step: float = 0.1,
        min_level: float = 0.05,
        ewma_alpha: float = 0.3,
        max_retry_after: float = 60.0,
    ):
        if window <= 0:
            raise ValueError(f"control window must be > 0, got {window}")
        self.window = float(window)
        self._clock = clock
        self.rng = rng if rng is not None else random.Random()
        self.max_rps = float(max_rps) if max_rps else None
        self.target_latency_s = target_latency_s
        self.target_queue = target_queue
        self.target_tick_lag = target_tick_lag
        self.md_factor = md_factor
        self.ai_step = ai_step
        self.min_level = min_level
        self.max_retry_after = max_retry_after

        self.level = 1.0
        # Predictive leg (workload forecaster seam): an externally
        # supplied demand forecast in offered RPS for the NEXT window.
        # None (the default) leaves the controller purely reactive.
        self.forecast_rps: Optional[float] = None
        self._bands: List[int] = []  # sorted ascending wire priority
        self._arrivals = 0           # this window, shed included
        self._last_rate = 0.0        # previous closed window, per second
        self._window_end: Optional[float] = None
        self._lat = _Ewma(ewma_alpha)
        self._queue = _Ewma(ewma_alpha)
        self._tick_lag = _Ewma(ewma_alpha)
        self.windows = 0
        self.overloaded_windows = 0

    # -- signal feeds ----------------------------------------------------

    def observe_rpc(self, seconds: float) -> None:
        self._lat.observe(seconds)

    def observe_queue(self, depth: float) -> None:
        self._queue.observe(depth)

    def observe_tick_lag(self, ratio: float) -> None:
        self._tick_lag.observe(ratio)

    def set_forecast(self, rps: Optional[float]) -> None:
        """Feed a demand forecast (offered RPS expected in the next
        window) from a predictive model. The forecast joins the
        pressure max scaled by ``max_rps`` like the measured rate, so
        a predicted storm multiplies the level DOWN at the boundary
        *entering* the spike instead of the one after it. Requires
        ``max_rps`` to be configured (there is no budget to scale
        against otherwise); pass None to drop back to reactive-only."""
        self.forecast_rps = None if rps is None else float(rps)

    # -- the control loop ------------------------------------------------

    def pressure(self) -> float:
        """Max of the signal ratios; > 1.0 means overloaded."""
        p = 0.0
        if self.max_rps is not None:
            p = self._last_rate / self.max_rps
            if self.forecast_rps is not None:
                p = max(p, self.forecast_rps / self.max_rps)
        p = max(p, self._lat.value / self.target_latency_s)
        p = max(p, self._queue.value / self.target_queue)
        p = max(p, self._tick_lag.value / self.target_tick_lag)
        return p

    def _roll(self, now: float) -> None:
        if self._window_end is None:
            self._window_end = now + self.window
            return
        while now >= self._window_end:
            self._last_rate = self._arrivals / self.window
            self._arrivals = 0
            if self.pressure() > 1.0:
                self.level = max(self.min_level, self.level * self.md_factor)
                self.overloaded_windows += 1
            else:
                self.level = min(1.0, self.level + self.ai_step)
            # Idle windows decay the EWMAs so stale pressure cannot
            # pin the level down after the load is gone.
            self._lat.decay()
            self._queue.decay()
            self._tick_lag.decay()
            self.windows += 1
            self._window_end += self.window

    # -- admit decisions -------------------------------------------------

    def _note_band(self, priority: int) -> None:
        i = bisect.bisect_left(self._bands, priority)
        if i == len(self._bands) or self._bands[i] != priority:
            self._bands.insert(i, priority)

    def band_probability(self, priority: int) -> float:
        """Admit probability for this band at the current level (the
        segment mapping in the module docstring)."""
        if not self._bands:
            return 1.0
        bands = self._bands
        b = len(bands)
        # Rank from the bottom; an unseen priority slots at its sorted
        # insertion point (so it sheds like its nearest-lower neighbor).
        j = bisect.bisect_right(bands, priority) - 1
        j = max(j, 0)
        lo = (b - 1 - j) / b
        return min(max((self.level - lo) * b, 0.0), 1.0)

    def admit(self, priority: int) -> Tuple[bool, Optional[float]]:
        """One admit decision for a sheddable request in this band.
        Returns (admitted, retry_after_seconds_or_None)."""
        now = self._clock()
        self._roll(now)
        self._arrivals += 1
        self._note_band(priority)
        top = self._bands[-1]
        multi_band = len(self._bands) > 1
        if multi_band and priority >= top:
            # The goodput floor: the top band is never shed while lower
            # bands exist to shed first.
            return True, None
        if (
            self.max_rps is not None
            and self._arrivals > self.max_rps * self.window
        ):
            # Hard per-window cap: reacts inside the spiking window,
            # before the AIMD level has had a boundary to move at.
            return False, self.retry_after(priority)
        p = self.band_probability(priority)
        if p >= 1.0:
            return True, None
        if p <= 0.0 or self.rng.random() >= p:
            return False, self.retry_after(priority)
        return True, None

    def admit_many(self, priorities):
        """Vectorized twin of calling `admit` once per entry of
        ``priorities`` (in input order) at a single clock reading;
        returns the boolean admitted mask as a numpy array.

        Exactness contract (the vector population engine's parity pin
        rides on it): the window rolls once — within one batch only the
        first sequential call could have moved it; every arrival counts
        toward the hard cap at its 1-based global index; band discovery
        happens at each unseen priority's first occurrence, so the
        batch is split there and the band set / top band / probability
        mapping is static within each segment; and `self.rng.random()`
        is drawn in input order for exactly the positions whose band
        probability is fractional — the same draws, in the same order,
        the sequential loop would have made.
        """
        import numpy as np  # deferred: keep the module import-light

        prio = np.asarray(priorities, dtype=np.int64)
        n = int(prio.size)
        out = np.zeros(n, dtype=bool)
        if n == 0:
            return out
        self._roll(self._clock())
        a0 = self._arrivals
        self._arrivals += n

        uniq, first = np.unique(prio, return_index=True)
        known = set(self._bands)
        new_at = {
            int(ix): int(v)
            for v, ix in zip(uniq.tolist(), first.tolist())
            if int(v) not in known
        }
        cuts = sorted({0, n, *new_at})
        cap = (
            None if self.max_rps is None else self.max_rps * self.window
        )
        for s, e in zip(cuts[:-1], cuts[1:]):
            if s in new_at:
                self._note_band(new_at[s])
            seg = prio[s:e]
            bands = self._bands
            b = len(bands)
            top = bands[-1]
            if b > 1:
                # The goodput floor: top band admitted outright, exempt
                # even from the hard cap (checked first in `admit`).
                top_mask = seg >= top
            else:
                top_mask = np.zeros(e - s, dtype=bool)
            admitted = top_mask.copy()
            rest = ~top_mask
            if cap is not None:
                arrival_index = a0 + np.arange(
                    s + 1, e + 1, dtype=np.float64
                )
                rest &= ~(arrival_index > cap)
            if rest.any():
                j = np.searchsorted(bands, seg, side="right") - 1
                j = np.maximum(j, 0)
                lo = (b - 1 - j) / b
                p = np.clip((self.level - lo) * b, 0.0, 1.0)
                admitted |= rest & (p >= 1.0)
                frac = np.flatnonzero(rest & (p > 0.0) & (p < 1.0))
                for i in frac.tolist():
                    admitted[i] = self.rng.random() < p[i]
            out[s:e] = admitted
        return out

    def retry_after(self, priority: int) -> float:
        """Pacing hint for a shed response: heavier overload and deeper
        bands wait longer (spreading the retry wave down-band)."""
        if self._bands:
            rank_from_top = len(self._bands) - 1 - max(
                bisect.bisect_right(self._bands, priority) - 1, 0
            )
        else:
            rank_from_top = 0
        hint = self.window * (1.0 / max(self.level, 0.1)) * (1 + rank_from_top)
        return min(max(hint, self.window), self.max_retry_after)

    # -- introspection ---------------------------------------------------

    def status(self) -> Dict:
        return {
            "level": round(self.level, 6),
            "window_s": self.window,
            "max_rps": self.max_rps,
            "pressure": round(self.pressure(), 6),
            "offered_rps_last_window": round(self._last_rate, 3),
            "forecast_rps": (
                None if self.forecast_rps is None
                else round(self.forecast_rps, 3)
            ),
            "latency_ewma_s": round(self._lat.value, 6),
            "queue_ewma": round(self._queue.value, 3),
            "tick_lag_ewma": round(self._tick_lag.value, 6),
            "windows": self.windows,
            "overloaded_windows": self.overloaded_windows,
            "bands": {
                str(b): round(self.band_probability(b), 6)
                for b in self._bands
            },
        }
