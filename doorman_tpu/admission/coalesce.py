"""Micro-batching front-end for GetCapacity.

Concurrent GetCapacity RPCs park their futures into a grid-aligned
window (every window is anchored to the coalescer's start, so requests
arriving together resolve together instead of each starting its own
timer) and the whole window resolves with ONE grouped decision pass.
The pass groups parked work per resource and replays each resource's
requests in arrival order, so it is BYTE-IDENTICAL to running the same
stream through the per-request handler path:

  * `_decide` for different resources touches disjoint stores — only
    the per-resource order matters, and that is preserved;
  * `safe_capacity()` reads only its own resource's store and is
    computed immediately after each decide, exactly where the
    per-request path computes it.

tests/test_admission.py pins this parity (responses and stores, Python
and native engines, mixed bands and `has`-carrying refreshes).

Threading: the grouped pass leaves the event loop only when that is
safe — the native engine's mutex guards concurrent RPC writes, but the
persistence journal is documented loop-only (persist/__init__.py), so
the executor is used iff the server runs the native store WITHOUT
persistence. Python stores (and persisting servers) run the pass on the
loop: still one scheduling point for the whole window, which is the
actual win — O(windows) loop wakeups instead of O(requests).

``window <= 0`` disables parking: submit() runs a one-request batch
inline through the same grouped pass (same code path, same counters),
which keeps the chaos runner's stepped schedule synchronous.
"""

from __future__ import annotations

import asyncio
import contextvars
import logging
import time
from typing import Callable, List, Optional, Tuple

from doorman_tpu.algorithms import Request
from doorman_tpu.obs import trace as trace_mod
from doorman_tpu.proto import doorman_pb2 as pb

log = logging.getLogger(__name__)

__all__ = ["Coalescer", "decide_grouped", "decide_grouped_arrays"]

# Wire algorithm kinds the array pass carries. PROPORTIONAL_SHARE's
# variants (topup/logutil) resolve to different lane ids in
# `Resource._decide_kind`, so they fall out of this set automatically;
# PRIORITY_BANDS / FAIR_SHARE walk the store per request and stay on
# the sequential path.
_ARRAY_KINDS = frozenset((
    int(pb.Algorithm.NO_ALGORITHM),
    int(pb.Algorithm.STATIC),
    int(pb.Algorithm.PROPORTIONAL_SHARE),
))


def decide_grouped_arrays(
    server,
    resource_id: str,
    cids,
    has,
    wants,
    priorities,
    *,
    old_has,
    old_wants,
    new_mask,
    cid_handles=None,
    expected_count=None,
):
    """Array form of `decide_grouped` for one resource's batch of
    single-resource requests in arrival order: compute every grant in a
    vectorized pass, commit them in one bulk store write, and return
    ``(grants, expiry, refresh_interval, safe, fast_rows)`` float/int
    arrays in input order — or None when this resource can't take the
    array path (unsupported algorithm lane, learning mode, persistence
    journaling per decide, or a store the caller's mirrors don't fully
    describe via ``expected_count``).

    Exactness argument (the vector population's parity pin): the
    sequential pass evolves only the store's running aggregates between
    rows — ``sum_wants``/``sum_has`` by per-row ``+= delta`` and
    ``count`` by new-client subclients. np.cumsum accumulates strictly
    left-to-right, so seeding it with the aggregate's starting value
    reproduces the identical sequence of float additions; each row's
    grant formula is then evaluated with the scalar algorithm's exact
    operation order. The one circularity — PROPORTIONAL_SHARE's free
    clamp reads ``sum_has`` which depends on earlier grants — is
    resolved by hypothesis: assume no row clamps (the steady state),
    check ``grant <= free`` elementwise, and on the first violating row
    commit only the exact prefix before it, finishing the remainder
    through the sequential `decide_grouped` (so clamped ticks are
    slower, never wrong).

    ``old_has``/``old_wants``/``new_mask`` are the caller's per-row
    mirrors of the store rows (exact, because every value they hold
    came out of this same decide path); ``expected_count`` is the
    caller's live-lease count — a mismatch with ``store.count`` means a
    foreign writer shares the store and the array pass stands down.
    Preconditions the CALLER owns: server is master, no lease in the
    store is expired (a sequential decide would sweep it), and every
    row's client already holds a lease iff ``new_mask`` says so.
    """
    import numpy as np  # deferred: the RPC path never pays the import

    res = server.get_or_create_resource(resource_id)
    if (
        res._decide_kind not in _ARRAY_KINDS
        or res.in_learning_mode
        or server._persist is not None
    ):
        return None
    store = res.store
    if expected_count is not None and store.count != expected_count:
        return None

    n = len(wants)
    w = np.ascontiguousarray(wants, np.float64)
    prio = np.ascontiguousarray(priorities, np.int64)
    old_h = np.ascontiguousarray(old_has, np.float64)
    old_w = np.ascontiguousarray(old_wants, np.float64)
    new = np.ascontiguousarray(new_mask, bool)
    cap = res.capacity
    length = res._lease_length
    interval = res._refresh_interval
    now = server._clock()

    kind = res._decide_kind
    if kind == int(pb.Algorithm.NO_ALGORITHM):
        grants = w.copy()
        fast_rows = n
    elif kind == int(pb.Algorithm.STATIC):
        # STATIC's capacity is per client, not a shared pool: no
        # cross-row state at all.
        grants = np.minimum(cap, w)
        fast_rows = n
    else:  # PROPORTIONAL_SHARE (scalar.proportional_share)
        # sum_wants as row i reads it: the starting aggregate plus the
        # earlier rows' (wants - old.wants) deltas, accumulated in the
        # same left-to-right order assign() applies them.
        sw_before = np.cumsum(
            np.concatenate(([store.sum_wants], (w - old_w)[:-1]))
        )
        all_wants = (sw_before - old_w) + w
        with np.errstate(divide="ignore", invalid="ignore"):
            # Both branches evaluate everywhere; the overload quotient
            # is garbage (and discarded) on underloaded rows.
            grants = np.where(
                all_wants < cap, w, w * (cap / all_wants)
            )
        # sum_has as row i reads it, under the no-clamp hypothesis.
        sh_before = np.cumsum(
            np.concatenate(([store.sum_has], (grants - old_h)[:-1]))
        )
        free = np.maximum(cap - (sh_before - old_h), 0.0)
        ok = grants <= free
        fast_rows = n if bool(ok.all()) else int(np.argmax(~ok))
        grants = grants[:fast_rows]

    name_of = None
    if cids is None:
        # Names are recoverable from the engine's interning table; the
        # fast path never materializes them.
        name_of = server._store_engine.client_name

    if fast_rows:
        bulk_handles = getattr(store, "bulk_assign_handles", None)
        if cid_handles is not None and bulk_handles is not None:
            bulk_handles(
                cid_handles[:fast_rows], length, interval,
                grants, w[:fast_rows], priority=prio[:fast_rows],
            )
        else:
            names = (
                cids[:fast_rows] if cids is not None
                else [name_of(int(h)) for h in cid_handles[:fast_rows]]
            )
            store.bulk_assign(
                names, length, interval, grants, w[:fast_rows],
                priority=prio[:fast_rows],
            )

    out_grants = np.empty(n, np.float64)
    out_expiry = np.empty(n, np.float64)
    out_safe = np.empty(n, np.float64)
    out_refresh = np.full(n, interval, np.float64)
    out_grants[:fast_rows] = grants
    out_expiry[:fast_rows] = now + length

    # safe_capacity immediately after each row's assign (where the
    # per-request path computes it): configured value, or capacity over
    # the subclient count — which moves only when a NEW client lands.
    if res.template.HasField("safe_capacity"):
        out_safe[:] = res.template.safe_capacity
    else:
        count_after = store.count  # already includes every bulk row
        if fast_rows:
            # Rewind to the count each row observed: start minus the
            # rows after it.
            new_cum = np.cumsum(new[:fast_rows].astype(np.int64))
            start = count_after - (
                int(new_cum[-1]) if fast_rows else 0
            )
            counts = np.maximum(start + new_cum, 1)
            out_safe[:fast_rows] = res.template.capacity / counts

    if fast_rows < n:
        work = []
        for i in range(fast_rows, n):
            name = (
                cids[i] if cids is not None
                else name_of(int(cid_handles[i]))
            )
            work.append((resource_id, Request(
                name, float(has[i]), float(w[i]), 1,
                priority=int(prio[i]),
            )))
        for j, (lease, _res, safe) in enumerate(
            decide_grouped(server, work)
        ):
            i = fast_rows + j
            out_grants[i] = lease.has
            out_expiry[i] = lease.expiry
            out_refresh[i] = lease.refresh_interval
            out_safe[i] = safe

    return out_grants, out_expiry, out_refresh, out_safe, fast_rows


def decide_grouped(server, work: List[Tuple[str, Request]]) -> List[tuple]:
    """The grouped per-resource decision pass, shared by the coalescer's
    window resolution and the stream fanout's per-shard tick-edge pass.

    `work` is (resource_id, Request) pairs; returns
    `(lease, resource, safe_capacity)` per pair IN INPUT ORDER, decided
    grouped by resource with each resource's requests replayed in
    arrival order — byte-identical to running the same stream through
    the per-request path (see the module docstring's parity argument:
    different resources touch disjoint stores, and safe_capacity is
    computed immediately after each decide, exactly where the
    per-request path computes it)."""
    slots: List[tuple] = [None] * len(work)  # type: ignore[list-item]
    groups: dict = {}
    for i, (resource_id, request) in enumerate(work):
        groups.setdefault(resource_id, []).append((i, request))
    for resource_id, entries in groups.items():
        for i, request in entries:
            lease, res = server._decide(resource_id, request)
            slots[i] = (lease, res, res.safe_capacity())
    return slots


class Coalescer:
    def __init__(
        self,
        server,
        *,
        window: float,
        on_window: Optional[Callable[[int, float], None]] = None,
    ):
        """`server` is the owning CapacityServer; `on_window(occupancy,
        seconds)` fires after each resolved window (metrics hook)."""
        self.server = server
        self.window = float(window)
        self._on_window = on_window
        self._pending: List[Tuple[pb.GetCapacityRequest, asyncio.Future]] = []
        self._flush_handle = None
        # Wall clock by design (all marks in this class): the window grid
        # paces a real event loop. Chaos keeps determinism by running
        # window <= 0 (inline submit), so this timing never fires there.
        self._anchor = time.monotonic()  # doorman: allow[seeded-determinism]
        self.flushes = 0
        self.coalesced_requests = 0  # requests that shared a window
        self.max_occupancy = 0

    @property
    def queue_depth(self) -> int:
        return len(self._pending)

    async def submit(
        self, request: pb.GetCapacityRequest
    ) -> pb.GetCapacityResponse:
        if self.window <= 0:
            return (await self._resolve([(request, None)]))[0]
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        self._pending.append((request, fut))
        if self._flush_handle is None:
            # Grid alignment: fire at the next window boundary since
            # the anchor, not `window` after THIS arrival — late
            # arrivals in a window ride the same flush.
            elapsed = time.monotonic() - self._anchor  # doorman: allow[seeded-determinism]
            delay = self.window - (elapsed % self.window)
            self._flush_handle = loop.call_later(delay, self._flush)
        return await fut

    def _flush(self) -> None:
        self._flush_handle = None
        batch, self._pending = self._pending, []
        if batch:
            asyncio.ensure_future(self._resolve_parked(batch))

    async def _resolve_parked(self, batch) -> None:
        try:
            outs = await self._resolve(batch)
        except Exception as e:
            log.exception("coalesced decision pass failed")
            for _, fut in batch:
                if fut is not None and not fut.done():
                    fut.set_exception(e)
            return
        for (_, fut), out in zip(batch, outs):
            if fut is not None and not fut.done():
                fut.set_result(out)

    async def _resolve(self, batch) -> List[pb.GetCapacityResponse]:
        server = self.server
        start = time.monotonic()  # doorman: allow[seeded-determinism]
        n = len(batch)
        with trace_mod.default_tracer().span(
            "admission.window", cat="admission",
            args={
                "server": server.id, "occupancy": n,
                "resources": len(
                    {rr.resource_id for req, _ in batch
                     for rr in req.resource}
                ),
            },
        ):
            if not server.is_master:
                # A flip while parked: every parked request gets the
                # redirect it would have gotten from the handler.
                outs = []
                for _ in batch:
                    out = pb.GetCapacityResponse()
                    out.mastership.CopyFrom(server._mastership())
                    outs.append(out)
            else:
                # Resources are created ON the loop before any executor
                # hop, so the grouped pass never races get-or-create
                # against other handlers.
                for req, _ in batch:
                    for rr in req.resource:
                        server.get_or_create_resource(rr.resource_id)
                if server._native_store and server._persist is None:
                    ctx = contextvars.copy_context()
                    outs = await asyncio.get_running_loop().run_in_executor(
                        None, ctx.run, self._decide_batch, batch
                    )
                else:
                    outs = self._decide_batch(batch)
        seconds = time.monotonic() - start  # doorman: allow[seeded-determinism]
        self.flushes += 1
        self.max_occupancy = max(self.max_occupancy, n)
        if n > 1:
            self.coalesced_requests += n
        if self._on_window is not None:
            self._on_window(n, seconds)
        return outs

    def _decide_batch(self, batch) -> List[pb.GetCapacityResponse]:
        """The grouped decision pass (see module docstring for the
        parity argument). May run on the loop or in the executor."""
        server = self.server
        work: List[Tuple[str, Request]] = []
        for req, _ in batch:
            for rr in req.resource:
                has = rr.has.capacity if rr.HasField("has") else 0.0
                work.append((
                    rr.resource_id,
                    Request(req.client_id, has, rr.wants, 1,
                            priority=rr.priority),
                ))
        try:
            decided = decide_grouped(server, work)
        except BaseException:
            # A partially-applied window leaves the fused staging cache
            # unable to prove freshness for rows already written (their
            # dirty flags would be consumed against a pre-write pack);
            # drop the whole cache — the clean fallback is the
            # round-trip pack.
            server._fused_invalidate()
            raise
        # Admission-fused staging: the grouped writes just landed, so
        # pack the touched rows NOW — in this RPC window, overlapped
        # with whatever tick is in flight — instead of at the next
        # tick's dispatch (no-op unless the server attached staging).
        server._fused_stage({resource_id for resource_id, _ in work})
        outs = []
        cursor = 0
        for req, _ in batch:
            out = pb.GetCapacityResponse()
            for rr in req.resource:
                lease, _res, safe = decided[cursor]
                cursor += 1
                resp = out.response.add()
                resp.resource_id = rr.resource_id
                resp.gets.expiry_time = int(lease.expiry)
                resp.gets.refresh_interval = int(lease.refresh_interval)
                resp.gets.capacity = lease.has
                resp.safe_capacity = safe
            outs.append(out)
        return outs

    def status(self) -> dict:
        return {
            "window_s": self.window,
            "queue_depth": self.queue_depth,
            "flushes": self.flushes,
            "coalesced_requests": self.coalesced_requests,
            "max_occupancy": self.max_occupancy,
        }
