"""Deadline-aware admission: fast-fail requests that cannot make it.

A request whose gRPC deadline is shorter than the latency the admission
path is currently delivering would park in a coalescing window, consume
a decision slot, and then miss its deadline anyway — the worst of both
worlds (work done, goodput zero). The tracker below keeps an EWMA of
observed decision latency (park -> resolved); the expected latency for
a NEW arrival is one full coalescing window (the worst-case park) plus
that EWMA. A request with less remaining deadline than that fast-fails
with the same RESOURCE_EXHAUSTED + retry-after contract as an overload
shed (one client-side handling path), before it costs anything.
"""

from __future__ import annotations

from typing import Optional

__all__ = ["DecisionLatency", "fast_fail_reason"]


class DecisionLatency:
    """EWMA of admission decision latency in seconds (submit to
    response, coalescing park included)."""

    def __init__(self, alpha: float = 0.2):
        self.alpha = alpha
        self.value = 0.0
        self.samples = 0

    def observe(self, seconds: float) -> None:
        self.samples += 1
        if self.samples == 1:
            self.value = seconds
        else:
            self.value += self.alpha * (seconds - self.value)


def expected_latency(window: float, latency: DecisionLatency) -> float:
    """Worst-case expected admission latency for a new arrival."""
    return max(window, 0.0) + latency.value


def fast_fail_reason(
    context, window: float, latency: DecisionLatency
) -> Optional[str]:
    """A human-readable fast-fail reason when the RPC's remaining
    deadline cannot cover the expected admission latency; None when the
    request should proceed (no deadline, or enough headroom)."""
    if context is None:
        return None
    try:
        remaining = context.time_remaining()
    except Exception:
        return None
    if remaining is None:
        return None
    expected = expected_latency(window, latency)
    if remaining < expected:
        return (
            f"deadline {remaining:.3f}s shorter than expected admission "
            f"latency {expected:.3f}s; fast-failing instead of queueing"
        )
    return None
