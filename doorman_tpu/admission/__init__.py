"""RPC admission control: coalescing, overload shedding, deadlines.

The serving front door for the 1M-client north star. Three cooperating
parts, wired into CapacityServer via the ``admission=`` kwarg:

  * `coalesce.Coalescer` — parks concurrent GetCapacity futures into a
    grid-aligned micro-batch window and resolves each window with one
    grouped, byte-identical-to-per-request decision pass;
  * `controller.AimdController` — per-priority-band admit
    probabilities from an AIMD level fed by arrival rate, RPC latency,
    queue depth, and tick lag (lowest bands shed first, the top band
    never while lower bands exist);
  * `policy` / `deadline` — the shed matrix (GetCapacity only — never
    ReleaseCapacity, GetServerCapacity, or Discovery), the
    RESOURCE_EXHAUSTED + ``doorman-retry-after`` trailing-metadata
    contract, and fast-fail for requests whose gRPC deadline cannot
    cover the expected admission latency.

See doc/admission.md for the controller math and the operator story.
"""

from __future__ import annotations

import random
from typing import Dict, Optional

from doorman_tpu.admission.coalesce import Coalescer
from doorman_tpu.admission.controller import AimdController
from doorman_tpu.admission.deadline import DecisionLatency, fast_fail_reason
from doorman_tpu.admission.ramp import EstablishmentRamp
from doorman_tpu.admission.policy import (
    RETRY_AFTER_KEY,
    SHED_MATRIX,
    Shed,
    sheddable,
)
from doorman_tpu.obs import metrics as metrics_mod

__all__ = [
    "Admission",
    "AimdController",
    "Coalescer",
    "DecisionLatency",
    "EstablishmentRamp",
    "RETRY_AFTER_KEY",
    "SHED_MATRIX",
    "Shed",
    "sheddable",
]

_OCCUPANCY_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0)


class Admission:
    """Facade the server wires through: one Admission per server.

    Construct with controller knobs, then `bind(server)` (done by
    CapacityServer.__init__) attaches the server's clock and builds the
    coalescer. `rng` seeds the controller's admit draws — the chaos
    runner passes its plan-seeded RNG so storms replay deterministically.
    """

    def __init__(
        self,
        *,
        coalesce_window: float = 0.0,
        controller: Optional[AimdController] = None,
        clock=None,
        rng: Optional[random.Random] = None,
        **controller_kwargs,
    ):
        self.coalesce_window = float(coalesce_window)
        self.controller = controller
        self._clock = clock
        self._rng = rng
        self._controller_kwargs = controller_kwargs
        self.latency = DecisionLatency()
        self.coalescer: Optional[Coalescer] = None
        self._server = None
        # (method, band) -> {"admitted": n, "shed": n, "fast_fail": n}.
        # Plain dict (not the prometheus counters) so the chaos
        # invariants read exact deterministic integers.
        self.tallies: Dict = {}
        # Frontend pool attribution: worker index -> the same tally
        # shape, absorbed from listener-worker heartbeats (real pool)
        # or stamped at establishment (inline pool). The gate itself
        # runs HERE either way — these never double-count into
        # `tallies`, they say which listener the traffic arrived
        # through (/debug/frontend).
        self.worker_tallies: Dict[int, Dict] = {}

        reg = metrics_mod.default_registry()
        self._requests = reg.counter(
            "doorman_admission_requests",
            "Admission decisions by method, priority band, and outcome "
            "(admitted / shed / fast_fail; pass_through for never-shed "
            "methods).",
            labels=("method", "band", "outcome"),
        )
        self._coalesced = reg.counter(
            "doorman_admission_coalesced_requests",
            "GetCapacity requests resolved in a shared coalescing "
            "window (occupancy > 1), by priority band.",
            labels=("band",),
        )
        self._occupancy = reg.histogram(
            "doorman_admission_window_occupancy",
            "Requests resolved per coalescing window.",
            buckets=_OCCUPANCY_BUCKETS,
        )
        self._decision = reg.histogram(
            "doorman_admission_decision_seconds",
            "Grouped decision-pass latency per coalescing window.",
        )
        self._level_gauge = reg.gauge(
            "doorman_admission_level",
            "Current AIMD admit level, by server.",
            labels=("server",),
        )

    # -- wiring ----------------------------------------------------------

    def bind(self, server) -> "Admission":
        self._server = server
        if self.controller is None:
            self.controller = AimdController(
                clock=self._clock or server._clock,
                rng=self._rng,
                **self._controller_kwargs,
            )
        self.coalescer = Coalescer(
            server, window=self.coalesce_window, on_window=self._on_window
        )
        return self

    def _on_window(self, occupancy: int, seconds: float) -> None:
        self.latency.observe(seconds)
        self.controller.observe_queue(float(occupancy))
        self._occupancy.observe(float(occupancy))
        self._decision.observe(seconds)

    # -- bookkeeping -----------------------------------------------------

    def _tally(self, method: str, band: int, outcome: str) -> None:
        entry = self.tallies.setdefault(
            (method, band), {"admitted": 0, "shed": 0, "fast_fail": 0}
        )
        entry[outcome] += 1
        self._requests.inc(method, str(band), outcome)

    # -- the decision ----------------------------------------------------

    def check_get_capacity(self, request, context) -> Optional[Shed]:
        """None to admit; a Shed to refuse with RESOURCE_EXHAUSTED +
        retry-after. The request's band is its most important resource
        line — a bulk refresh carrying ANY high-band resource is kept
        (shedding it would starve the high band along with the low)."""
        band = max((rr.priority for rr in request.resource), default=0)
        reason = fast_fail_reason(
            context, self.coalesce_window, self.latency
        )
        if reason is not None:
            self._tally("GetCapacity", band, "fast_fail")
            return Shed(
                reason=reason,
                retry_after=self.controller.retry_after(band),
                band=band,
                kind="deadline",
            )
        admitted, retry_after = self.controller.admit(band)
        if admitted:
            self._tally("GetCapacity", band, "admitted")
            return None
        self._tally("GetCapacity", band, "shed")
        return Shed(
            reason=(
                f"overload: band {band} shed at admit level "
                f"{self.controller.level:.3f}; retry after "
                f"{retry_after:.3f}s"
            ),
            retry_after=retry_after,
            band=band,
            kind="overload",
        )

    def check_get_capacity_band(self, band: int) -> bool:
        """One driver-side gate decision for a single-resource refresh
        of this band. Identical controller draw and tally sequence to
        `check_get_capacity` with no RPC context (so no deadline
        fast-fail — the vector population drives the server in-process
        with no per-request deadline, same as the loopback harness
        clients whose deadlines never bind)."""
        admitted, _ = self.controller.admit(band)
        self._tally(
            "GetCapacity", band, "admitted" if admitted else "shed"
        )
        return admitted

    def check_get_capacity_many(self, priorities):
        """Vectorized gate for a batch of single-resource refreshes
        (bands = priorities, in input order): one `admit_many` pass,
        bulk tallies. Returns the boolean admitted mask. Draw-order and
        tally-count identical to calling `check_get_capacity` once per
        request in the same order (the deterministic-tally contract the
        chaos invariants and the workload `_log_admission` rows read).
        """
        import numpy as np  # deferred: keep the module import-light

        prio = np.asarray(priorities, dtype=np.int64)
        admitted = self.controller.admit_many(prio)
        for outcome, mask in (("admitted", admitted), ("shed", ~admitted)):
            if not mask.any():
                continue
            bands, counts = np.unique(prio[mask], return_counts=True)
            for band, k in zip(bands.tolist(), counts.tolist()):
                entry = self.tallies.setdefault(
                    ("GetCapacity", int(band)),
                    {"admitted": 0, "shed": 0, "fast_fail": 0},
                )
                entry[outcome] += int(k)
                self._requests.inc(
                    "GetCapacity", str(int(band)), outcome, by=float(k)
                )
        return admitted

    def check_watch(self, request) -> Optional[Shed]:
        """Admission gate for WatchCapacity stream ESTABLISHMENT: the
        same AIMD band-ordered shed as a refresh (lowest bands
        extinguish first, the top band never while lower bands exist).
        No deadline fast-fail — a stream has no per-RPC deadline to
        protect. The per-band stream cap is enforced by the server's
        StreamRegistry (it owns the live counts) AFTER this gate, so a
        capped band still consumes an admit draw — establishment
        attempts are offered load like any other."""
        band = max((rr.priority for rr in request.resource), default=0)
        admitted, retry_after = self.controller.admit(band)
        if admitted:
            self._tally("WatchCapacity", band, "admitted")
            return None
        self._tally("WatchCapacity", band, "shed")
        return Shed(
            reason=(
                f"overload: stream establishment for band {band} shed "
                f"at admit level {self.controller.level:.3f}; retry "
                f"after {retry_after:.3f}s"
            ),
            retry_after=retry_after,
            band=band,
            kind="overload",
        )

    def absorb_worker_tallies(self, worker: int, tallies: Dict) -> None:
        """Merge one frontend worker's tally DELTAS (keys
        "method/band", counts since its last report) into the per-worker
        attribution table."""
        slot = self.worker_tallies.setdefault(int(worker), {})
        for key, counts in tallies.items():
            dst = slot.setdefault(
                key, {"admitted": 0, "shed": 0, "fast_fail": 0}
            )
            for outcome, n in counts.items():
                dst[outcome] = dst.get(outcome, 0) + int(n)

    def note_pass_through(self, method: str, band: int = 0) -> None:
        """Tally a never-shed method (the shed matrix's 'never' rows);
        these do not consume controller admit draws — they are load the
        controller cannot refuse, visible in the counters either way."""
        self._tally(method, band, "admitted")

    async def serve_get_capacity(self, request):
        """Resolve an ADMITTED GetCapacity through the coalescer."""
        return await self.coalescer.submit(request)

    def observe_rpc(self, seconds: float) -> None:
        self.controller.observe_rpc(seconds)
        if self._server is not None:
            self._level_gauge.set(self.controller.level, self._server.id)

    # -- introspection ---------------------------------------------------

    def status(self) -> dict:
        tallies = {
            f"{method}/{band}": dict(v)
            for (method, band), v in sorted(self.tallies.items())
        }
        return {
            "controller": self.controller.status()
            if self.controller is not None
            else None,
            "coalescer": self.coalescer.status()
            if self.coalescer is not None
            else None,
            "expected_latency_s": round(
                self.coalesce_window + self.latency.value, 6
            ),
            "tallies": tallies,
            "worker_tallies": {
                str(w): dict(v)
                for w, v in sorted(self.worker_tallies.items())
            },
        }
