"""The shed matrix and the retry-after contract.

What may be shed, and what a shed looks like on the wire:

  method               sheddable  why
  -------------------  ---------  ------------------------------------
  GetCapacity          by band    refreshes are RETRYABLE BY DESIGN —
                                  leases outlive a missed refresh, so a
                                  shed client keeps serving on its last
                                  grant and simply comes back later
  WatchCapacity        by band    stream ESTABLISHMENT only (an open
                                  stream is never shed mid-flight — it
                                  costs the server nothing until a row
                                  moves): a refused subscriber simply
                                  keeps polling, which is the exact
                                  contract it would have without the
                                  stream; per-band stream caps shed
                                  here too (kind="stream_cap")
  GetServerCapacity    never      one RPC aggregates a whole downstream
                                  subtree; shedding it degrades every
                                  client under that server at once
  ReleaseCapacity      never      releases SHRINK load — shedding one
                                  pins capacity on a dead client and
                                  makes the overload worse
  Discovery            never      mastership discovery is how clients
                                  drain AWAY from this server

A shed GetCapacity is `RESOURCE_EXHAUSTED` with the pacing hint in
trailing metadata under ``doorman-retry-after`` (seconds, decimal). The
hint is the admission-path's analog of the lease's `refresh_interval`
field — "come back in N seconds" — carried in metadata because a
non-OK gRPC status cannot carry a response message. Clients honor it
with jitter (half the hint plus a uniform draw over the other half) so
a shed wave does not re-synchronize into the next storm.
"""

from __future__ import annotations

from dataclasses import dataclass

__all__ = ["RETRY_AFTER_KEY", "SHED_MATRIX", "Shed", "sheddable"]

# gRPC trailing-metadata key carrying the retry-after hint (seconds).
RETRY_AFTER_KEY = "doorman-retry-after"

# method -> may the admission controller shed it?
SHED_MATRIX = {
    "GetCapacity": True,
    "WatchCapacity": True,  # establishment only; see the table above
    "GetServerCapacity": False,
    "ReleaseCapacity": False,
    "Discovery": False,
}


def sheddable(method: str) -> bool:
    return SHED_MATRIX.get(method, False)


@dataclass(frozen=True)
class Shed:
    """A decision to refuse one request."""

    reason: str
    retry_after: float
    band: int
    kind: str  # "overload" | "deadline" | "stream_cap"
