"""Establishment ramp: grid-aligned micro-batching for WatchCapacity
establishment, the streaming twin of the GetCapacity coalescer.

A storm of stream establishments is the front-end's worst arrival
shape: each one is a gate check plus a full-snapshot decide pass, and
under the single-loop server every arrival was its own loop wakeup.
The ramp parks concurrent establishment thunks into the same
grid-aligned window discipline as admission/coalesce.py — every window
is anchored to the ramp's start, so a burst arriving together resolves
together in ONE loop callback, in arrival order (the registry's
establishment-order contract is preserved: `order` is assigned inside
the thunk, at resolution, and resolution replays arrival order).

The frontend listener workers forward establishments to the tick
process; the ramp is where those forwarded arrivals amortize — N
workers' storms become O(windows) loop wakeups on the device-owning
process instead of O(establishments).

``window <= 0`` disables parking: submit() runs the thunk inline —
the chaos runner and the stepped workload harness stay synchronous
and deterministic.
"""

from __future__ import annotations

import asyncio
import time
from typing import Any, Callable, List, Optional, Tuple

__all__ = ["EstablishmentRamp"]


class EstablishmentRamp:
    def __init__(
        self,
        *,
        window: float,
        on_window: Optional[Callable[[int, float], None]] = None,
    ):
        self.window = float(window)
        self._on_window = on_window
        self._pending: List[Tuple[Callable[[], Any], asyncio.Future]] = []
        self._flush_handle = None
        # Wall clock by design: the window grid paces a real event
        # loop. Chaos/workload keep determinism by running window <= 0
        # (inline submit), so this timing never fires there.
        self._anchor = time.monotonic()  # doorman: allow[seeded-determinism]
        self.flushes = 0
        self.batched = 0  # establishments that shared a window
        self.total = 0
        self.max_occupancy = 0

    @property
    def queue_depth(self) -> int:
        return len(self._pending)

    async def submit(self, establish: Callable[[], Any]) -> Any:
        """Run one establishment thunk at the next window boundary.
        The thunk is synchronous (gate check + registry subscribe — no
        awaits); its result or exception propagates to the caller."""
        self.total += 1
        if self.window <= 0:
            return establish()
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        self._pending.append((establish, fut))
        if self._flush_handle is None:
            # Grid alignment: fire at the next boundary since the
            # anchor, not `window` after THIS arrival — late arrivals
            # in a window ride the same flush.
            elapsed = time.monotonic() - self._anchor  # doorman: allow[seeded-determinism]
            delay = self.window - (elapsed % self.window)
            self._flush_handle = loop.call_later(delay, self._flush)
        return await fut

    def _flush(self) -> None:
        self._flush_handle = None
        batch, self._pending = self._pending, []
        if not batch:
            return
        t0 = time.perf_counter()
        self.flushes += 1
        self.max_occupancy = max(self.max_occupancy, len(batch))
        if len(batch) > 1:
            self.batched += len(batch)
        for establish, fut in batch:
            if fut.cancelled():
                continue
            try:
                fut.set_result(establish())
            except Exception as exc:  # propagate to the awaiting handler
                fut.set_exception(exc)
        if self._on_window is not None:
            self._on_window(len(batch), time.perf_counter() - t0)

    def close(self) -> None:
        if self._flush_handle is not None:
            self._flush_handle.cancel()
            self._flush_handle = None
        # Resolve stragglers inline rather than leaving them parked
        # forever on a closing server.
        self._flush()

    def status(self) -> dict:
        return {
            "window": self.window,
            "total": self.total,
            "flushes": self.flushes,
            "batched": self.batched,
            "max_occupancy": self.max_occupancy,
            "queue_depth": self.queue_depth,
        }
