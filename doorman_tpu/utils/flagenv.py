"""Populate argparse defaults from environment variables.

Capability parity with reference go/flagenv/flagenv.go:22-69: a flag
`--foo-bar` with prefix DOORMAN falls back to env var DOORMAN_FOO_BAR when
not given on the command line.
"""

from __future__ import annotations

import argparse
import os


def flag_to_env(prefix: str, flag_name: str) -> str:
    return f"{prefix}_{flag_name}".upper().replace("-", "_")


def populate(parser: argparse.ArgumentParser, prefix: str = "DOORMAN") -> None:
    """For every parser option, use the matching env var as the default (an
    explicit command-line value still wins)."""
    for action in parser._actions:  # noqa: SLF001 - argparse has no public iterator
        if not action.option_strings:
            continue
        name = action.option_strings[-1].lstrip("-")
        env = flag_to_env(prefix, name)
        if env in os.environ:
            raw = os.environ[env]
            if action.type is not None:
                raw = action.type(raw)
            elif isinstance(action, argparse._StoreTrueAction):  # noqa: SLF001
                raw = raw.lower() in ("1", "true", "yes")
            elif isinstance(action, argparse._StoreFalseAction):  # noqa: SLF001
                # DOORMAN_NO_FOO=true means "apply the flag": dest = False.
                raw = raw.lower() not in ("1", "true", "yes")
            elif not isinstance(action, argparse._StoreAction):  # noqa: SLF001
                raise ValueError(
                    f"cannot populate {env}: unsupported action for "
                    f"--{name}"
                )
            action.default = raw
            action.required = False
