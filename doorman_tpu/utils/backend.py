"""Backend initialization watchdog.

jax.devices() blocks forever when the tunneled device backend is
unreachable; callers that must not hang (the bench, the driver's entry
compile-check) probe it on a daemon thread with a deadline instead.
One shared implementation so the bench and the entry point cannot
drift.
"""

from __future__ import annotations

import threading
from typing import Optional, Tuple


def probe_backend(
    timeout_s: float = 180.0,
) -> Tuple[Optional[list], Optional[BaseException]]:
    """Initialize jax's default backend with a deadline.

    Returns (devices, None) on success, (None, exception) when
    initialization failed fast, and (None, None) when it timed out —
    the abandoned daemon thread keeps blocking harmlessly."""
    result: dict = {}

    def probe():
        try:
            import jax

            result["devices"] = jax.devices()
        except Exception as e:
            result["exc"] = e

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout_s)
    return result.get("devices"), result.get("exc")


def probe_backend_or_reason(
    timeout_s: float = 180.0,
) -> Tuple[Optional[list], Optional[str], Optional[BaseException]]:
    """probe_backend plus the shared diagnostic line:
    (devices, None, None) on success, (None, reason, exc) on failure —
    the bench and the entry point render the identical message for the
    identical condition, and raisers chain `exc` so the original
    backend traceback survives."""
    devices, exc = probe_backend(timeout_s)
    if devices is not None:
        return devices, None, None
    if exc is not None:
        return None, f"{type(exc).__name__}: {exc}", exc
    return None, (
        f"jax backend did not initialize within {timeout_s:.0f}s "
        "(device tunnel down?)"
    ), None
