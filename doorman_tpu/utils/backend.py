"""Backend initialization watchdog.

jax.devices() blocks forever when the tunneled device backend is
unreachable, and an in-process probe that hangs leaves a stuck init
thread that can race later device work for exclusive access. Callers
that must not hang (the bench, the driver's entry compile-check, the
tools/drives scripts) therefore probe in THROWAWAY subprocesses with a
deadline BEFORE any in-process jax use, waiting out transient tunnel
blips on a paced retry schedule. One shared implementation so the
callers cannot drift.
"""

from __future__ import annotations

import time
from typing import Optional


def wait_for_backend(
    attempts: int = 3,
    per_timeout_s: float = 180.0,
    cwd: Optional[str] = None,
    probe_argv=None,
) -> Optional[str]:
    """Wait out a device-tunnel blip: probe the backend in a THROWAWAY
    subprocess every attempt (a fresh process re-initializes JAX, so a
    tunnel that recovered mid-wait is actually picked up — an
    in-process jax.devices() that began during the outage may be stuck
    or have cached the failure). Attempts are paced to the full
    per-attempt window even when a probe fails fast (connection
    refused), so the total wait genuinely spans ~attempts*per_timeout
    seconds of wall clock; per_timeout_s defaults to the full single
    window a slow-but-healthy cold init can legitimately need. Returns
    None once a probe succeeds, else the last failure reason. Progress
    goes to stderr so a long wait is visibly a wait.

    `probe_argv` substitutes the probe command — a list, or a callable
    returning one, resolved fresh per attempt (the chaos harness
    injects failing/healing probes this way to pin the retry
    classification deterministically)."""
    import subprocess
    import sys

    default_argv = [sys.executable, "-c",
                    "import jax; jax.devices(); print('ok')"]
    reason = "backend probe never ran"
    for attempt in range(1, attempts + 1):
        attempt_start = time.monotonic()  # doorman: allow[seeded-determinism]
        if callable(probe_argv):
            argv = probe_argv()
        else:
            argv = probe_argv or default_argv
        try:
            proc = subprocess.run(
                argv,
                capture_output=True, text=True, timeout=per_timeout_s,
                cwd=cwd,
            )
            if proc.returncode == 0 and "ok" in proc.stdout:
                return None
            reason = (
                proc.stderr.strip()[-1500:] or f"rc={proc.returncode}"
            )
            # A broken environment cannot heal by waiting; report it in
            # seconds, not after the full retry schedule. Only the
            # FINAL stderr line (the raising exception) counts —
            # incidental import warnings earlier in the tail must not
            # abort the blip-riding retries.
            last_line = reason.splitlines()[-1] if reason else ""
            # Code/environment breakage can never heal by waiting.
            # ONLY error types that transport failures never raise are
            # classified unretryable: RuntimeError/ValueError stay
            # retryable because a down tunnel surfaces exactly those
            # (fast and verbatim-identical), and burning the paced
            # schedule on them would recreate the round-4 failure mode
            # (a multi-hour outage reported as unreachable seconds in,
            # when spanning the blip was the whole point).
            if last_line.startswith(
                ("ModuleNotFoundError", "ImportError", "SyntaxError",
                 "AttributeError", "NameError")
            ):
                print(
                    f"backend probe failed (unretryable): {last_line}",
                    file=sys.stderr, flush=True,
                )
                return reason
        except subprocess.TimeoutExpired:
            reason = (
                f"jax backend did not initialize within "
                f"{per_timeout_s:.0f}s (device tunnel down?)"
            )
        print(
            f"backend probe {attempt}/{attempts} failed: {reason}",
            file=sys.stderr, flush=True,
        )
        if attempt < attempts:
            # Pace fast failures to the attempt window: the point is to
            # span the blip, not to burn every attempt in seconds.
            elapsed = time.monotonic() - attempt_start  # doorman: allow[seeded-determinism]
            time.sleep(max(0.0, per_timeout_s - elapsed))
    return reason


def probe_devices(
    per_timeout_s: float = 120.0,
    cwd: Optional[str] = None,
    probe_argv=None,
) -> Optional[tuple]:
    """One throwaway-subprocess probe of the default backend's device
    inventory: (platform, device_count), or None when the probe fails
    (unreachable or broken backend). Same isolation rationale as
    wait_for_backend — jax is never initialized in-process, so the
    caller can still pick a different platform (e.g. a forced
    multi-device CPU fallback) before its own first backend use."""
    import subprocess
    import sys

    argv = probe_argv or [
        sys.executable, "-c",
        "import jax; d = jax.devices(); "
        "print('ok', d[0].platform, len(d))",
    ]
    try:
        proc = subprocess.run(
            argv, capture_output=True, text=True,
            timeout=per_timeout_s, cwd=cwd,
        )
    except subprocess.TimeoutExpired:
        return None
    if proc.returncode != 0:
        return None
    for line in proc.stdout.splitlines():
        parts = line.split()
        if len(parts) == 3 and parts[0] == "ok":
            try:
                return parts[1], int(parts[2])
            except ValueError:
                return None
    return None
