"""Host<->device transfer helpers for high-latency device links.

A single `jax.device_get` of a large array serializes one copy stream;
tunneled/remote device links (and to a lesser degree PCIe) only reach
full bandwidth with several async copies in flight. `chunked_device_get`
splits the copy along the leading axis and overlaps the pieces.
"""

from __future__ import annotations

import numpy as np

from doorman_tpu.utils import dispatch as dispatch_mod


def split_for_download(
    arr, *, chunks: "int | None" = None, min_bytes: int = 1 << 17
) -> list:
    """Split a device array into leading-axis slices for an overlapped
    download whose async copies the CALLER starts (use when the copy
    should begin well before the consuming `device_get`, e.g. at
    dispatch time in a pipelined tick). Always returns a list — length
    1 when splitting cannot help. chunks=None sizes the stream count
    to the array (one per ~256 KB, between 2 and 8).

    Each slice is an XLA slice op producing its own (small) device
    buffer — NOT a view — so the split costs one dispatch and a
    transient allocation per part; the bytes crossing the host link
    are unchanged."""
    nbytes = getattr(arr, "nbytes", 0)
    ndim = getattr(arr, "ndim", 0)
    if chunks is None:
        chunks = int(min(8, max(2, nbytes >> 18)))
    if chunks <= 1:
        # Single-stream download (the fused tick's shape): no slice op
        # at all — the array itself is the one part.
        return [arr]
    if ndim < 1 or nbytes < min_bytes or arr.shape[0] < chunks:
        return [arr]
    bounds = np.linspace(0, arr.shape[0], chunks + 1).astype(int)
    parts = [arr[a:b] for a, b in zip(bounds[:-1], bounds[1:])]
    # Each split slice is its own device op (see docstring): the
    # overlap's dispatch cost, counted so the fused tick's single-
    # stream download shows up as fewer dispatches, not just a claim.
    dispatch_mod.count_dispatch(len(parts))
    return parts


def land_parts(parts: list) -> np.ndarray:
    """Land `split_for_download` parts into one contiguous ndarray
    (preallocated — no per-part concatenate copy). Every part landed
    is one device->host sync in the dispatch accounting
    (utils.dispatch) — the chokepoint the fused-tick `host_syncs`
    number reads."""
    import jax

    dispatch_mod.count_host_sync(len(parts))
    if len(parts) == 1:
        return jax.device_get(parts[0])
    lead = sum(int(p.shape[0]) for p in parts)
    out = np.empty((lead,) + tuple(parts[0].shape[1:]), parts[0].dtype)
    pos = 0
    for p in parts:
        n = int(p.shape[0])
        out[pos : pos + n] = jax.device_get(p)
        pos += n
    return out


def start_download(arr, *, chunks: "int | None" = None,
                   min_bytes: int = 1 << 17) -> list:
    """Split `arr` for an overlapped download AND start the async
    copies; pair with `land_parts` to consume. Failure to start a copy
    is non-fatal (numpy/fake-backend arrays land synchronously)."""
    parts = split_for_download(arr, chunks=chunks, min_bytes=min_bytes)
    try:
        for p in parts:
            p.copy_to_host_async()
    except Exception:
        pass
    return parts


def start_sharded_download(arr) -> list:
    """Per-shard async downloads of a leading-axis device-sharded
    array: one part per shard, ordered by leading-axis offset, so
    `land_parts` reassembles the full [n_dev, ...] block.  Each part is
    a shard's own device buffer — no cross-device reshuffle, and every
    device's host link streams its slice concurrently.  Falls back to
    `start_download` when the array is not sharded (single-device
    resident path)."""
    try:
        shards = list(arr.addressable_shards)
    except Exception:
        return start_download(arr)
    if len(shards) <= 1:
        return start_download(arr)
    parts = [
        s.data
        for s in sorted(shards, key=lambda s: s.index[0].start or 0)
    ]
    try:
        for p in parts:
            p.copy_to_host_async()
    except Exception:
        pass
    return parts


def chunked_device_get(
    arr, *, chunks: int = 8, min_bytes: int = 1 << 20
) -> np.ndarray:
    """device_get with the copy split into `chunks` overlapping pieces.

    Small arrays (< min_bytes) and scalars take the plain path; the
    split is along axis 0. Returns one contiguous ndarray either way.
    """
    if getattr(arr, "nbytes", 0) < min_bytes:
        import jax

        return jax.device_get(arr)
    return land_parts(start_download(arr, chunks=chunks,
                                     min_bytes=min_bytes))
