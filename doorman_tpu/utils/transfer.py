"""Host<->device transfer helpers for high-latency device links.

A single `jax.device_get` of a large array serializes one copy stream;
tunneled/remote device links (and to a lesser degree PCIe) only reach
full bandwidth with several async copies in flight. `chunked_device_get`
splits the copy along the leading axis and overlaps the pieces.
"""

from __future__ import annotations

import numpy as np


def chunked_device_get(
    arr, *, chunks: int = 8, min_bytes: int = 1 << 20
) -> np.ndarray:
    """device_get with the copy split into `chunks` overlapping pieces.

    Small arrays (< min_bytes) and scalars take the plain path; the
    split is along axis 0. Returns one contiguous ndarray either way.
    """
    import jax

    nbytes = getattr(arr, "nbytes", 0)
    ndim = getattr(arr, "ndim", 0)
    if ndim < 1 or nbytes < min_bytes or arr.shape[0] < chunks:
        return jax.device_get(arr)
    bounds = np.linspace(0, arr.shape[0], chunks + 1).astype(int)
    parts = [arr[a:b] for a, b in zip(bounds[:-1], bounds[1:])]
    for p in parts:
        p.copy_to_host_async()
    out = np.empty(arr.shape, arr.dtype)
    for p, a, b in zip(parts, bounds[:-1], bounds[1:]):
        out[a:b] = jax.device_get(p)
    return out
