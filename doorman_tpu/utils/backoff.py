"""Exponential backoff (capability parity with reference
go/timeutil/timeutil.go:26-37: factor 1.3, clamped to [base, max]) with
opt-in FULL jitter.

The deterministic 1.3^n ladder has a fleet-scale failure mode: every
client that failed together retries together, forever — an outage ends
and the whole population storms the recovering master in lockstep. Full
jitter (AWS style: the delay is drawn uniformly from [0, ladder value])
decorrelates the wave; the client refresh retry path and the storm
drivers opt in via ``jitter=``.
"""

from __future__ import annotations

import random

_FACTOR = 1.3

# Shared retry/refresh timing defaults (reference server.go:82-90 and
# connection.go:30-38 use the same values).
MIN_BACKOFF = 1.0
MAX_BACKOFF = 60.0
VERY_LONG_TIME = 60.0 * 60

_JITTER_RNG = random.Random()  # doorman: allow[seeded-determinism]


def backoff(base: float, maximum: float, retries: int, *,
            jitter=None) -> float:
    """Delay in seconds growing exponentially with `retries` from `base`,
    clamped to `maximum`.

    ``jitter`` opts into full jitter: pass a ``random.Random`` for a
    seeded stream (tests, storm drivers), or ``True`` for the module
    RNG; the returned delay is then uniform in [0, ladder value]. The
    default (None) keeps the reference's deterministic ladder."""
    delay = float(base)
    while delay < maximum and retries > 0:
        delay *= _FACTOR
        retries -= 1
    delay = min(delay, maximum)
    if jitter:
        rng = jitter if isinstance(jitter, random.Random) else _JITTER_RNG
        return rng.uniform(0.0, delay)
    return delay
