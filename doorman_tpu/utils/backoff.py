"""Exponential backoff (capability parity with reference
go/timeutil/timeutil.go:26-37: factor 1.3, clamped to [base, max])."""

from __future__ import annotations

_FACTOR = 1.3

# Shared retry/refresh timing defaults (reference server.go:82-90 and
# connection.go:30-38 use the same values).
MIN_BACKOFF = 1.0
MAX_BACKOFF = 60.0
VERY_LONG_TIME = 60.0 * 60


def backoff(base: float, maximum: float, retries: int) -> float:
    """Delay in seconds growing exponentially with `retries` from `base`,
    clamped to `maximum`."""
    delay = float(base)
    while delay < maximum and retries > 0:
        delay *= _FACTOR
        retries -= 1
    return min(delay, maximum)
