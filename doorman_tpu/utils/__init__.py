"""Support utilities: backoff, env-var flag population."""

from doorman_tpu.utils.backoff import backoff  # noqa: F401
