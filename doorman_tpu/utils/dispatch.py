"""Per-process device-dispatch and host-sync accounting.

The fused-tick work (doc/design.md "Fused device-resident tick") turns
"one tick is one dispatch" from a claim into a number: every host->
device transfer and executable launch goes through a counted chokepoint
(`solver.engine.place`, `solver.engine.count_launch`, the download
split in `utils.transfer`), and every device->host landing through
another (`utils.transfer.land_parts`, the delta-mask / match landings).
The counters are process-global on purpose — the tick path may fan
work across executor threads, and the consumers (flight recorder,
/debug/status, bench.py) all want "what did this process ask of the
device between two points in time", which a `snapshot()` delta answers.

What counts as what:

  dispatches  — device work the host ENQUEUES: one per `place()`
                (host->device transfer op), one per tick-executable
                launch (`count_launch`), and one per extra slice op a
                split download creates (`utils.transfer
                .split_for_download` documents that each part beyond a
                single-part download is its own device op). The scoped
                tick's scope-index buffer counts only when it is
                actually re-placed: an unchanged scope reuses the
                cached device copy (TickEngineBase._place_scope), so a
                steady scoped tick reads 3 dispatches while churn
                moves the scope and 2 at the quiet-tick fixpoint —
                tests/test_scoped_solve.py pins both.
  host_syncs  — device->host landings the host WAITS on: one per part
                `land_parts` consumes, one per direct device->host
                `np.asarray`/`device_get` landing on the tick path
                (the delta mask, the mesh ticks' solve-moved mask,
                the stream matcher's pairs).

Increments are a few per tick, so one lock covers both counters.
"""

from __future__ import annotations

import threading
from typing import Dict

__all__ = [
    "count_dispatch",
    "count_host_sync",
    "snapshot",
    "delta",
]

_lock = threading.Lock()
_counts: Dict[str, int] = {  # guarded-by: _lock
    "dispatches": 0,
    "host_syncs": 0,
}


def count_dispatch(n: int = 1) -> None:
    """Record `n` device dispatches (transfer ops / launches)."""
    if n <= 0:
        return
    with _lock:
        _counts["dispatches"] += n


def count_host_sync(n: int = 1) -> None:
    """Record `n` device->host landings the host blocked on."""
    if n <= 0:
        return
    with _lock:
        _counts["host_syncs"] += n


def snapshot() -> Dict[str, int]:
    """Current cumulative counters (monotone since process start)."""
    with _lock:
        return dict(_counts)


def delta(since: Dict[str, int]) -> Dict[str, int]:
    """Counter movement since a previous `snapshot()`."""
    now = snapshot()
    return {k: now[k] - since.get(k, 0) for k in now}
