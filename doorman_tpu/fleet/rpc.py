"""The reconcile beat over real gRPC: head service + shard reporter.

Deployment shape (doc/federation.md, "Deploying the beat over RPC"):
the fleet head runs a small gRPC service speaking the EXISTING
Capacity surface; each shard process runs a ShardReporter task that
periodically sweeps its straddling stores, sends the compact summaries
as one GetServerCapacity (server_id "fleet-shard-<k>"), and installs
the response leases as its straddle shares. No new proto, no
per-client rows on the wire, and the failure story is inherited: a
shard that stops reporting freezes at its last share and drains; a
head that dies stops renewing every share and the whole straddle
decays to per-shard zero — capacity is never invented by an outage.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Callable, Dict, Optional

import grpc

from doorman_tpu.federation.reconcile import summarize_resource
from doorman_tpu.fleet.beat import (
    BeatCore,
    decode_summary,
    encode_summary,
    parse_shard_server_id,
    shard_server_id,
)
from doorman_tpu.obs import trace as trace_mod
from doorman_tpu.proto import doorman_pb2 as pb
from doorman_tpu.proto.grpc_api import (
    CapacityServicer,
    CapacityStub,
    add_capacity_servicer,
)

log = logging.getLogger(__name__)

__all__ = ["FleetBeatServicer", "ShardReporter", "serve_beat"]


class FleetBeatServicer(CapacityServicer):
    """The head's service: GetServerCapacity carrying a fleet-shard
    server_id is a beat report — decode the summaries, fold them into
    BeatCore, answer with the reporting shard's shares as response
    leases. Anything else is politely refused (the head allocates
    nothing itself)."""

    def __init__(self, core: BeatCore):
        self.core = core

    async def Discovery(self, request, context):
        # The head holds no election: it is always "master" of the
        # beat, which lets supervisor readiness checks reuse the
        # ordinary Discovery probe.
        return pb.DiscoveryResponse(is_master=True)

    async def GetServerCapacity(self, request, context):
        shard = parse_shard_server_id(request.server_id)
        if shard is None:
            await context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                "the fleet head only serves beat reports "
                "(server_id 'fleet-shard-<k>')",
            )
        out = pb.GetServerCapacityResponse()
        with trace_mod.default_tracer().span(
            "fleet.beat", cat="fleet",
            args={"shard": shard, "resources": len(request.resource)},
        ):
            for req in request.resource:
                summary = decode_summary(req, shard)
                share = self.core.offer(shard, req.resource_id, summary)
                if share is None:
                    continue
                value, expiry = share
                resp = out.response.add()
                resp.resource_id = req.resource_id
                resp.gets.capacity = float(value)
                resp.gets.expiry_time = int(expiry)
                resp.gets.refresh_interval = max(
                    1, int(self.core.share_ttl / 2)
                )
        return out

    async def GetCapacity(self, request, context):
        await context.abort(
            grpc.StatusCode.UNIMPLEMENTED,
            "the fleet head is not a capacity server; dial a shard",
        )

    async def ReleaseCapacity(self, request, context):
        await context.abort(
            grpc.StatusCode.UNIMPLEMENTED,
            "the fleet head is not a capacity server; dial a shard",
        )

    async def WatchCapacity(self, request, context):
        await context.abort(
            grpc.StatusCode.UNIMPLEMENTED,
            "the fleet head is not a capacity server; dial a shard",
        )


async def serve_beat(
    core: BeatCore, *, host: str = "127.0.0.1", port: int = 0
):
    """Bind the beat service. Returns (grpc.aio server, bound port)."""
    server = grpc.aio.server()
    add_capacity_servicer(server, FleetBeatServicer(core))
    bound = server.add_insecure_port(f"{host}:{port}")
    await server.start()
    return server, bound


class ShardReporter:
    """The shard-side half of the beat: sweep + summarize the
    straddling resources, report, install the returned shares.

    Runs inside the shard's server process (cmd/server.py --fleet-beat)
    with direct access to the CapacityServer — summaries never leave
    the process as anything bigger than the per-band aggregates. A
    failed report is a missed beat, not an error: the share installed
    last time keeps serving until its expiry, which is the same
    partition-drain story the in-process reconciler pins."""

    def __init__(
        self,
        server,
        shard: int,
        beat_addr: str,
        straddle,
        *,
        interval: float = 2.0,
        clock: Callable[[], float] = time.time,
    ):
        self.server = server
        self.shard = int(shard)
        self.beat_addr = beat_addr
        self.straddle = tuple(straddle)
        self.interval = float(interval)
        self._clock = clock
        self._channel = None
        self._stub = None
        self.reports = 0
        self.failures = 0
        self.installed: Dict[str, float] = {}

    def _ensure_stub(self):
        if self._stub is None:
            self._channel = grpc.aio.insecure_channel(self.beat_addr)
            self._stub = CapacityStub(self._channel)
        return self._stub

    def _build_request(self) -> Optional[pb.GetServerCapacityRequest]:
        from doorman_tpu.core.resource import algo_kind_for

        req = pb.GetServerCapacityRequest(
            server_id=shard_server_id(self.shard)
        )
        for rid in self.straddle:
            res = self.server.resources.get(rid)
            if res is None:
                # Not claimed yet on this shard: report the empty
                # summary so the head still counts us live (and the
                # zero-demand slack split reaches us).
                req.resource.add(resource_id=rid)
                continue
            res.store.clean()
            summary = summarize_resource(
                res, self.shard, kind=algo_kind_for(res.template)
            )
            req.resource.append(encode_summary(summary, rid))
        return req if len(req.resource) else None

    async def step(self) -> bool:
        """One report round. Returns True when the report landed and
        the shares were installed."""
        if not self.server.is_master:
            # A non-master candidate holds no store worth reporting;
            # its silence freezes the share, exactly as intended.
            return False
        request = self._build_request()
        if request is None:
            return False
        try:
            resp = await self._ensure_stub().GetServerCapacity(
                request, timeout=max(self.interval, 1.0)
            )
        except Exception as e:
            self.failures += 1
            log.warning(
                "shard %d beat report to %s failed: %r",
                self.shard, self.beat_addr, e,
            )
            return False
        self.reports += 1
        for r in resp.response:
            self.server.set_straddle_share(
                r.resource_id, r.gets.capacity, float(r.gets.expiry_time)
            )
            self.installed[r.resource_id] = float(r.gets.capacity)
        trace_mod.default_tracer().instant(
            "fleet.report", cat="fleet",
            args={"shard": self.shard,
                  "resources": len(resp.response)},
        )
        return True

    async def run(self) -> None:
        """The beat loop; cancel the task to stop. First report fires
        immediately — bring-up wants the bootstrap split installed
        BEFORE the front door opens (doc/federation.md corollary)."""
        while True:
            try:
                await self.step()
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception(
                    "shard %d beat step blew up; next beat retries",
                    self.shard,
                )
            await asyncio.sleep(self.interval)

    async def close(self) -> None:
        if self._channel is not None:
            await self._channel.close()
            self._channel = None
            self._stub = None

    def status(self) -> dict:
        return {
            "shard": self.shard,
            "beat_addr": self.beat_addr,
            "straddle": list(self.straddle),
            "interval": self.interval,
            "reports": self.reports,
            "failures": self.failures,
            "installed": {
                rid: round(v, 6)
                for rid, v in sorted(self.installed.items())
            },
        }
