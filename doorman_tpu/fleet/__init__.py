"""Fleet runtime: the federation made deployable.

PR 10's FederatedRoots proved the POP reconcile beat in-process with a
fixed shard count. This package is the production shape of the same
loops:

  * `epoch`      — routing epochs: versioned ShardRouter maps so the
                   shard count can change while clients are live.
  * `controller` — FleetController: the in-process fleet runtime
                   (active set, live reshard N→M, the reconcile beat
                   with drain-via-freeze), interface-compatible with
                   FederatedRoots so every harness drives it unchanged.
  * `beat`       — the wire codec (ShardSummary <-> GetServerCapacity
                   band aggregates) and BeatCore, the transport-free
                   reconcile state the RPC beat service runs on.
  * `autoscale`  — hysteresis + cool-down shard-count controller over
                   SLO verdicts.
  * `rpc`        — the gRPC beat service + per-shard reporter loop.
  * `supervisor` — spawn/monitor real `cmd.server` shard processes.
"""

from doorman_tpu.fleet.autoscale import Autoscaler
from doorman_tpu.fleet.beat import (
    BeatCore,
    decode_summary,
    encode_summary,
    parse_shard_server_id,
    shard_server_id,
)
from doorman_tpu.fleet.controller import FleetController
from doorman_tpu.fleet.epoch import EpochChange, EpochRouter

__all__ = [
    "Autoscaler",
    "BeatCore",
    "EpochChange",
    "EpochRouter",
    "FleetController",
    "decode_summary",
    "encode_summary",
    "parse_shard_server_id",
    "shard_server_id",
]
