"""FleetSupervisor: real CapacityServer shard processes under one roof.

Spawns each shard as `python -m doorman_tpu.cmd.server` (the actual
binary, not a test double) with the fleet's wiring flags: per-shard
identity (--shard i/N — election lock suffix + persist namespace, so a
later M-shard restart finds shard k's journal under the same
namespace), the shared config file, and the beat reporter
(--fleet-beat) pointed at the head. Readiness is probed with the
ordinary Discovery RPC; liveness by waitpid. Scale-out spawns a new
process; scale-in terminates one and lets the share-freeze drain do
the rest — the supervisor never copies state between shards, because
the lease machinery makes that unnecessary.
"""

from __future__ import annotations

import asyncio
import logging
import os
import signal
import socket
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import grpc

from doorman_tpu.proto import doorman_pb2 as pb
from doorman_tpu.proto.grpc_api import CapacityStub

log = logging.getLogger(__name__)

__all__ = ["FleetSupervisor", "ShardProcess", "free_port"]


def free_port() -> int:
    """An OS-granted free TCP port (bind-then-close; the tiny reuse
    race is acceptable for loopback smokes and dev fleets)."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@dataclass
class ShardProcess:
    index: int
    port: int
    proc: subprocess.Popen
    log_path: Optional[str] = None
    started_at: float = field(default_factory=time.time)

    @property
    def addr(self) -> str:
        return f"127.0.0.1:{self.port}"

    @property
    def alive(self) -> bool:
        return self.proc.poll() is None


class FleetSupervisor:
    def __init__(
        self,
        config_path: str,
        *,
        beat_addr: str = "",
        straddle: Sequence[str] = (),
        report_interval: float = 2.0,
        persist: str = "",
        mode: str = "immediate",
        minimum_refresh_interval: float = 0.0,
        log_dir: Optional[str] = None,
        extra_args: Sequence[str] = (),
        env: Optional[Dict[str, str]] = None,
    ):
        self.config_path = config_path
        self.beat_addr = beat_addr
        self.straddle = tuple(straddle)
        self.report_interval = float(report_interval)
        self.persist = persist
        self.mode = mode
        self.minimum_refresh_interval = float(minimum_refresh_interval)
        self.log_dir = log_dir
        self.extra_args = tuple(extra_args)
        self.env = dict(env) if env is not None else None
        self.shards: Dict[int, ShardProcess] = {}

    # -- lifecycle ----------------------------------------------------

    def spawn(self, index: int, n_shards: int) -> ShardProcess:
        """Start shard `index` of an `n_shards` fleet. Idempotent per
        live index (respawns a dead one in place)."""
        existing = self.shards.get(index)
        if existing is not None and existing.alive:
            return existing
        port = free_port()
        argv = [
            sys.executable, "-m", "doorman_tpu.cmd.server",
            "--host", "127.0.0.1",
            "--port", str(port),
            "--debug-port", "-1",
            "--config", f"file:{self.config_path}",
            "--mode", self.mode,
            "--shard", f"{index}/{max(n_shards, index + 1)}",
            "--minimum-refresh-interval",
            str(self.minimum_refresh_interval),
            "--jax-platform", "cpu",
        ]
        if self.beat_addr:
            argv += [
                "--fleet-beat", self.beat_addr,
                "--fleet-report-interval", str(self.report_interval),
            ]
            if self.straddle:
                argv += ["--fleet-straddle", ",".join(self.straddle)]
        if self.persist:
            argv += ["--persist", self.persist]
        argv += list(self.extra_args)
        stdout = subprocess.DEVNULL
        log_path = None
        if self.log_dir:
            os.makedirs(self.log_dir, exist_ok=True)
            log_path = os.path.join(self.log_dir, f"shard{index}.log")
            stdout = open(log_path, "ab")
        env = dict(os.environ)
        # The shard tick is host-side for fleet smokes; never let a
        # child grab an accelerator out from under the head.
        env.setdefault("JAX_PLATFORMS", "cpu")
        if self.env:
            env.update(self.env)
        proc = subprocess.Popen(
            argv, stdout=stdout, stderr=subprocess.STDOUT, env=env
        )
        if stdout is not subprocess.DEVNULL:
            stdout.close()
        sp = ShardProcess(index=index, port=port, proc=proc,
                          log_path=log_path)
        self.shards[index] = sp
        log.info("spawned shard %d pid %d on %s",
                 index, proc.pid, sp.addr)
        return sp

    async def wait_ready(
        self, index: int, *, timeout: float = 30.0
    ) -> ShardProcess:
        """Poll Discovery until the shard answers as master (trivial
        election deployments answer immediately once configured)."""
        sp = self.shards[index]
        # Bring-up of a real child process: the poll deadline is
        # wall-clock by design, outside any seeded replay.
        deadline = time.monotonic() + timeout  # doorman: allow[seeded-determinism]
        last: Optional[Exception] = None
        while time.monotonic() < deadline:  # doorman: allow[seeded-determinism]
            if not sp.alive:
                raise RuntimeError(
                    f"shard {index} exited rc={sp.proc.returncode} "
                    f"during bring-up (log: {sp.log_path})"
                )
            try:
                async with grpc.aio.insecure_channel(sp.addr) as ch:
                    out = await CapacityStub(ch).Discovery(
                        pb.DiscoveryRequest(), timeout=2.0
                    )
                if out.is_master:
                    return sp
            except Exception as e:
                last = e
            await asyncio.sleep(0.2)
        raise TimeoutError(
            f"shard {index} not ready within {timeout}s "
            f"(last error: {last!r}, log: {sp.log_path})"
        )

    def stop(self, index: int, *, grace: float = 5.0) -> None:
        """Scale-in: SIGTERM the shard and reap it. Its straddle share
        freezes at the head and drains through expiry + lease length —
        that IS the drain procedure (doc/operations.md)."""
        sp = self.shards.get(index)
        if sp is None:
            return
        if sp.alive:
            sp.proc.send_signal(signal.SIGTERM)
            try:
                sp.proc.wait(timeout=grace)
            except subprocess.TimeoutExpired:
                sp.proc.kill()
                sp.proc.wait(timeout=grace)
        log.info("stopped shard %d rc=%s", index, sp.proc.returncode)

    def stop_all(self) -> None:
        for index in sorted(self.shards, reverse=True):
            self.stop(index)

    # -- observation --------------------------------------------------

    def addrs(self) -> Dict[int, str]:
        return {i: sp.addr for i, sp in self.shards.items() if sp.alive}

    def status(self) -> dict:
        return {
            "config": self.config_path,
            "beat": self.beat_addr,
            "shards": {
                i: {
                    "addr": sp.addr,
                    "pid": sp.proc.pid,
                    "alive": sp.alive,
                    "rc": sp.proc.returncode,
                    "log": sp.log_path,
                }
                for i, sp in sorted(self.shards.items())
            },
        }
