"""The reconcile beat's wire form.

doc/federation.md promised it: a wire deployment runs the POP
reconciliation step over the EXISTING RPC surface. Each shard reports
its straddle summary as a `GetServerCapacity` — the compact per-band
aggregate shape the protocol already carries, never per-client rows —
and receives its share back as the response lease, expiry included.
This module is that promise made concrete:

  * the codec: ShardSummary <-> ServerCapacityResourceRequest. One
    PriorityBandAggregate per demand-curve breakpoint (priority is the
    breakpoint index, num_clients the aggregated weight, wants the
    aggregated wants), `has` carries the shard's granted sum. O(distinct
    ratios) on the wire, exactly like the in-process summary.
  * `shard_server_id` / `parse_shard_server_id`: the server_id
    convention ("fleet-shard-<k>") that marks a GetServerCapacity as a
    beat report and names the reporting shard.
  * BeatCore: the transport-free beat state for the PUSH deployment —
    each report folds into the per-resource reconciler together with
    the other shards' last-known summaries; shards that have not
    reported within `stale_after` count as unreachable, so their shares
    freeze and drain exactly as a partition does in-process.

Breakpoint ratios are recomputed on decode as Σwants/Σweight — exact
whenever a breakpoint's clients share a representable wants/weight
quotient (they share the exact ratio by construction; integer weights
keep the round-trip lossless), and within 1 ulp otherwise, which the
level comparisons tolerate by the same argument as the local solves.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Iterable, Optional, Set, Tuple

from doorman_tpu.federation.reconcile import (
    ShardSummary,
    StraddleReconciler,
)
from doorman_tpu.proto import doorman_pb2 as pb

__all__ = [
    "BeatCore",
    "SHARD_REPORT_PREFIX",
    "decode_summary",
    "encode_summary",
    "parse_shard_server_id",
    "shard_server_id",
]

SHARD_REPORT_PREFIX = "fleet-shard-"


def shard_server_id(shard: int) -> str:
    """The server_id a shard reports under — what marks the RPC as a
    beat report rather than an intermediate's aggregation."""
    return f"{SHARD_REPORT_PREFIX}{int(shard)}"


def parse_shard_server_id(server_id: str) -> Optional[int]:
    """Inverse of shard_server_id; None for ordinary server ids."""
    if not server_id.startswith(SHARD_REPORT_PREFIX):
        return None
    try:
        return int(server_id[len(SHARD_REPORT_PREFIX):])
    except ValueError:
        return None


def encode_summary(
    summary: ShardSummary, resource_id: str
) -> pb.ServerCapacityResourceRequest:
    """ShardSummary -> the wire aggregate. priority indexes the
    breakpoint (the curve is sorted by ratio, so the index IS the
    order), num_clients carries the aggregated weight, wants the
    aggregated wants; `has` reports the shard's granted sum."""
    req = pb.ServerCapacityResourceRequest(resource_id=resource_id)
    req.has.capacity = float(summary.has)
    for i, (_ratio, wants, weight) in enumerate(summary.breakpoints):
        req.wants.add(
            priority=i,
            num_clients=int(round(weight)),
            wants=float(wants),
        )
    return req


def decode_summary(
    req: pb.ServerCapacityResourceRequest, shard: int
) -> ShardSummary:
    """Wire aggregate -> ShardSummary. Ratios are recomputed from the
    aggregated sums (see module docstring for the exactness bound);
    bands arrive breakpoint-ordered but are re-sorted defensively —
    the curve's invariant, not the sender's, is what the fill math
    needs."""
    breakpoints = []
    wants_sum = 0.0
    weight_sum = 0.0
    for band in req.wants:
        weight = float(band.num_clients) or 1.0
        wants = float(band.wants)
        breakpoints.append((wants / weight, wants, weight))
        wants_sum += wants
        weight_sum += weight
    breakpoints.sort(key=lambda b: b[0])
    return ShardSummary(
        shard=int(shard),
        wants=wants_sum,
        has=float(req.has.capacity),
        weight=weight_sum,
        breakpoints=tuple(breakpoints),
    )


class BeatCore:
    """Push-mode beat state: one StraddleReconciler per straddling
    resource, fed one shard report at a time.

    The pull deployment (FleetController) sweeps every shard in one
    step, so Σ installed shares ≤ capacity holds within a single beat.
    Push-mode installs are staggered — each shard's share lands when
    ITS report arrives — so the pointwise bound holds at report-round
    granularity: every fresh shard re-reports within `stale_after`, a
    silent shard freezes at its last share, and the frozen window
    (share expiry + lease length) covers every grant issued under a
    stale share, exactly the in-process drain argument.

    `template(rid)` supplies (capacity, kind, lease_length) for a
    straddling resource — the fleet head reads it from the same config
    file the shards serve, the one copy of truth the whole straddle
    answers to."""

    def __init__(
        self,
        template: Callable[[str], Optional[Tuple[float, int, float]]],
        *,
        expected: Iterable[int],
        share_ttl: float = 10.0,
        stale_after: Optional[float] = None,
        clock: Callable[[], float] = time.time,
    ):
        self._template = template
        self.expected: Set[int] = set(int(s) for s in expected)
        self.share_ttl = float(share_ttl)
        # A shard is presumed partitioned after 2 missed report
        # intervals unless the caller says otherwise.
        self.stale_after = (
            float(stale_after) if stale_after is not None
            else 2.0 * self.share_ttl
        )
        self._clock = clock
        self._reconcilers: Dict[str, StraddleReconciler] = {}
        # Last fresh report per (resource, shard) — the push analog of
        # the pull sweep's summaries dict.
        self._reports: Dict[str, Dict[int, Tuple[ShardSummary, float]]] = {}
        self.reports = 0

    def set_expected(self, expected: Iterable[int]) -> None:
        """Reshard seam: the active set changed. Departed shards stop
        being expected, so their silence is drain, not partition alarm
        — either way the freeze covers them."""
        self.expected = set(int(s) for s in expected)

    def _reconciler(self, rid: str) -> Optional[StraddleReconciler]:
        rec = self._reconcilers.get(rid)
        if rec is not None:
            return rec
        tpl = self._template(rid)
        if tpl is None:
            return None
        capacity, kind, lease_length = tpl
        rec = StraddleReconciler(
            rid,
            float(capacity),
            int(kind),
            share_ttl=self.share_ttl,
            lease_length=float(lease_length),
        )
        self._reconcilers[rid] = rec
        return rec

    def offer(
        self, shard: int, rid: str, summary: ShardSummary
    ) -> Optional[Tuple[float, float]]:
        """Fold one shard's report in and compute its share. Returns
        (share, expiry) to send back as the response lease, or None
        when the resource has no reconciler (not straddling / no
        template)."""
        rec = self._reconciler(rid)
        if rec is None:
            return None
        now = self._clock()
        self.reports += 1
        reports = self._reports.setdefault(rid, {})
        reports[int(shard)] = (summary, now)
        fresh: Dict[int, ShardSummary] = {}
        for s, (summ, at) in list(reports.items()):
            if s != int(shard) and now - at > self.stale_after:
                continue
            if s in self.expected or s == int(shard):
                fresh[s] = summ
        unreachable = self.expected - set(fresh)
        shares = rec.reconcile(fresh, now, unreachable=unreachable)
        value = shares.get(int(shard))
        if value is None:
            return None
        return float(value), now + rec.share_ttl

    def straddle_capacities(self) -> Dict[str, float]:
        return {
            rid: rec.capacity for rid, rec in self._reconcilers.items()
        }

    def has_sums(self) -> Dict[str, float]:
        """Σ reported grants per resource over every shard's LAST
        report — stale reports included, because a silent shard's
        grants still exist until they drain. This is the wire-plane
        reading of the fed_capacity_sum invariant (the smoke asserts
        it against straddle_capacities every beat round)."""
        return {
            rid: sum(s.has for (s, _at) in reports.values())
            for rid, reports in self._reports.items()
        }

    def status(self) -> dict:
        now = self._clock()
        return {
            "expected": sorted(self.expected),
            "share_ttl": self.share_ttl,
            "stale_after": self.stale_after,
            "reports": self.reports,
            "resources": {
                rid: {
                    "reconciler": rec.status(),
                    "last_report": {
                        s: round(now - at, 3)
                        for s, (_summ, at) in sorted(
                            self._reports.get(rid, {}).items()
                        )
                    },
                }
                for rid, rec in sorted(self._reconcilers.items())
            },
        }
