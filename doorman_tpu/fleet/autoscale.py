"""SLO-driven elastic shard count: hysteresis + cool-down.

The SLO engine (obs/slo.py) already renders the fleet's health as
verdict dicts — tick budget, goodput, satisfaction — so the autoscaler
is deliberately small: it turns a STREAK of same-signed verdicts into
one shard-count step, and then refuses to move again until the
cool-down lapses. Both guards exist because verdict noise is real
(a single stressed tick fails a gate; a single quiet tick passes with
huge margin) and a fleet that flaps 2→3→2→3 pays the reshard drain
window each way while delivering nothing.

Signals:

  * GROW when any watched verdict FAILS (the fleet is missing an
    objective — more shards is the lever this controller owns);
  * SHRINK when every watched verdict passes with at least
    `shrink_margin` headroom (margin is the engine's absolute
    headroom: target - observed for "max" gates, observed - target
    for "min");
  * HOLD otherwise, and any signal flip resets the streak.

`observe()` returns the target shard count when a step fires, else
None; the caller (workload autoscale generator, cmd.fleet loop) owns
actually calling FleetController.reshard.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

__all__ = ["Autoscaler"]


class Autoscaler:
    def __init__(
        self,
        *,
        min_shards: int,
        max_shards: int,
        step: int = 1,
        hysteresis: int = 3,
        cooldown: int = 6,
        shrink_margin: float = 0.0,
    ):
        if not 1 <= min_shards <= max_shards:
            raise ValueError(
                f"bounds [{min_shards}, {max_shards}] are not a range"
            )
        self.min_shards = int(min_shards)
        self.max_shards = int(max_shards)
        self.step = int(step)
        self.hysteresis = int(hysteresis)
        self.cooldown = int(cooldown)
        self.shrink_margin = float(shrink_margin)
        # Signed streak: positive = consecutive grow signals, negative
        # = consecutive shrink signals.
        self._streak = 0
        self._last_change: Optional[int] = None
        self.decisions: List[dict] = []

    def _signal(self, verdicts: Sequence[dict]) -> int:
        scored = [v for v in verdicts if v.get("status") != "no_data"]
        if not scored:
            return 0
        if any(v.get("status") == "fail" for v in scored):
            return 1
        if all(
            (v.get("margin") or 0.0) >= self.shrink_margin
            for v in scored
        ):
            return -1
        return 0

    def observe(
        self, tick: int, verdicts: Sequence[dict], current: int
    ) -> Optional[int]:
        """Fold one tick's verdicts in. Returns the new target shard
        count when hysteresis + cool-down + bounds all clear, else
        None."""
        signal = self._signal(verdicts)
        if signal == 0 or (signal > 0) != (self._streak > 0):
            self._streak = signal
        else:
            self._streak += signal
        if abs(self._streak) < self.hysteresis:
            return None
        if (
            self._last_change is not None
            and tick - self._last_change < self.cooldown
        ):
            return None
        target = current + self.step * (1 if self._streak > 0 else -1)
        target = max(self.min_shards, min(self.max_shards, target))
        if target == current:
            return None
        self._last_change = tick
        reason = "grow:fail-streak" if self._streak > 0 else (
            "shrink:margin-streak"
        )
        self._streak = 0
        self.decisions.append(
            {"tick": tick, "from": current, "to": target,
             "reason": reason}
        )
        return target

    def status(self) -> dict:
        return {
            "bounds": [self.min_shards, self.max_shards],
            "hysteresis": self.hysteresis,
            "cooldown": self.cooldown,
            "shrink_margin": self.shrink_margin,
            "streak": self._streak,
            "last_change": self._last_change,
            "decisions": list(self.decisions),
        }
