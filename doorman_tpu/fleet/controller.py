"""FleetController: the in-process fleet runtime.

Interface-compatible with federation.FederatedRoots (`reconcile_once`,
`straddle_capacities`, `blocked`, `status`) so the chaos and workload
harnesses drive a fleet exactly like a fixed federation — plus the two
things FederatedRoots cannot do:

  * an ACTIVE SET smaller than the provisioned server pool, changed
    live by `reshard(m)` (routing epochs, fleet/epoch.py);
  * drain semantics on shrink that reuse the reconciler's frozen-share
    machinery verbatim: a shard leaving the active set simply stops
    appearing in the beat's summaries, so its last share freezes
    (charged against the pool), decays at expiry, and its slack is
    re-offered only after expiry + lease_length — identical to how a
    partitioned shard drains, because shrink IS a deliberate partition.

Reshard mechanics per resource class:

  * straddling — nothing moves; the next beat sees the new live set
    and re-splits the shares (grow: the new shard enters with an empty
    summary and receives an even slack split; shrink: the departed
    shard freezes and drains as above). Σ shares ≤ capacity holds
    pointwise through both directions because every install lands in
    one beat and the frozen window covers stragglers.
  * ordinary (hash-routed) — owners change only where the stable hash
    changes (EpochChange.moved). The old owner gets an epoch-stamped
    redirect table (CapacityServer.set_fleet_routing) so stale clients
    chase to the new owner at RPC speed; the old owner's rows drain by
    lease expiry (the client stops renewing there) and the new owner's
    learning-mode warm-up carries each client's reported `has` across
    the move, so grants are lease-continuous and never double-issued
    to one client.

The wall-clock deployment (fleet/rpc.py + fleet/supervisor.py) runs the
same decisions over GetServerCapacity; this class is the deterministic
twin the acceptance tests pin.
"""

from __future__ import annotations

import logging
import time
from typing import Callable, Dict, Iterable, Mapping, Optional, Set

from doorman_tpu.core.resource import algo_kind_for
from doorman_tpu.federation.reconcile import (
    ShardSummary,
    StraddleReconciler,
    summarize_resource,
)
from doorman_tpu.fleet.epoch import EpochChange, EpochRouter
from doorman_tpu.obs import trace as trace_mod
from doorman_tpu.server import config as config_mod

log = logging.getLogger(__name__)

DEFAULT_SHARE_TTL = 10.0

__all__ = ["FleetController", "DEFAULT_SHARE_TTL"]


class FleetController:
    """Coordinator over a provisioned pool {shard -> CapacityServer},
    of which the first `active` are serving. `addrs` maps shard index
    to the address clients dial (the redirect targets); omit it in
    harnesses that never exercise server-side redirects."""

    def __init__(
        self,
        servers: Dict[int, object],
        *,
        straddle: Iterable[str] = (),
        overrides: Optional[Mapping[str, int]] = None,
        active: Optional[int] = None,
        addrs: Optional[Mapping[int, str]] = None,
        share_ttl: float = DEFAULT_SHARE_TTL,
        clock: Callable[[], float] = time.time,
    ):
        if set(servers) != set(range(len(servers))):
            raise ValueError(
                f"servers {sorted(servers)} are not a dense pool "
                f"[0, {len(servers)})"
            )
        n_active = len(servers) if active is None else int(active)
        if not 1 <= n_active <= len(servers):
            raise ValueError(
                f"active {n_active} outside [1, {len(servers)}] "
                "(provisioned pool)"
            )
        self.servers = dict(servers)
        self.addrs: Dict[int, str] = dict(addrs or {})
        self.epochs = EpochRouter(
            n_active, straddle=straddle, overrides=overrides
        )
        self.share_ttl = float(share_ttl)
        self._clock = clock
        # Partition seam, same contract as FederatedRoots.blocked.
        self.blocked: Set[int] = set()
        self._reconcilers: Dict[str, StraddleReconciler] = {}
        self.beats = 0
        self.reshards = 0

    # -- routing ------------------------------------------------------

    @property
    def router(self):
        return self.epochs.router

    @property
    def epoch(self) -> int:
        return self.epochs.epoch

    @property
    def active(self) -> int:
        return self.epochs.n_shards

    @property
    def provisioned(self) -> int:
        return len(self.servers)

    def note_resources(self, resource_ids: Iterable[str]) -> None:
        self.epochs.note_resources(resource_ids)

    # -- live resharding ----------------------------------------------

    def reshard(self, n_shards: int) -> EpochChange:
        """Publish a new epoch serving `n_shards` of the provisioned
        pool. Grow and shrink are both live: nothing restarts, no store
        rows are copied — routing changes and the lease machinery
        drains the rest."""
        n_shards = int(n_shards)
        if not 1 <= n_shards <= self.provisioned:
            raise ValueError(
                f"reshard to {n_shards} outside provisioned pool "
                f"[1, {self.provisioned}]"
            )
        change = self.epochs.advance(n_shards)
        self.reshards += 1
        self._push_routing(change)
        trace_mod.default_tracer().instant(
            "fleet.epoch", cat="fleet", args=change.as_log()
        )
        return change

    def _push_routing(self, change: EpochChange) -> None:
        """Install epoch-stamped redirect tables: every server learns
        where every tracked resource it does NOT own now lives, so a
        stale-epoch client's next refresh gets a mastership redirect
        to the new owner instead of a silently wrong answer. The table
        is computed from the FULL tracked set under the new router and
        replaces the previous epoch's — a resource that moved back
        simply drops out."""
        router = self.router
        owners = {
            rid: router.shard_of(rid)
            for rid in self.epochs.tracked
            if not router.is_straddling(rid)
        }
        for shard, server in self.servers.items():
            routed_away = {
                rid: self.addrs.get(owner, "")
                for rid, owner in owners.items()
                if owner != shard
            }
            install = getattr(server, "set_fleet_routing", None)
            if install is not None:
                install(change.epoch, routed_away)

    # -- the reconcile beat -------------------------------------------

    def _reconciler(self, resource_id: str) -> Optional[StraddleReconciler]:
        rec = self._reconcilers.get(resource_id)
        if rec is not None:
            return rec
        # Home shard's template first (the one copy of config the
        # straddle answers to), any configured active shard as the
        # fallback — a freshly-activated shard may still be loading.
        home = self.router.shard_of(resource_id)
        order = [home] + [s for s in range(self.active) if s != home]
        tpl = None
        for shard in order:
            server = self.servers[shard]
            if server.config is None:
                continue
            tpl = config_mod.find_template(server.config, resource_id)
            if tpl is not None:
                break
        if tpl is None:
            return None
        rec = StraddleReconciler(
            resource_id,
            float(tpl.capacity),
            algo_kind_for(tpl),
            share_ttl=self.share_ttl,
            lease_length=float(tpl.algorithm.lease_length),
        )
        self._reconcilers[resource_id] = rec
        return rec

    def reconcile_once(self) -> dict:
        """One beat over every straddling resource, scoped to the
        ACTIVE shards. A shard outside the active set is simply absent
        from the summaries — the reconciler freezes its last share and
        drains it exactly like a partition, which is the shrink story.
        Returns {resource_id: {shard: installed share}}."""
        self.beats += 1
        now = self._clock()
        installed: Dict[str, Dict[int, float]] = {}
        with trace_mod.default_tracer().span(
            "fleet.beat", cat="fleet",
            args={"epoch": self.epoch, "active": self.active,
                  "blocked": len(self.blocked)},
        ):
            for rid in sorted(self.router.straddle):
                rec = self._reconciler(rid)
                if rec is None:
                    continue
                summaries: Dict[int, ShardSummary] = {}
                unreachable = {s for s in self.blocked if s < self.active}
                for shard in range(self.active):
                    if shard in unreachable:
                        continue
                    server = self.servers[shard]
                    if not server.is_master:
                        unreachable.add(shard)
                        continue
                    res = server.resources.get(rid)
                    if res is not None:
                        res.store.clean()
                        summaries[shard] = summarize_resource(
                            res, shard, kind=rec.kind
                        )
                    else:
                        summaries[shard] = ShardSummary(shard=shard)
                shares = rec.reconcile(
                    summaries, now, unreachable=unreachable
                )
                for shard, value in shares.items():
                    self.servers[shard].set_straddle_share(
                        rid, value, now + self.share_ttl
                    )
                installed[rid] = shares
        return installed

    def straddle_capacities(self) -> Dict[str, float]:
        """{resource_id: configured capacity} — the capacity-sum
        invariant's bound, summed by chaos.invariants.check_federation
        over EVERY provisioned shard so draining shards stay covered."""
        return {
            rid: rec.capacity for rid, rec in self._reconcilers.items()
        }

    def status(self) -> dict:
        return {
            "epochs": self.epochs.status(),
            "active": self.active,
            "provisioned": self.provisioned,
            "share_ttl": self.share_ttl,
            "beats": self.beats,
            "reshards": self.reshards,
            "blocked": sorted(self.blocked),
            "straddle": {
                rid: rec.status()
                for rid, rec in sorted(self._reconcilers.items())
            },
        }
