"""Routing epochs: versioned shard maps for live resharding N→M.

The ShardRouter is a pure function of (resource id, shard count), so a
shard-count change is a ROUTING change: every resource whose
`stable_shard(rid, N) != stable_shard(rid, M)` has a new owner, and
everything else stays put (that locality is the point of the stable
hash — an N→N+1 move touches ~1/(N+1) of the space, not all of it).
The epoch number versions the map: servers stamp their redirect tables
with it, clients apply it to move exactly the affected routes, and the
flight recorder logs it so an operator can line a grant wiggle up with
the reshard that caused it.

Straddling resources never "move": they are served by every active
shard, so a reshard re-splits their shares (the reconciler sees the new
live set on the next beat) rather than rerouting them. Overrides pin a
resource to a fixed shard across epochs; an override pointing past the
new shard count is a configuration error and fails the advance.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterable, List, Mapping, Optional, Tuple

from doorman_tpu.federation.router import ShardRouter

__all__ = ["EpochChange", "EpochRouter"]


@dataclass(frozen=True)
class EpochChange:
    """One published reshard: the epoch it created and the diff the
    fleet must act on."""

    epoch: int
    n_from: int
    n_to: int
    # Shards entering / leaving the active set.
    added: Tuple[int, ...]
    removed: Tuple[int, ...]
    # Known (tracked) non-straddling resources whose owner changed,
    # with their old and new owners — the redirect/drain worklist.
    moved: Tuple[Tuple[str, int, int], ...]

    def as_log(self) -> dict:
        return {
            "epoch": self.epoch,
            "from": self.n_from,
            "to": self.n_to,
            "added": list(self.added),
            "removed": list(self.removed),
            "moved": [[rid, old, new] for rid, old, new in self.moved],
        }


class EpochRouter:
    """A ShardRouter with a version number and an advance() that
    computes the move diff.

    The moved-resource diff is computed over the TRACKED resource set
    (`note_resources`): the router itself is a hash and needs no
    enumeration, but redirect tables and drain verification do — the
    fleet feeds it every resource id it has seen (config templates,
    claimed resources), which is exactly the set a diff could matter
    for."""

    def __init__(
        self,
        n_shards: int,
        *,
        straddle: Iterable[str] = (),
        overrides: Optional[Mapping[str, int]] = None,
        resources: Iterable[str] = (),
    ):
        self.straddle = tuple(sorted(set(straddle)))
        self.overrides: Dict[str, int] = dict(overrides or {})
        self.epoch = 0
        self.router = ShardRouter(
            n_shards,
            straddle=self.straddle,
            overrides=self.overrides or None,
        )
        self._tracked: List[str] = []
        self._tracked_set = set()
        self.note_resources(self.straddle)
        self.note_resources(resources)

    @property
    def n_shards(self) -> int:
        return self.router.n_shards

    @property
    def tracked(self) -> Tuple[str, ...]:
        """Every resource id the diff covers, in first-seen order."""
        return tuple(self._tracked)

    def note_resources(self, resource_ids: Iterable[str]) -> None:
        """Track resource ids for the advance() move diff (idempotent,
        order-stable)."""
        for rid in resource_ids:
            if rid not in self._tracked_set:
                self._tracked_set.add(rid)
                self._tracked.append(rid)

    def advance(self, n_shards: int) -> EpochChange:
        """Publish a new epoch routing to `n_shards` shards. Returns
        the change record; raises on a no-op or an override stranded
        outside the new range (ShardRouter validates)."""
        n_shards = int(n_shards)
        if n_shards == self.router.n_shards:
            raise ValueError(
                f"reshard to current shard count {n_shards} is a no-op"
            )
        old = self.router
        new = ShardRouter(
            n_shards,
            straddle=self.straddle,
            overrides=self.overrides or None,
        )
        moved = tuple(
            (rid, old.shard_of(rid), new.shard_of(rid))
            for rid in sorted(self._tracked)
            if not old.is_straddling(rid)
            and old.shard_of(rid) != new.shard_of(rid)
        )
        grow = n_shards > old.n_shards
        self.router = new
        self.epoch += 1
        return EpochChange(
            epoch=self.epoch,
            n_from=old.n_shards,
            n_to=n_shards,
            added=tuple(range(old.n_shards, n_shards)) if grow else (),
            removed=tuple(range(n_shards, old.n_shards)) if not grow else (),
            moved=moved,
        )

    def status(self) -> dict:
        return {
            "epoch": self.epoch,
            "router": self.router.status(),
            "tracked": len(self._tracked),
        }
