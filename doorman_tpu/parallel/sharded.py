"""Client-axis sharded solve: the reference's server tree fused on-chip.

The edge list shards across devices along a mesh axis; each device computes
partial per-resource aggregates over its shard and the totals are combined
with psum over the mesh (ICI) — exactly the aggregation an intermediate
doorman server performs over its clients before asking the root
(reference server.go:227-261, doorman.proto PriorityBandAggregate). Every
device then computes final grants for its own edges from the replicated
totals; no further communication is needed.

With a two-axis mesh ("dc", "clients") the psum runs over both axes — the
partial-sum-within-dc / combine-across-dc structure is the two-level tree
of BASELINE.json config 4; `dc_aggregates` exposes the per-dc partials
(the intermediate servers' band tables) for observability.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from doorman_tpu.parallel.compat import shard_map

from doorman_tpu.solver.kernels import EdgeBatch, ResourceBatch, solve_edges


def _psum_reduce(local_reduce, axis_names):
    def reduce_fn(values):
        return jax.lax.psum(local_reduce(values), axis_names)

    return reduce_fn


def _psum_max(local_reduce, axis_names):
    def reduce_fn(values):
        return jax.lax.pmax(local_reduce(values), axis_names)

    return reduce_fn


def make_sharded_solver(mesh: Mesh, *, donate: bool = False):
    """Build a jitted solve(edges, resources) -> gets running under
    shard_map over `mesh`: edge arrays sharded over all mesh axes, resource
    arrays replicated, per-resource totals combined with psum/pmax."""
    axes = tuple(mesh.axis_names)
    edge_spec = P(axes)  # edge axis sharded over every mesh axis
    rep = P()

    def shard_fn(rid, wants, has, sub, active, cap, kind, learning, static_cap):
        from doorman_tpu.solver.fairshare import (
            local_segment_max,
            local_segment_sum,
        )

        R = cap.shape[0]
        edges = EdgeBatch(
            resource=rid, wants=wants, has=has, subclients=sub, active=active
        )
        resources = ResourceBatch(
            capacity=cap, algo_kind=kind, learning=learning,
            static_capacity=static_cap,
        )
        segsum = _psum_reduce(local_segment_sum(rid, R), axes)
        segmax = _psum_max(local_segment_max(rid, R), axes)
        return solve_edges(edges, resources, segsum, segmax)

    mapped = shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(
            edge_spec, edge_spec, edge_spec, edge_spec, edge_spec,
            rep, rep, rep, rep,
        ),
        out_specs=edge_spec,
    )

    # Donate only the per-tick edge arrays; the replicated resource config
    # is reused across ticks.
    @partial(jax.jit, donate_argnums=(0,) if donate else ())
    def solve(edges: EdgeBatch, resources: ResourceBatch) -> jax.Array:
        return mapped(
            edges.resource, edges.wants, edges.has, edges.subclients,
            edges.active,
            resources.capacity, resources.algo_kind, resources.learning,
            resources.static_capacity,
        )

    return solve


def make_sharded_dense_solver(mesh: Mesh, *, donate: bool = False):
    """Resource-axis sharded dense solve: the [R, K] bucket tables shard
    their row axis across every mesh axis. Rows are independent (each row
    is one resource's clients), so unlike the edge path this needs NO
    collectives — pure scale-out of the TPU-optimal layout; grants land
    sharded the same way. Place inputs with `shard_dense` (which also
    pads R up to the device count).

    With donate=True the four per-tick [R, K] demand tables are donated;
    the per-resource config arrays are reused across ticks."""
    from doorman_tpu.solver.dense import DenseBatch, solve_dense

    axes = tuple(mesh.axis_names)
    row = P(axes)
    rowk = P(axes, None)

    def shard_fn(wants, has, sub, active, cap, kind, learning, static_cap):
        return solve_dense(
            DenseBatch(
                wants=wants, has=has, subclients=sub, active=active,
                capacity=cap, algo_kind=kind, learning=learning,
                static_capacity=static_cap,
            )
        )

    mapped = shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(rowk, rowk, rowk, rowk, row, row, row, row),
        out_specs=rowk,
    )

    @partial(jax.jit, donate_argnums=tuple(range(4)) if donate else ())
    def solve_parts(
        wants, has, subclients, active, capacity, algo_kind, learning,
        static_capacity,
    ) -> jax.Array:
        return mapped(
            wants, has, subclients, active,
            capacity, algo_kind, learning, static_capacity,
        )

    def solve(batch) -> jax.Array:
        return solve_parts(
            batch.wants, batch.has, batch.subclients, batch.active,
            batch.capacity, batch.algo_kind, batch.learning,
            batch.static_capacity,
        )

    return solve


def make_sharded_chunked_solver(mesh: Mesh, *, donate: bool = False):
    """Chunk-row sharded WIDE-resource solve: the chunked layout
    (solver.dense.ChunkedDenseBatch — a resource spans consecutive
    [row, K] chunks) with the row axis sharded over every mesh axis.
    Unlike the narrow dense solve, a wide resource's chunks SPAN
    devices, so per-segment totals are the two-level reduction's local
    half (row reduction + local sorted segment_sum) combined with one
    [S]-sized psum over ICI — the same aggregation the host-side server
    tree performs, fused on-chip. This is the scale-out story for
    doorman's headline shape: one shared resource with more clients
    than one chip comfortably holds. Place inputs with
    `shard_chunked`."""
    from doorman_tpu.solver.dense import chunked_reduces
    from doorman_tpu.solver.lanes import solve_lanes

    axes = tuple(mesh.axis_names)
    row = P(axes)
    rowk = P(axes, None)
    rep = P()

    def shard_fn(wants, has, sub, active, row_seg, cap, kind, learning,
                 static_cap):
        local_sum, local_max = chunked_reduces(row_seg, cap.shape[0])
        return solve_lanes(
            wants, has, sub, active, cap, kind, learning, static_cap,
            segsum=_psum_reduce(local_sum, axes),
            segmax=_psum_max(local_max, axes),
            expand=lambda totals: totals[row_seg][:, None],
        )

    mapped = shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(rowk, rowk, rowk, rowk, row, rep, rep, rep, rep),
        out_specs=rowk,
    )

    @partial(jax.jit, donate_argnums=tuple(range(4)) if donate else ())
    def solve_parts(wants, has, subclients, active, row_seg, capacity,
                    algo_kind, learning, static_capacity):
        return mapped(
            wants, has, subclients, active, row_seg,
            capacity, algo_kind, learning, static_capacity,
        )

    def solve(batch) -> jax.Array:
        return solve_parts(
            batch.wants, batch.has, batch.subclients, batch.active,
            batch.row_seg, batch.capacity, batch.algo_kind,
            batch.learning, batch.static_capacity,
        )

    return solve


def resident_chunk_reduces(
    mesh: Mesh,
    row_seg,
    num_segments: int,
    rows_per_shard: int,
):
    """Cross-shard chunk reduction for the MESH-RESIDENT wide tick
    (solver.resident_wide with mesh=): the shard-local halves of
    solve_chunked's two-level reduction combined over ICI, like
    make_sharded_chunked_solver — but assembled so totals come out
    BIT-IDENTICAL to the single-device solve.

    make_sharded_chunked_solver psums per-shard [S] partial totals,
    which re-associates the float sum of any resource whose chunks
    straddle a shard boundary (fine for a stateless solve pinned by
    allclose tests; not for the resident path, whose store-parity
    invariant is byte equality with the single-device tick).  Here each
    shard instead contributes its per-row reductions into the GLOBAL
    [R] row vector at its own offset; the psum adds disjoint supports
    (every other shard holds the identity — exact), so the assembled
    vector is bitwise the single-device row-total vector, and every
    shard then runs the SAME sorted segment op over it.  Straddling
    chunks need no special case — their rows assemble from two shards.
    Traffic: one [R]-sized psum/pmax per reduce call (the [S] variant's
    collective is smaller, but R is only ~#clients/W).

    Returns (segsum, segmax) taking the shard-local [Rl, W] lease block
    and returning replicated [S] totals — plug into solve_lanes with
    expand=totals[row_seg_local][:, None].
    """
    axes = tuple(mesh.axis_names)
    shape = dict(mesh.shape)
    row_seg = jnp.asarray(np.asarray(row_seg), jnp.int32)
    R = int(row_seg.shape[0])

    def shard_base():
        # Linear shard index in mesh-axis order -> global row offset.
        idx = jnp.zeros((), jnp.int32)
        for ax in axes:
            idx = idx * shape[ax] + jax.lax.axis_index(ax)
        return idx * rows_per_shard

    def assemble(local, fill, combine):
        rows = jnp.full((R,), fill, local.dtype)
        rows = jax.lax.dynamic_update_slice(rows, local, (shard_base(),))
        return combine(rows, axes)

    def segsum(v):
        rows = assemble(v.sum(axis=1), 0, jax.lax.psum)
        return jax.ops.segment_sum(
            rows, row_seg, num_segments=num_segments,
            indices_are_sorted=True,
        )

    def segmax(v):
        rows = assemble(v.max(axis=1), -jnp.inf, jax.lax.pmax)
        return jax.ops.segment_max(
            rows, row_seg, num_segments=num_segments,
            indices_are_sorted=True,
        )

    return segsum, segmax


def scoped_chunk_reduces(
    mesh: Mesh,
    gpos,
    row_seg_compact,
    num_compact_rows: int,
    num_segments: int,
):
    """The SCOPED variant of resident_chunk_reduces: the psum/pmax
    collective is restricted to the scoped chunks (the churn-
    proportional wide tick, solver.resident_wide scoped mode).

    Where the full reduce assembles every shard's per-row totals into
    the global [R] row vector, the scoped reduce assembles each shard's
    COMPACT per-row totals into the global compact row vector [Cbg] at
    the host-computed global compact positions `gpos` (traced int32,
    one per local compact slot; padding slots carry the out-of-range
    index Cbg and drop). The supports stay disjoint — every global
    compact position is owned by exactly one shard, every other shard
    contributes the combine identity — so the psum/pmax is exact, and
    the segment op runs over the compact row->segment map in global
    row order: the partial sums of a straddling segment add in exactly
    the full reduce's order, which keeps scoped totals bit-identical
    to the full-table reduce for every scoped segment. Traffic: one
    [Cbg]-sized collective per reduce call instead of [R] — the psum
    now scales with churn, not table size.

    Call INSIDE the shard_mapped body with the traced per-shard
    `gpos` / replicated `row_seg_compact` slices. Returns (segsum,
    segmax) taking the shard-local compact [Cbl, W] lease block and
    returning replicated [num_segments] totals.
    """
    axes = tuple(mesh.axis_names)

    def assemble(local, fill, combine):
        rows = jnp.full((num_compact_rows,), fill, local.dtype)
        rows = rows.at[gpos].set(local, mode="drop")
        return combine(rows, axes)

    def segsum(v):
        rows = assemble(v.sum(axis=1), 0, jax.lax.psum)
        return jax.ops.segment_sum(
            rows, row_seg_compact, num_segments=num_segments,
            indices_are_sorted=True,
        )

    def segmax(v):
        rows = assemble(v.max(axis=1), -jnp.inf, jax.lax.pmax)
        return jax.ops.segment_max(
            rows, row_seg_compact, num_segments=num_segments,
            indices_are_sorted=True,
        )

    return segsum, segmax


def shard_chunked(mesh: Mesh, batch):
    """Place a ChunkedDenseBatch on the mesh: chunk rows (and row_seg)
    sharded over all mesh axes, padded with inactive rows mapped to the
    LAST segment (the caller's padding segment) so per-shard row_seg
    stays sorted; the per-segment config arrays are replicated."""
    from doorman_tpu.solver.dense import ChunkedDenseBatch

    put = _row_placer(mesh, int(np.asarray(batch.row_seg).shape[0]))
    pad_seg = int(np.asarray(batch.capacity).shape[0]) - 1
    return ChunkedDenseBatch(
        wants=put(batch.wants),
        has=put(batch.has),
        subclients=put(batch.subclients),
        active=put(batch.active),
        row_seg=put(batch.row_seg, fill=pad_seg),
        capacity=put(batch.capacity, sharded_rows=False),
        algo_kind=put(batch.algo_kind, sharded_rows=False),
        learning=put(batch.learning, sharded_rows=False),
        static_capacity=put(batch.static_capacity, sharded_rows=False),
    )


def make_sharded_priority_solver(
    mesh: Mesh, num_bands: int = 4, *, donate: bool = False
):
    """Resource-axis sharded PRIORITY_BANDS solve with capacity groups.

    Rows (resources) shard across every mesh axis like the dense solve,
    but group caps couple resources ACROSS shards: each device computes
    its local per-group usage and a psum over the mesh replicates the
    totals, so the theta bisection runs identically everywhere — one
    [G]-sized collective per bisection evaluation is the entire
    cross-device traffic (the banded water-fill itself stays row-local).
    Place inputs with `shard_priority`; group_cap is replicated."""
    from doorman_tpu.solver.priority import PriorityBatch, solve_priority

    axes = tuple(mesh.axis_names)
    row = P(axes)
    rowk = P(axes, None)
    rep = P()

    def shard_fn(wants, weights, band, active, cap, group, group_cap):
        return solve_priority(
            PriorityBatch(
                wants=wants, weights=weights, band=band, active=active,
                capacity=cap, group=group, group_cap=group_cap,
            ),
            num_bands=num_bands,
            combine_axes=axes,
        )

    mapped = shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(rowk, rowk, rowk, rowk, row, row, rep),
        out_specs=rowk,
    )

    @partial(jax.jit, donate_argnums=tuple(range(4)) if donate else ())
    def solve_parts(wants, weights, band, active, cap, group, group_cap):
        return mapped(wants, weights, band, active, cap, group, group_cap)

    def solve(batch) -> jax.Array:
        return solve_parts(
            batch.wants, batch.weights, batch.band, batch.active,
            batch.capacity, batch.group, batch.group_cap,
        )

    return solve


def _row_placer(mesh: Mesh, num_rows: int):
    """Shared pad-and-place machinery for the row-sharded batch layouts
    (shard_dense / shard_priority / shard_chunked): rows pad up to a
    multiple of the device count with `fill` — shard_chunked relies on
    fill=pad_seg keeping row_seg sorted — then land sharded over all
    mesh axes (spec P(axes, ...) per trailing rank) or replicated
    (spec=None)."""
    n_dev = int(np.prod(list(mesh.shape.values())))
    pad = (-num_rows) % n_dev
    axes = tuple(mesh.axis_names)

    def put(arr, *, sharded_rows: bool = True, fill=0):
        arr = np.asarray(arr)
        if not sharded_rows:
            return jax.device_put(arr, NamedSharding(mesh, P()))
        if pad:
            arr = np.concatenate(
                [arr, np.full((pad,) + arr.shape[1:], fill, arr.dtype)]
            )
        spec = P(axes, *([None] * (arr.ndim - 1)))
        return jax.device_put(arr, NamedSharding(mesh, spec))

    return put


def shard_priority(mesh: Mesh, batch):
    """Place a PriorityBatch on the mesh: row (resource) axis sharded
    over all mesh axes and padded with inactive, ungrouped rows up to a
    multiple of the device count; group_cap replicated."""
    from doorman_tpu.solver.priority import PriorityBatch

    put = _row_placer(mesh, int(np.asarray(batch.capacity).shape[0]))
    return PriorityBatch(
        wants=put(batch.wants),
        weights=put(batch.weights),
        band=put(batch.band),
        active=put(batch.active),
        capacity=put(batch.capacity),
        # Padding rows are ungrouped (-1): they contribute nothing to
        # any group's usage.
        group=put(batch.group, fill=-1),
        group_cap=put(batch.group_cap, sharded_rows=False),
    )


def shard_dense(mesh: Mesh, batch):
    """Place a DenseBatch on the mesh: row (resource) axis sharded over
    all mesh axes, padded with inactive rows up to a multiple of the
    device count (the dense analog of shard_edges)."""
    from doorman_tpu.solver.dense import DenseBatch

    put = _row_placer(mesh, int(np.asarray(batch.capacity).shape[0]))
    return DenseBatch(
        wants=put(batch.wants),
        has=put(batch.has),
        subclients=put(batch.subclients),
        active=put(batch.active),
        capacity=put(batch.capacity),
        algo_kind=put(batch.algo_kind),
        learning=put(batch.learning),
        static_capacity=put(batch.static_capacity),
    )


def shard_edges(mesh: Mesh, edges: EdgeBatch) -> EdgeBatch:
    """Place an EdgeBatch on the mesh: edge arrays sharded over all mesh
    axes. The edge axis is padded (inactive edges) up to a multiple of the
    device count so every shard is equal-sized."""
    n_dev = int(np.prod(list(mesh.shape.values())))
    E = int(np.asarray(edges.active).shape[0])
    pad = (-E) % n_dev
    if pad:
        def extend(arr, fill):
            arr = np.asarray(arr)
            return np.concatenate(
                [arr, np.full((pad,), fill, dtype=arr.dtype)]
            )

        # Pad with the last (maximal) resource id: keeps the edge list
        # sorted by segment id, which the segment reductions rely on.
        rid = np.asarray(edges.resource)
        last_rid = rid[-1] if rid.size else 0
        edges = EdgeBatch(
            resource=extend(edges.resource, last_rid),
            wants=extend(edges.wants, 0),
            has=extend(edges.has, 0),
            subclients=extend(edges.subclients, 0),
            active=extend(edges.active, False),
        )
    spec = NamedSharding(mesh, P(tuple(mesh.axis_names)))
    put = lambda a: jax.device_put(a, spec)
    return EdgeBatch(
        resource=put(edges.resource),
        wants=put(edges.wants),
        has=put(edges.has),
        subclients=put(edges.subclients),
        active=put(edges.active),
    )


def replicate_resources(mesh: Mesh, resources: ResourceBatch) -> ResourceBatch:
    spec = NamedSharding(mesh, P())
    put = lambda a: jax.device_put(a, spec)
    return ResourceBatch(
        capacity=put(resources.capacity),
        algo_kind=put(resources.algo_kind),
        learning=put(resources.learning),
        static_capacity=put(resources.static_capacity),
    )


def dc_aggregates(mesh: Mesh, edges: EdgeBatch, num_resources: int):
    """Per-dc (first mesh axis) aggregate tables — the on-chip analog of
    each intermediate server's PriorityBandAggregate report: for every dc,
    per-resource (sum_wants, sum_has, num_subclients). Returns three arrays
    of shape [n_dc, R]."""
    if len(mesh.axis_names) < 2:
        raise ValueError("dc_aggregates needs a two-axis ('dc', ...) mesh")
    axes = tuple(mesh.axis_names)
    dc_axis, client_axes = axes[0], axes[1:]
    edge_spec = P(axes)

    def shard_fn(rid, wants, has, sub, active):
        from doorman_tpu.solver.fairshare import local_segment_sum

        segsum = local_segment_sum(rid, num_resources)
        zero = jnp.zeros((), wants.dtype)
        w = jnp.where(active, wants, zero)
        h = jnp.where(active, has, zero)
        s = jnp.where(active, sub, zero)
        # Combine across the client axes only: one [R] row per dc.
        row = lambda v: jax.lax.psum(segsum(v), client_axes)
        return (
            row(w)[None, :], row(h)[None, :], row(s)[None, :],
        )

    mapped = shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(edge_spec,) * 5,
        out_specs=(P(dc_axis, None),) * 3,
    )
    return jax.jit(mapped)(
        edges.resource, edges.wants, edges.has, edges.subclients, edges.active
    )
