"""Multi-chip parallelism: device meshes and the client-axis sharded solve.

The reference scales by a tree of servers — intermediate servers aggregate
their clients' demand into priority bands and forward it to the root
(reference doc/design.md:204-220, server.go:822-901). On TPU the same
structure is fused on-chip: the edge list shards across devices over a mesh
axis ("clients" = the leaf/intermediate role), per-resource aggregates
combine with psum over ICI (= band aggregation), and every device then
computes its shard's grants from the replicated totals (= the root solve).
A second mesh axis ("dc") models the two-level tree.
"""

from doorman_tpu.parallel.mesh import make_mesh  # noqa: F401
from doorman_tpu.parallel.multihost import (  # noqa: F401
    make_multihost_mesh,
    pack_process_edges,
)
from doorman_tpu.parallel.sharded import (  # noqa: F401
    make_sharded_chunked_solver,
    make_sharded_dense_solver,
    make_sharded_priority_solver,
    make_sharded_solver,
    shard_chunked,
    shard_dense,
    shard_edges,
    shard_priority,
)
