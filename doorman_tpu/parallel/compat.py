"""Version-compat shims for JAX APIs that moved between releases.

`shard_map` graduated from `jax.experimental.shard_map` to the top-level
`jax` namespace (and the experimental module is slated for removal), so no
single import spelling works across the JAX versions this framework
supports. Every in-repo user imports it from here; tests import through
`doorman_tpu.parallel`, so a wrong spelling would break collection of the
whole sharded suite, not just one test.

The wrapper also disables the static replication checker (`check_rep`,
renamed `check_vma` in newer releases) by default: the solvers run
`psum`-combined scans whose carries the checker cannot type (it reports
"Scan carry input and output got mismatched replication types" and
suggests exactly this flag), while the numerics are pinned independently
against the single-chip solve in tests/test_sharded.py. Callers can still
pass the flag explicitly to re-enable the check.
"""

from __future__ import annotations

import inspect

try:  # jax >= 0.5: top-level export
    from jax import shard_map as _shard_map  # type: ignore[attr-defined]
except ImportError:  # older jax: experimental home
    from jax.experimental.shard_map import shard_map as _shard_map

_CHECK_KW = next(
    (
        kw
        for kw in ("check_rep", "check_vma")
        if kw in inspect.signature(_shard_map).parameters
    ),
    None,
)


def shard_map(f, *args, **kwargs):
    """`jax.shard_map` with the replication check off unless overridden."""
    if _CHECK_KW is not None:
        kwargs.setdefault(_CHECK_KW, False)
    return _shard_map(f, *args, **kwargs)
