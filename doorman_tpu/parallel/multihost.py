"""Multi-host execution: runtime wiring + host-local shard packing.

The reference spans hosts by construction — its server tree is processes
on different machines talking gRPC (doc/design.md:204-220 in
/root/reference). The TPU framework's equivalent for the *solve* is a
multi-host TPU slice: every host runs the same program, the mesh spans
all hosts' chips, and collectives ride ICI. This module is the glue the
design doc's recipe describes (doc/design.md "Multi-host"):

  * `initialize()` — `jax.distributed.initialize` wiring with
    `DOORMAN_*` env fallbacks (utils/flagenv.py convention), idempotent;
  * `make_multihost_mesh()` — a ("dc", "clients") mesh whose leading
    axis follows process boundaries, so each host's chips form its own
    "dc" block (the intermediate-server role of the fused tree) and the
    per-dc partial aggregation never leaves the host's chips;
  * `pad_edge_block()` / `pack_process_edges()` — each host packs ONLY
    its own clients' edges (the leases its RPC frontends own) and the
    global sharded EdgeBatch is assembled with
    `jax.make_array_from_process_local_data`, so edge tables never cross
    DCN; the psum inside the sharded solve is the only cross-host
    traffic.

The packing math is pure (unit-tested on the CPU mesh in
tests/test_multihost.py); `__graft_entry__.dryrun_multichip` runs the
same path end-to-end against the single-device solve.
"""

from __future__ import annotations

import logging
import os
from collections import Counter
from typing import Optional, Sequence, Tuple

import numpy as np

from doorman_tpu.solver.kernels import EdgeBatch

log = logging.getLogger(__name__)

_initialized = False


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_ids: "Optional[Sequence[int]]" = None,
) -> None:
    """`jax.distributed.initialize` with DOORMAN_* env fallbacks.

    Call once per process before any other JAX use, on every host of
    the slice. No-ops when already initialized or when neither
    arguments nor env vars name a coordinator (single-host runs).
    Env: DOORMAN_COORDINATOR (host:port), DOORMAN_NUM_PROCESSES,
    DOORMAN_PROCESS_ID.
    """
    global _initialized
    if _initialized:
        return
    coordinator_address = coordinator_address or os.environ.get(
        "DOORMAN_COORDINATOR"
    )
    if coordinator_address is None:
        return  # single-host: the default runtime is already correct
    if num_processes is None and "DOORMAN_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["DOORMAN_NUM_PROCESSES"])
    if process_id is None and "DOORMAN_PROCESS_ID" in os.environ:
        process_id = int(os.environ["DOORMAN_PROCESS_ID"])

    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids,
    )
    _initialized = True
    log.info(
        "multihost runtime up: process %d/%d, %d local of %d global devices",
        jax.process_index(), jax.process_count(),
        len(jax.local_devices()), len(jax.devices()),
    )


def make_multihost_mesh(
    axis_names: Tuple[str, ...] = ("dc", "clients"),
    devices: Optional[Sequence] = None,
):
    """Mesh over all hosts' devices with the leading axis following
    process boundaries: host i's chips are block i of the first axis.

    With per-host shards packed host-locally (`pack_process_edges`),
    this layout keeps every edge's data on its owner's chips; the
    leading axis doubles as the "dc" level of the fused two-level tree
    (parallel/sharded.py `dc_aggregates`). Falls back to a single axis
    when `axis_names` has one name."""
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    devices = sorted(
        devices, key=lambda d: (d.process_index, getattr(d, "id", 0))
    )
    n = len(devices)
    if len(axis_names) == 1:
        return Mesh(np.array(devices), axis_names)
    # Every process must contribute the SAME device count, else the
    # reshape below would put one host's chips into another host's
    # "dc" row and the host-local packing invariant silently breaks
    # (total-count divisibility alone cannot catch 3+5 over 2 hosts).
    counts = Counter(d.process_index for d in devices)
    n_proc = len(counts)
    if len(set(counts.values())) > 1:
        raise ValueError(
            f"uneven devices per process {counts}: the dc-axis layout "
            "requires every host to contribute the same device count"
        )
    dev_array = np.array(devices).reshape(n_proc, n // n_proc)
    return Mesh(dev_array, axis_names)


# -- host-local edge packing (pure math, unit-testable) -----------------


def pad_edge_block(edges: EdgeBatch, size: int) -> EdgeBatch:
    """Pad a host's local edge arrays to the agreed per-host block
    `size` with inactive edges (the solve masks them out). The fill
    resource id repeats the block's last id so per-shard edge lists
    stay sorted by segment — the segment reductions rely on it."""
    arrs = {
        "resource": np.asarray(edges.resource),
        "wants": np.asarray(edges.wants),
        "has": np.asarray(edges.has),
        "subclients": np.asarray(edges.subclients),
        "active": np.asarray(edges.active),
    }
    e = arrs["active"].shape[0]
    if e > size:
        raise ValueError(
            f"host holds {e} edges, over the per-host block size {size}"
        )
    pad = size - e
    if pad == 0:
        return EdgeBatch(**arrs)
    last_rid = arrs["resource"][-1] if e else 0
    fills = {
        "resource": last_rid, "wants": 0, "has": 0, "subclients": 0,
        "active": False,
    }
    return EdgeBatch(
        **{
            k: np.concatenate(
                [v, np.full((pad,), fills[k], dtype=v.dtype)]
            )
            for k, v in arrs.items()
        }
    )


def pack_process_edges(
    mesh, local_edges: EdgeBatch, edges_per_host: int
) -> EdgeBatch:
    """Assemble the global sharded EdgeBatch from THIS host's edges.

    Every host calls this with its own clients' edge list (padded here
    to `edges_per_host`, which all hosts must agree on — it is config,
    not data); `jax.make_array_from_process_local_data` lays host i's
    block onto host i's chips, so nothing crosses DCN. The result is
    addressable shard-wise and feeds parallel.sharded.make_sharded_solver
    directly. Single-process: equivalent to shard_edges (same layout).
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    n_dev = int(mesh.devices.size)
    n_proc = max(
        len({d.process_index for d in mesh.devices.flat}), 1
    )
    # The edge axis shards over every mesh axis, so the global length
    # must divide by the device count — round the per-host block up to
    # a multiple of the per-host device count (deterministic from mesh
    # shape + config, so every host agrees).
    per_host_dev = max(n_dev // n_proc, 1)
    edges_per_host += (-edges_per_host) % per_host_dev
    block = pad_edge_block(local_edges, edges_per_host)
    global_e = edges_per_host * n_proc
    sharding = NamedSharding(mesh, P(tuple(mesh.axis_names)))

    def assemble(local: np.ndarray) -> "jax.Array":
        return jax.make_array_from_process_local_data(
            sharding, local, (global_e,) + local.shape[1:]
        )

    return EdgeBatch(
        resource=assemble(np.asarray(block.resource)),
        wants=assemble(np.asarray(block.wants)),
        has=assemble(np.asarray(block.has)),
        subclients=assemble(np.asarray(block.subclients)),
        active=assemble(np.asarray(block.active)),
    )


def split_edges_by_host(
    edges: EdgeBatch, n_hosts: int
) -> "list[EdgeBatch]":
    """Deal a global edge list into `n_hosts` contiguous blocks (test
    and simulation helper: it models which edges each host's RPC
    frontends would own). Blocks keep global order, so reassembly by
    concatenation is the identity — the invariant the packing tests
    pin."""
    e = int(np.asarray(edges.active).shape[0])
    bounds = np.linspace(0, e, n_hosts + 1).astype(int)
    out = []
    for a, b in zip(bounds[:-1], bounds[1:]):
        out.append(
            EdgeBatch(
                resource=np.asarray(edges.resource)[a:b],
                wants=np.asarray(edges.wants)[a:b],
                has=np.asarray(edges.has)[a:b],
                subclients=np.asarray(edges.subclients)[a:b],
                active=np.asarray(edges.active)[a:b],
            )
        )
    return out
