"""Device mesh helpers."""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh


def make_mesh(
    axis_sizes: Optional[Sequence[int]] = None,
    axis_names: Tuple[str, ...] = ("clients",),
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a Mesh over the available devices.

    With the default single "clients" axis, all devices shard the edge list.
    For the two-level tree pass axis_names=("dc", "clients") and the per-axis
    sizes (their product must equal the device count).
    """
    devices = list(devices if devices is not None else jax.devices())
    if axis_sizes is None:
        axis_sizes = [len(devices)] if len(axis_names) == 1 else None
    if axis_sizes is None:
        raise ValueError("axis_sizes required for multi-axis meshes")
    if int(np.prod(axis_sizes)) != len(devices):
        raise ValueError(
            f"axis sizes {axis_sizes} do not cover {len(devices)} devices"
        )
    dev_array = np.array(devices).reshape(axis_sizes)
    return Mesh(dev_array, axis_names)


def make_mesh_from_spec(
    spec: str, devices: Optional[Sequence[jax.Device]] = None
) -> Mesh:
    """Build a Mesh from an operator-facing axis-size spec.

    '8' or '2x4' name per-axis sizes (their product must equal the
    device count — make_mesh validates); '' or 'auto' takes one axis
    over every visible device. Axis names follow the repo convention:
    one axis -> ("clients",), two -> ("dc", "clients"), more -> ax<i>.
    The row-sharded resident solvers flatten all axes anyway; the names
    matter only for the edge-sharded solve's dc_aggregates view.
    """
    spec = (spec or "").strip().lower()
    if spec in ("", "auto"):
        devices = list(devices if devices is not None else jax.devices())
        return make_mesh(devices=devices)
    try:
        sizes = [int(p) for p in spec.replace("*", "x").split("x")]
    except ValueError:
        raise ValueError(
            f"bad mesh spec {spec!r}: want 'auto', '8', or '2x4'"
        ) from None
    names = {
        1: ("clients",),
        2: ("dc", "clients"),
    }.get(len(sizes)) or tuple(f"ax{i}" for i in range(len(sizes)))
    return make_mesh(sizes, names, devices)
