"""Shipped fault plans: the standing chaos regression suite.

Each plan is small enough to run in the tier-1 smoke suite (tens of
virtual ticks, milliseconds-to-seconds of wall clock on CPU) and is the
replay artifact for its scenario — `python -m doorman_tpu.cmd.chaos
--plan master_flap` runs one by name, `--save-plan` dumps the JSON for
editing. Timelines below are in ticks (1 virtual second each).
"""

from __future__ import annotations

from typing import Dict

from doorman_tpu.chaos.plan import FaultEvent, FaultPlan


def master_flap() -> FaultPlan:
    """Two candidates; the master's etcd view browns out past the lock
    TTL. Expect: step-down without split-brain, the standby wins after
    the lock lapses, clients chase the redirect once the old master's
    watcher heals, allocation returns to baseline via learning mode.
    The streaming leg: one WatchCapacity subscriber rides along — its
    stream must terminate with a mastership redirect at the flip, the
    client must fall back to polling (the lease-window invariants hold
    for it like any polling client), and it must re-establish a stream
    once a master is back."""
    return FaultPlan(
        name="master_flap",
        seed=1,
        setup={
            "servers": 2,
            "clients": 3,
            "wants": [20.0, 30.0, 60.0],
            # The streaming leg (runner: stream_step per tick; servers
            # get stream_push + a per-tick fanout beat).
            "streams": 1,
            "stream_wants": [15.0],
            "capacity": 100,
            "mode": "immediate",
            "lease_length": 60,
            "refresh_interval": 1,
            "learning_mode_duration": 3,
            "election_ttl": 3.0,
        },
        events=[
            FaultEvent(at_tick=7, kind="kv_drop", target="s0",
                       duration_ticks=5),
        ],
        warmup_ticks=7,
        total_ticks=24,
        reconverge_ticks=8,
    )


def etcd_brownout() -> FaultPlan:
    """One master, three phases: a single dropped renewal round-trip
    (must be survived by the transient-retry tolerance), one spurious
    NOT_MASTER on the client link (one failed refresh, lease retained),
    then a sustained brownout past the TTL (mastership lost, lock
    lapses, the same server re-wins and relearns)."""
    return FaultPlan(
        name="etcd_brownout",
        seed=2,
        setup={
            "servers": 1,
            "clients": 3,
            "wants": [15.0, 25.0, 40.0],
            "capacity": 60,
            "mode": "immediate",
            "lease_length": 60,
            "refresh_interval": 1,
            "learning_mode_duration": 3,
            "election_ttl": 3.0,
        },
        events=[
            FaultEvent(at_tick=7, kind="kv_drop", target="s0",
                       duration_ticks=1, params={"calls": 1}),
            FaultEvent(at_tick=8, kind="grpc_not_master",
                       target="link:s0", duration_ticks=1,
                       params={"calls": 1}),
            FaultEvent(at_tick=9, kind="kv_drop", target="s0",
                       duration_ticks=4),
        ],
        warmup_ticks=7,
        total_ticks=20,
        reconverge_ticks=6,
    )


def device_tunnel_outage() -> FaultPlan:
    """Batch server on the resident tick path: the device solve dies
    mid-rotation for three ticks (tick errors, not crashes — stores
    keep serving last solved grants), then a ResidentOverflow forces
    the BatchSolver fallback, then one slow solve. Allocation never
    deviates from baseline. The shadow audit rides along as the CLEAN
    pin: across tick errors, the fallback and the slow solve, the
    sampled oracle replay must report ZERO divergences (the
    grant_corruption plan is the dirty twin that must report some)."""
    return FaultPlan(
        name="device_tunnel_outage",
        seed=3,
        setup={
            "servers": 1,
            "clients": 4,
            "wants": [10.0, 20.0, 30.0, 40.0],
            "capacity": 80,
            "mode": "batch",
            "native_store": True,
            "lease_length": 60,
            "refresh_interval": 1,
            "learning_mode_duration": 0,
            "election_ttl": 3.0,
            "audit_sample": 3,
        },
        events=[
            FaultEvent(at_tick=7, kind="solver_error", target="s0",
                       duration_ticks=3),
            FaultEvent(at_tick=10, kind="resident_overflow", target="s0",
                       duration_ticks=1, params={"calls": 1}),
            FaultEvent(at_tick=11, kind="solver_slow", target="s0",
                       duration_ticks=1,
                       params={"calls": 1, "seconds": 0.02}),
        ],
        warmup_ticks=7,
        total_ticks=20,
        reconverge_ticks=6,
    )


def intermediate_partition() -> FaultPlan:
    """Root + intermediate + clients on the intermediate: the
    intermediate<->root hop partitions for longer than the parent lease,
    so the intermediate's capacity decays to zero (clients degrade, no
    overcommit), then heals and re-leases from the root."""
    return FaultPlan(
        name="intermediate_partition",
        seed=4,
        setup={
            "servers": 1,
            "intermediate": True,
            "clients": 3,
            "wants": [10.0, 20.0, 30.0],
            "capacity": 90,
            "mode": "immediate",
            "lease_length": 6,
            "refresh_interval": 1,
            "learning_mode_duration": 0,
            "election_ttl": 3.0,
        },
        events=[
            FaultEvent(at_tick=6, kind="grpc_drop", target="link:s0",
                       duration_ticks=9),
        ],
        warmup_ticks=6,
        total_ticks=24,
        reconverge_ticks=6,
    )


def master_flap_warm(
    name: str = "master_flap_warm",
    algorithm: "str | None" = None,
    variant: "str | None" = None,
) -> FaultPlan:
    """master_flap with persistence enabled (a shared snapshot+journal
    backend): the master's etcd view browns out past the lock TTL, it
    steps down CLEANLY (terminal journal marker), and the standby that
    wins the lock restores the full lease table instead of relearning.
    Expect: a `restore` event with mode=warm and a complete journal,
    learning mode skipped for the restored resource (the cold path
    would relearn for `learning_mode_duration` = 10 ticks), restored
    grants never above capacity (the `restore_capacity` invariant), and
    reconvergence within 2 ticks of the heal — the budget that makes
    warm takeover observable: it is 1/5th of the learning window the
    cold path would need before serving real grants again.

    `algorithm`/`variant` parametrize the scenario over the fairness
    portfolio (PLANS ships one per lane): the restore/learning-mode
    decisions and the reconvergence SLO are algorithm-independent
    CONTRACTS, so every lane must meet the same budgets — and each
    parametrization's event log is pinned deterministic per kind by
    tests/test_chaos_smoke.py."""
    setup_extra = {}
    if algorithm is not None:
        setup_extra["algorithm"] = algorithm
    if variant is not None:
        setup_extra["algorithm_variant"] = variant
    return FaultPlan(
        name=name,
        seed=5,
        setup=setup_extra | {
            "servers": 2,
            "clients": 3,
            "wants": [20.0, 30.0, 60.0],
            "capacity": 100,
            "mode": "immediate",
            "lease_length": 60,
            "refresh_interval": 1,
            # Long enough that a cold takeover visibly eats the plan's
            # reconvergence budget; the warm path must not need it.
            "learning_mode_duration": 10,
            "election_ttl": 3.0,
            "persist": True,
            "snapshot_interval": 3.0,
        },
        events=[
            FaultEvent(at_tick=13, kind="kv_drop", target="s0",
                       duration_ticks=5),
        ],
        # The initial (cold, empty-backend) learning window is 10 ticks;
        # the baseline snapshot must land after it.
        warmup_ticks=13,
        total_ticks=26,
        reconverge_ticks=2,
    )


def client_storm() -> FaultPlan:
    """A refresh storm from a swarm of low-band clients against an
    admission-enabled master. Three baseline clients sit in three
    priority bands on a PRIORITY_BANDS resource; at the storm tick, 20
    extra band-0 clients start hammering refreshes every tick — an
    offered load ~8x the controller's max_rps budget. Expect: the
    hard per-window cap sheds most of the swarm in its very first
    window (before the AIMD level has a boundary to move at), the
    level then collapses and band probabilities extinguish bottom-up
    (band 0 first, band 1 next, the top band NEVER — the goodput-floor
    invariant), baseline allocations ride through byte-unchanged (shed
    refreshes retain leases; the admitted slice of the swarm only gets
    band-0 leftovers under PRIORITY_BANDS), the swarm's releases at
    heal all pass (releases-never-shed), and post-heal additive
    recovery readmits every band with ticks to spare inside the
    reconverge budget."""
    return FaultPlan(
        name="client_storm",
        seed=6,
        setup={
            "servers": 1,
            "clients": 3,
            "wants": [20.0, 30.0, 40.0],
            # Wire priorities: c0 is the top band the floor protects.
            "priorities": [2, 1, 0],
            "capacity": 100,
            "algorithm": "PRIORITY_BANDS",
            "mode": "immediate",
            "lease_length": 60,
            "refresh_interval": 1,
            "learning_mode_duration": 0,
            "election_ttl": 3.0,
            # One admit window per tick; 10 rps against 3 rps of
            # baseline traffic — the swarm alone trips the budget.
            "admission": {"max_rps": 10.0, "window": 1.0},
        },
        events=[
            FaultEvent(at_tick=8, kind="client_storm",
                       duration_ticks=6,
                       params={"clients": 20, "wants": 10.0,
                               "priority": 0}),
        ],
        warmup_ticks=8,
        total_ticks=28,
        reconverge_ticks=12,
    )


def shard_partition() -> FaultPlan:
    """Federated root tier: three shards (per-shard election locks, one
    master each), one straddling resource r0 (capacity 90) whose shares
    reconcile POP-style every tick, one client per shard (wants
    30/30/60: overloaded, so shares sit at the demand-proportional
    22.5/22.5/45). At the fault tick, shard s1 partitions from the
    reconciler: its share stops renewing, coasts to its ttl, then the
    shard decays to ZERO capacity — its client degrades (the plan's
    `degraded` marker). Blast radius is the invariant: the other
    shards' clients ride through byte-unchanged (shard_blast_radius),
    and Σ shard grants never exceeds 90 on any tick, because the lost
    shard's frozen share stays charged against the pool through its
    drain window (fed_capacity_sum — POP's reconciliation safety).
    At heal the reconciler reaches s1 again, re-grants its share, and
    the allocation reconverges to baseline within budget."""
    return FaultPlan(
        name="shard_partition",
        seed=7,
        setup={
            "servers": 3,
            "federated": {
                "shards": 3,
                "straddle": ["r0"],
                "share_ttl": 2.0,
                "client_shards": [0, 1, 2],
            },
            "clients": 3,
            "wants": [30.0, 30.0, 60.0],
            "capacity": 90,
            # Batch mode re-solves every store row each tick, so a
            # share shrink lands on ALL of a shard's grants the very
            # next tick — the pointwise capacity-sum bound needs no
            # refresh-ordering slack.
            "mode": "batch",
            "lease_length": 60,
            "refresh_interval": 1,
            "learning_mode_duration": 0,
            "election_ttl": 3.0,
        },
        events=[
            FaultEvent(at_tick=8, kind="shard_partition", target="s1",
                       duration_ticks=6),
        ],
        warmup_ticks=8,
        total_ticks=26,
        reconverge_ticks=6,
    )


def fleet_reshard_live() -> FaultPlan:
    """Live resharding 2→3→2 over a provisioned pool of four shards.

    The fleet controller serves the straddling r0 (capacity 120) from
    an ACTIVE set of two shards; one client per active shard (wants
    30/20 — underloaded, so the steady state is wants-granted). At tick
    8 the active set grows to three: the new shard enters the beat
    with an empty summary and receives an even slack split — nothing
    restarts, no rows move. At tick 16 it shrinks back: shard 2 leaves
    the active set, its share freezes (charged against the pool) and
    drains through expiry + lease length. The acceptance is lease
    continuity: both clients' grants are byte-unchanged through BOTH
    handoffs, and Σ shard grants ≤ 120 holds pointwise on every tick
    (fed_capacity_sum) — the frozen-share drain is exactly what makes
    the shrink direction safe. Batch mode, so share changes land on
    all of a shard's grants the very next tick."""
    return FaultPlan(
        name="fleet_reshard_live",
        seed=17,
        setup={
            "servers": 4,
            "federated": {
                "fleet": True,
                "active": 2,
                "straddle": ["r0"],
                "share_ttl": 2.0,
                "client_shards": [0, 1],
            },
            "clients": 2,
            "wants": [30.0, 20.0],
            "capacity": 120,
            "mode": "batch",
            "lease_length": 60,
            "refresh_interval": 1,
            "learning_mode_duration": 0,
            "election_ttl": 3.0,
        },
        events=[
            FaultEvent(at_tick=8, kind="fleet_reshard",
                       duration_ticks=0, params={"to": 3}),
            FaultEvent(at_tick=16, kind="fleet_reshard",
                       duration_ticks=0, params={"to": 2}),
        ],
        warmup_ticks=8,
        total_ticks=26,
        reconverge_ticks=4,
    )


def fleet_reshard_partition() -> FaultPlan:
    """A reshard landing in the middle of a shard partition.

    Three provisioned shards, two active, one client on each (wants
    30/30 against capacity 90). Shard 1 partitions from the beat at
    tick 8; while its share is still frozen, the fleet grows to three
    at tick 10 — the reconciler must split the UNFROZEN remainder
    between the live shards, keeping s1's frozen share charged, so
    Σ grants ≤ 90 holds pointwise through the overlap of partition and
    reshard. s1's client degrades as its shard's capacity decays (the
    plan's degraded marker); s0's client rides through byte-unchanged
    (shard_blast_radius). At heal the beat reaches s1 again, re-grants
    its share, and allocations reconverge within budget."""
    return FaultPlan(
        name="fleet_reshard_partition",
        seed=18,
        setup={
            "servers": 3,
            "federated": {
                "fleet": True,
                "active": 2,
                "straddle": ["r0"],
                "share_ttl": 2.0,
                "client_shards": [0, 1],
            },
            "clients": 2,
            "wants": [30.0, 30.0],
            "capacity": 90,
            "mode": "batch",
            "lease_length": 60,
            "refresh_interval": 1,
            "learning_mode_duration": 0,
            "election_ttl": 3.0,
        },
        events=[
            FaultEvent(at_tick=8, kind="shard_partition", target="s1",
                       duration_ticks=6),
            FaultEvent(at_tick=10, kind="fleet_reshard",
                       duration_ticks=0, params={"to": 3}),
        ],
        warmup_ticks=8,
        total_ticks=28,
        reconverge_ticks=8,
    )


def grant_corruption() -> FaultPlan:
    """The shadow-oracle audit's proving ground: a batch server under
    steady overload (FAIR_SHARE, wants 110 vs capacity 100, so the
    waterfill output is constant) has one row of its solve output
    silently scaled by 0.75 for nine ticks. The corruption shrinks a
    grant, so every structural invariant (capacity conservation,
    has <= wants, band floors) still passes — only the bit-identity
    audit can see it. With the auditor sampling every 3 ticks inline,
    the corrupted store value is constant across consecutive samples,
    the two-strike identical-digest rule confirms at the second sample,
    and the divergence lands within 2K ticks of the fault —
    deterministically, byte-stable across replays. After heal the
    solve output reverts and allocation reconverges within budget; the
    verdict's audit block pins the divergence count and the offending
    resource."""
    return FaultPlan(
        name="grant_corruption",
        seed=11,
        setup={
            "servers": 1,
            "clients": 3,
            "wants": [20.0, 30.0, 60.0],
            "capacity": 100,
            # Has-independent lane: under constant overload the
            # waterfill's output never moves, so the corrupted store
            # value is digest-stable across audit samples.
            "algorithm": "FAIR_SHARE",
            # Python-store batch path: prepare -> solve -> apply over
            # every resource each tick, so the corrupted solve output
            # lands in the store the same tick (no delivery lag to
            # reason about) and the audit sees it immediately.
            "mode": "batch",
            "native_store": False,
            "lease_length": 60,
            "refresh_interval": 1,
            "learning_mode_duration": 0,
            "election_ttl": 3.0,
            # Shadow audit every 3 ticks, comparisons inline so the
            # event log is byte-stable.
            "audit_sample": 3,
        },
        events=[
            FaultEvent(at_tick=10, kind="grant_corrupt", target="s0",
                       duration_ticks=9,
                       params={"row": 0, "factor": 0.75}),
        ],
        warmup_ticks=8,
        total_ticks=32,
        reconverge_ticks=8,
    )


def frontend_worker_crash() -> FaultPlan:
    """Serving-plane crash arc: a batch master with an inline frontend
    pool (2 listener workers over per-worker push rings) serves four
    WatchCapacity stream clients next to three refresh clients. At the
    fault tick, worker 0 dies for four ticks: every stream it held ends
    with a mastership redirect THAT TICK (reset-to-redirect — never a
    silent lapse), the dead worker's stream shards reassign to the
    survivor, and the clients' next stream_step chases the redirect and
    re-establishes — landing on worker 1, where pushes resume. At heal
    the worker restarts with a FRESH ring cursor (no frame replay;
    resume rides the push-seq contract) and new establishments home
    back to it. Base allocations ride through byte-unchanged — the
    serving plane is fanout only, the tick process never stopped
    deciding — and the event log (crash, redirects, re-establishes,
    restore) replays byte-identically."""
    return FaultPlan(
        name="frontend_worker_crash",
        seed=12,
        setup={
            "servers": 1,
            "clients": 3,
            "wants": [20.0, 30.0, 60.0],
            "capacity": 100,
            "mode": "batch",
            "lease_length": 60,
            "refresh_interval": 1,
            "learning_mode_duration": 0,
            "election_ttl": 3.0,
            "streams": 4,
            "stream_shards": 4,
            "frontend_workers": 2,
        },
        events=[
            FaultEvent(at_tick=8, kind="worker_crash", target="s0",
                       duration_ticks=4, params={"worker": 0}),
        ],
        warmup_ticks=8,
        total_ticks=24,
        reconverge_ticks=8,
    )


def frontend_ring_stall() -> FaultPlan:
    """Serving-plane stall arc: same topology as the crash plan, but
    worker 0's ring pump freezes for ten ticks over a deliberately tiny
    ring (256 bytes). The tick edge keeps publishing (appends never
    block — backpressure is the reader's problem, frontend/ring.py), so
    the frozen reader is lapped; at resume the pump detects the lap and
    resets EVERY stream the worker held to a redirect — the loud
    failure mode the ring is designed for, instead of silently missing
    pushes. Clients chase the redirect and re-establish; the survivor's
    streams never notice."""
    return FaultPlan(
        name="frontend_ring_stall",
        seed=13,
        setup={
            "servers": 1,
            "clients": 3,
            "wants": [20.0, 30.0, 60.0],
            "capacity": 100,
            "mode": "batch",
            "lease_length": 60,
            "refresh_interval": 1,
            "learning_mode_duration": 0,
            "election_ttl": 3.0,
            "streams": 4,
            "stream_shards": 4,
            "frontend_workers": 2,
            # Small enough that a ten-tick stall laps the frozen reader
            # (beats + per-tick lease-refresh pushes), but with headroom
            # for a healthy tick's establishment snapshots.
            "frontend_ring": 512,
        },
        events=[
            FaultEvent(at_tick=8, kind="ring_stall", target="s0",
                       duration_ticks=10, params={"worker": 0}),
        ],
        warmup_ticks=8,
        total_ticks=28,
        reconverge_ticks=8,
    )


def _warm_variant(name, algorithm, variant):
    def build():
        return master_flap_warm(
            name=name, algorithm=algorithm, variant=variant
        )

    return build


PLANS: Dict[str, "callable"] = {
    "master_flap": master_flap,
    "master_flap_warm": master_flap_warm,
    # The warm-takeover arc across the fairness portfolio: same faults,
    # same reconvergence budget, one plan per algorithm lane
    # (FAIR_SHARE rides the plain fair-share plan below).
    "master_flap_warm_fair": _warm_variant(
        "master_flap_warm_fair", "FAIR_SHARE", None
    ),
    "master_flap_warm_maxmin": _warm_variant(
        "master_flap_warm_maxmin", "FAIR_SHARE", "maxmin"
    ),
    "master_flap_warm_balanced": _warm_variant(
        "master_flap_warm_balanced", "FAIR_SHARE", "balanced"
    ),
    "master_flap_warm_logutil": _warm_variant(
        "master_flap_warm_logutil", "PROPORTIONAL_SHARE", "logutil"
    ),
    "client_storm": client_storm,
    "etcd_brownout": etcd_brownout,
    "fleet_reshard_live": fleet_reshard_live,
    "fleet_reshard_partition": fleet_reshard_partition,
    "frontend_worker_crash": frontend_worker_crash,
    "frontend_ring_stall": frontend_ring_stall,
    "grant_corruption": grant_corruption,
    "device_tunnel_outage": device_tunnel_outage,
    "intermediate_partition": intermediate_partition,
    "shard_partition": shard_partition,
}


def get_plan(name: str) -> FaultPlan:
    try:
        return PLANS[name]()
    except KeyError:
        raise KeyError(
            f"unknown plan {name!r}; shipped plans: {sorted(PLANS)}"
        ) from None
