"""Fault injectors: shims at the seams the stack already crosses.

Four seams, matching the production failure surface:

  * the election's lease-KV (ChaosLeaseKV wrapping any LeaseKV) — the
    in-process equivalent of etcd round-trips failing;
  * the etcd v3 gateway client itself (ChaosEtcdGateway, a drop-in
    EtcdGateway speaking the REAL HTTP dialect against tests/fake_etcd
    or a live cluster): delayed/dropped round-trips and watch stalls;
  * gRPC hops between client<->server and intermediate<->root
    (ChaosGrpcProxy): latency, dropped RPCs, spurious NOT_MASTER;
  * the solver/backend boundary (SolverInjector): ResidentOverflow,
    slow device steps, a dead backend raising mid-tick.

All injectors consult one FaultState switchboard the runner drives from
the plan's event schedule; none of them mutates doorman code — they
wrap instances, so production paths run unmodified when no fault is
active.
"""

from __future__ import annotations

import asyncio
import random
import socket
import sys
import time
from typing import Dict, List, Optional, Tuple

from doorman_tpu.proto import doorman_pb2 as pb
from doorman_tpu.proto.grpc_api import CapacityServicer, add_capacity_servicer
from doorman_tpu.server.election import LeaseKV
from doorman_tpu.server.etcd import EtcdGateway
from doorman_tpu.chaos.plan import FaultEvent


class FaultInjected(ConnectionError):
    """An injected transport-style failure (distinguishable from a
    definite protocol outcome like 'lease gone')."""


class FaultState:
    """The live fault switchboard.

    The runner starts plan events here and advances ticks; injectors
    query with take(). A fault stays active until its duration expires;
    params["calls"] makes it count-limited instead ("drop the next N
    calls"), consumed by take(). The seeded RNG is the ONLY randomness
    a chaos run may use — injectors that jitter must draw from it, so a
    plan's seed fully determines the run."""

    def __init__(self, seed: int = 0):
        self.rng = random.Random(seed)
        self.tick = 0
        # (kind, target) -> {"until": tick, "params": dict}
        self._active: Dict[Tuple[str, str], Dict] = {}

    def begin_tick(self, tick: int) -> None:
        self.tick = tick
        gone = [
            key
            for key, entry in self._active.items()
            if entry["until"] <= tick
        ]
        for key in gone:
            del self._active[key]

    def start(self, ev: FaultEvent) -> None:
        self._active[(ev.kind, ev.target)] = {
            "until": ev.at_tick + max(ev.duration_ticks, 1),
            "params": dict(ev.params),
        }

    def active(self, kind: str, target: str) -> Optional[Dict]:
        """Params of the matching active fault (exact target wins over
        the "*" wildcard), or None. Does not consume call budgets."""
        for key in ((kind, target), (kind, "*")):
            entry = self._active.get(key)
            if entry is not None:
                return entry["params"]
        return None

    def take(self, kind: str, target: str) -> Optional[Dict]:
        """Like active(), but consumes one unit of a params["calls"]
        budget (deactivating the fault at zero)."""
        for key in ((kind, target), (kind, "*")):
            entry = self._active.get(key)
            if entry is None:
                continue
            params = entry["params"]
            calls = params.get("calls")
            if calls is not None:
                if calls <= 0:
                    del self._active[key]
                    continue
                params["calls"] = calls - 1
                if params["calls"] <= 0:
                    del self._active[key]
            return params
        return None


# ----------------------------------------------------------------------
# Election lease-KV seam
# ----------------------------------------------------------------------


class ChaosLeaseKV(LeaseKV):
    """Wraps any LeaseKV; kv_drop raises a transport-style failure,
    kv_delay adds real latency. `target` is the owning server's logical
    name, so a plan can brown out ONE candidate's view of etcd."""

    def __init__(self, inner: LeaseKV, state: FaultState, target: str):
        self.inner = inner
        self._state = state
        self.target = target

    async def _gate(self) -> None:
        p = self._state.take("kv_delay", self.target)
        if p is not None:
            await asyncio.sleep(float(p.get("seconds", 0.01)))
        p = self._state.take("kv_drop", self.target)
        if p is not None:
            raise FaultInjected(
                f"chaos: kv round-trip dropped ({self.target})"
            )

    async def acquire(self, key, value, ttl) -> bool:
        await self._gate()
        return await self.inner.acquire(key, value, ttl)

    async def refresh(self, key, value, ttl) -> bool:
        await self._gate()
        return await self.inner.refresh(key, value, ttl)

    async def get(self, key):
        await self._gate()
        return await self.inner.get(key)

    async def wait_for_change(self, key, timeout) -> None:
        await self.inner.wait_for_change(key, timeout)


# ----------------------------------------------------------------------
# etcd gateway seam (the real HTTP dialect)
# ----------------------------------------------------------------------


class ChaosEtcdGateway(EtcdGateway):
    """Drop-in EtcdGateway whose round-trips consult the switchboard.

    Runs against tests/fake_etcd.FakeEtcd (or live etcd) so the REAL
    EtcdKV election stack — renewal retries included — is what gets
    stressed; etcd_drop with params={"calls": 1} is exactly "one etcd
    hiccup". Blocking by design: the gateway always runs in executor
    threads."""

    def __init__(self, endpoints: List[str], state: FaultState,
                 target: str = "etcd"):
        super().__init__(endpoints)
        self._state = state
        self.target = target

    def _post(self, path: str, payload: dict, timeout: float = 30.0) -> dict:
        p = self._state.take("etcd_delay", self.target)
        if p is not None:
            time.sleep(float(p.get("seconds", 0.01)))
        # Peek before consuming: params["path_prefix"] scopes the drop
        # to one endpoint family (e.g. "/v3/lease/keepalive" targets
        # renewals without starving the election's watcher reads), and
        # a non-matching round-trip must not burn the calls budget.
        p = self._state.active("etcd_drop", self.target)
        if p is not None and path.startswith(p.get("path_prefix", "")):
            self._state.take("etcd_drop", self.target)
            raise FaultInjected(
                f"chaos: etcd round-trip dropped ({self.target}, {path})"
            )
        return super()._post(path, payload, timeout)

    def wait_for_change(self, key: str, timeout: float = 60.0) -> bool:
        p = self._state.take("etcd_watch_stall", self.target)
        if p is not None:
            # The watch neither delivers nor errors — it just hangs
            # until the caller's timeout (a stalled gateway stream).
            time.sleep(min(timeout, float(p.get("seconds", timeout))))
            return False
        return super().wait_for_change(key, timeout)


# ----------------------------------------------------------------------
# gRPC seam
# ----------------------------------------------------------------------


def _not_master_response(method: str, master: str):
    cls = {
        "Discovery": pb.DiscoveryResponse,
        "GetCapacity": pb.GetCapacityResponse,
        "GetServerCapacity": pb.GetServerCapacityResponse,
        "ReleaseCapacity": pb.ReleaseCapacityResponse,
    }[method]
    out = cls()
    if master:
        out.mastership.master_address = master
    else:
        out.mastership.SetInParent()
    return out


class ChaosGrpcProxy(CapacityServicer):
    """A loopback gRPC hop in front of a CapacityServer.

    Clients (and downstream servers) dial the proxy; each RPC consults
    the switchboard for the proxy's `link` target, then delegates to
    the backend servicer by direct method call (same loop, same grpc
    context — aborts and metadata behave exactly as if the client hit
    the server). Faults: grpc_drop (UNAVAILABLE), grpc_delay,
    grpc_not_master (a spurious mastership redirect)."""

    def __init__(self, state: FaultState, link: str):
        self._state = state
        self.link = link
        self.backend: Optional[CapacityServicer] = None  # set by runner
        self._server = None
        self.port: Optional[int] = None

    @property
    def address(self) -> str:
        return f"127.0.0.1:{self.port}"

    async def start(self) -> int:
        import grpc

        server = grpc.aio.server()
        add_capacity_servicer(server, self)
        self.port = server.add_insecure_port("127.0.0.1:0")
        await server.start()
        self._server = server
        return self.port

    async def stop(self) -> None:
        if self._server is not None:
            await self._server.stop(grace=None)
            self._server = None

    async def _intercept(self, method: str, request, context):
        import grpc

        p = self._state.take("grpc_delay", self.link)
        if p is not None:
            await asyncio.sleep(float(p.get("seconds", 0.01)))
        p = self._state.take("grpc_drop", self.link)
        if p is not None:
            await context.abort(
                grpc.StatusCode.UNAVAILABLE,
                f"chaos: rpc dropped ({self.link})",
            )
        p = self._state.take("grpc_not_master", self.link)
        if p is not None:
            return _not_master_response(method, p.get("master", ""))
        return await getattr(self.backend, method)(request, context)

    async def Discovery(self, request, context):
        return await self._intercept("Discovery", request, context)

    async def GetCapacity(self, request, context):
        return await self._intercept("GetCapacity", request, context)

    async def GetServerCapacity(self, request, context):
        return await self._intercept("GetServerCapacity", request, context)

    async def ReleaseCapacity(self, request, context):
        return await self._intercept("ReleaseCapacity", request, context)

    async def WatchCapacity(self, request, context):
        """The server-streaming leg of the proxy: establishment walks
        the same fault seams as a unary RPC (grpc_not_master yields a
        terminal redirect — exactly what a flipped master streams),
        then every backend push is forwarded message for message."""
        import grpc

        p = self._state.take("grpc_delay", self.link)
        if p is not None:
            await asyncio.sleep(float(p.get("seconds", 0.01)))
        p = self._state.take("grpc_drop", self.link)
        if p is not None:
            await context.abort(
                grpc.StatusCode.UNAVAILABLE,
                f"chaos: rpc dropped ({self.link})",
            )
        p = self._state.take("grpc_not_master", self.link)
        if p is not None:
            from doorman_tpu.proto import doorman_stream_pb2 as spb

            out = spb.WatchCapacityResponse()
            if p.get("master"):
                out.mastership.master_address = p["master"]
            else:
                out.mastership.SetInParent()
            yield out
            return
        async for msg in self.backend.WatchCapacity(request, context):
            yield msg


# ----------------------------------------------------------------------
# Solver / backend seam
# ----------------------------------------------------------------------


class SolverInjector:
    """Wraps a CapacityServer's solver entry points (instance-level, no
    doorman code modified): solver_error makes the device solve raise
    (tunnel down), solver_slow stretches it, resident_overflow raises
    ResidentOverflow from the resident step — exercising the server's
    fallback-to-BatchSolver path and the handle-clearing fix — and
    grant_corrupt scales one row of the solve's output (a silent wrong
    answer, not a crash: the fault only the shadow-oracle audit can
    see)."""

    def __init__(self, state: FaultState, target: str):
        self._state = state
        self.target = target

    def _gate(self) -> None:
        # Runs in the tick's executor thread: blocking sleep is correct.
        p = self._state.take("solver_slow", self.target)
        if p is not None:
            time.sleep(float(p.get("seconds", 0.01)))
        p = self._state.take("solver_error", self.target)
        if p is not None:
            raise RuntimeError(
                f"chaos: device backend unreachable ({self.target})"
            )

    def _corrupt(self, gets):
        """While grant_corrupt is active, scale gets[row] by `factor`
        (default 0.75 — shrinking keeps capacity conservation and
        has <= wants intact, so the corruption passes every structural
        invariant and only the bit-identity audit can catch it)."""
        p = self._state.active("grant_corrupt", self.target)
        if p is None:
            return gets
        import numpy as np

        out = np.asarray(gets).copy()
        row = int(p.get("row", 0))
        if 0 <= row < out.shape[0]:
            out[row] = out[row] * float(p.get("factor", 0.75))
        return out

    def install(self, server) -> None:
        injector = self
        orig_get_solver = server._get_solver

        def get_solver():
            solver = orig_get_solver()
            if not getattr(solver, "_chaos_wrapped", False):
                orig_solve = solver.solve

                def solve(snap):
                    injector._gate()
                    return injector._corrupt(orig_solve(snap))

                solver.solve = solve
                solver._chaos_wrapped = True
            return solver

        server._get_solver = get_solver

        def wrap_step(orig_step):
            def step(solver, resources, config_epoch):
                p = injector._state.take(
                    "resident_overflow", injector.target
                )
                if p is not None:
                    from doorman_tpu.solver.resident import ResidentOverflow

                    raise ResidentOverflow(
                        f"chaos: injected overflow ({injector.target})"
                    )
                injector._gate()
                return orig_step(solver, resources, config_epoch)

            return step

        server._resident_step = wrap_step(server._resident_step)
        server._resident_wide_step = wrap_step(server._resident_wide_step)


# ----------------------------------------------------------------------
# Host seams: stale ports, backend probes
# ----------------------------------------------------------------------


class PortInjector:
    """Holds loopback ports bound, simulating the stale server an
    interrupted drive leaks (the ensure_ports_free failure mode)."""

    def __init__(self):
        self._sockets: List[socket.socket] = []

    def bind(self) -> int:
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.bind(("127.0.0.1", 0))
        s.listen(1)
        self._sockets.append(s)
        return s.getsockname()[1]

    def release_all(self) -> None:
        for s in self._sockets:
            s.close()
        self._sockets.clear()


def backend_probe_argv(state: FaultState, target: str = "backend") -> list:
    """A probe argv for utils.backend.wait_for_backend, resolved against
    the switchboard at CALL time (wait_for_backend re-invokes it per
    attempt, so a fault with params={"calls": 1} fails exactly one probe
    and the retry schedule rides out the 'blip')."""
    p = state.take("backend_probe_fail", target)
    if p is None:
        return [sys.executable, "-c", "print('ok')"]
    mode = p.get("mode", "tunnel_down")
    if mode == "unretryable":
        return [sys.executable, "-c", "raise ModuleNotFoundError('chaos')"]
    # tunnel_down: the fast, verbatim-identical RuntimeError a dead
    # device tunnel surfaces — MUST stay retryable (round-4 lesson).
    return [sys.executable, "-c",
            "raise RuntimeError('chaos: tunnel down')"]
