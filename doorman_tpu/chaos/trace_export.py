"""Chrome trace-event export of a chaos run's virtual-time event log.

A chaos verdict's `event_log` is a list of `[tick, kind, ...]` entries
on the run's VIRTUAL clock. This module renders it in the same Chrome
trace-event JSON the span tracer exports (obs.trace), so a fault plan —
fault windows, mastership changes, invariant violations, degradation
and reconvergence markers — loads in Perfetto on one timeline, with one
virtual tick mapped to its tick_interval in trace time.

Fault events know their duration (duration_ticks), so they render as
complete spans; everything else is an instant marker. Tracks (tid) are
assigned per event kind so faults, mastership and violations stack as
separate swimlanes instead of overlapping.
"""

from __future__ import annotations

import json
from typing import Dict, List

__all__ = ["chrome_trace", "write_chrome_trace"]

# Swimlane per event kind; unknown kinds land on the last lane.
_LANES = ("fault", "master", "violation", "tick_error")
_OTHER_LANE = len(_LANES)

_PID = 1  # one logical "chaos" process


def _ts(tick: float, tick_interval: float) -> float:
    return tick * tick_interval * 1e6  # virtual µs


def chrome_trace(verdict: dict) -> dict:
    """Build the Chrome trace object from a runner verdict (as returned
    by ChaosRunner.run / written by `cmd.chaos --out`)."""
    interval = float(verdict.get("tick_interval", 1.0))
    events: List[dict] = [
        {
            "name": "process_name", "ph": "M", "pid": _PID, "tid": 0,
            "args": {"name": f"chaos:{verdict.get('plan', 'plan')}"},
        }
    ]
    lanes: Dict[str, int] = {k: i for i, k in enumerate(_LANES)}
    for name, tid in list(lanes.items()) + [("events", _OTHER_LANE)]:
        events.append({
            "name": "thread_name", "ph": "M", "pid": _PID, "tid": tid,
            "args": {"name": name},
        })
    for entry in verdict.get("event_log", []):
        tick, kind, rest = entry[0], str(entry[1]), entry[2:]
        tid = lanes.get(kind, _OTHER_LANE)
        ev = {
            "pid": _PID,
            "tid": tid,
            "ts": round(_ts(tick, interval), 3),
            "cat": "chaos",
            "args": {"tick": tick, "detail": rest},
        }
        if kind == "fault":
            # [tick, "fault", kind, target, duration_ticks]
            fault_kind = rest[0] if rest else "fault"
            target = rest[1] if len(rest) > 1 else ""
            dur_ticks = rest[2] if len(rest) > 2 else 1
            ev.update(
                name=f"{fault_kind}({target})",
                ph="X",
                dur=round(_ts(max(float(dur_ticks), 1.0), interval), 3),
            )
        elif kind == "violation":
            # [tick, "violation", invariant, subject, detail]
            ev.update(
                name=f"violation:{rest[0] if rest else '?'}", ph="i", s="p"
            )
        elif kind == "master":
            holders = ",".join(rest[0]) if rest and rest[0] else "(none)"
            ev.update(name=f"master={holders}", ph="i", s="p")
        else:
            ev.update(name=kind, ph="i", s="p")
        events.append(ev)
    events.sort(key=lambda e: e.get("ts", 0.0))
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def write_chrome_trace(verdict: dict, path: str) -> None:
    with open(path, "w") as f:
        json.dump(chrome_trace(verdict), f)
        f.write("\n")
