"""Invariants evaluated every chaos tick.

Four families, each with its documented slack:

  * capacity: per resource on each master, Σ live grants <= the largest
    capacity the resource carried within the last lease_length of
    virtual time. The window IS the contract: a grant issued under the
    old capacity legitimately lives until its lease lapses, so a
    capacity cut (or a parent-lease expiry zeroing an intermediate)
    tightens the bound only as old leases drain. Learning-mode
    resources are exempt while learning (the server deliberately grants
    whatever clients claim — server.go:438-455's relearning window).
  * single-master: at most one member of an election group believes it
    is master at any tick. No slack: two masters is the split-brain
    this whole subsystem exists to catch.
  * lease lag-never-lead: a client's believed capacity must be a value
    the serving master actually granted that client within the last
    lease_length (client state may LAG the server by a refresh
    interval, but a capacity the server never issued means forged or
    corrupted grants); held leases' expiry never moves backwards.
  * reconvergence (checked by the runner): after the plan heals, client
    allocations return to the fault-free baseline within the plan's
    reconverge budget.
  * admission (admission-enabled plans): the shed matrix is law —
    ReleaseCapacity and GetServerCapacity are NEVER shed (a shed
    release pins a dead client's capacity; a shed server aggregate
    starves a whole subtree), and the top priority band's GetCapacity
    shed count stays zero whenever lower bands exist (the goodput
    floor: overload shedding walks UP from the bottom band and never
    reaches the top while there is anything below it to shed).
    Deadline fast-fails are excluded — a request that brought too
    short a deadline was refused on its own terms, not the band's.
  * warm restore (persistence-enabled plans): every master takeover that
    restored state must land capacity-safe — per restored resource,
    sum(restored grants) <= the live capacity at restore (no learning
    slack here: the clamp in persist/restore.py is unconditional) — and
    a resource the restore reported as learning-skipped must actually be
    OUT of learning mode (a skip that silently fell back to learning
    would pass the capacity checks while eating the whole cold-path
    window the plan's reconverge budget excludes).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Tuple

EPS = 1e-6


@dataclass(frozen=True)
class Violation:
    tick: int
    invariant: str
    subject: str
    detail: str

    def as_log(self) -> list:
        return ["violation", self.invariant, self.subject, self.detail]


class _Window:
    """Max over observations within a trailing virtual-time window."""

    def __init__(self, span: float):
        self.span = span
        self._obs: List[Tuple[float, float]] = []  # (time, value)

    def observe(self, now: float, value: float) -> float:
        self._obs.append((now, value))
        cutoff = now - self.span
        self._obs = [(t, v) for t, v in self._obs if t >= cutoff]
        return max(v for _, v in self._obs)


class InvariantChecker:
    def __init__(self, clock, *, lease_length: float):
        self._clock = clock
        self._lease_length = lease_length
        self._cap_windows: Dict[str, _Window] = {}
        # (resource, client) -> recent server-granted values
        self._grant_windows: Dict[Tuple[str, str], List[Tuple[float, float]]] = {}
        # (server, resource, client) -> last seen expiry (monotonicity)
        self._expiries: Dict[Tuple[str, str, str], float] = {}
        self._expiry_regressions: List = []
        # Restore summaries already validated (by object identity: a
        # server keeps one summary per takeover).
        self._checked_restores: set = set()
        # Admission tallies only grow; report each offending key once,
        # not once per subsequent tick.
        self._reported_admission: set = set()

    # -- per-tick entry point ------------------------------------------

    def check_tick(
        self,
        tick: int,
        servers: Dict[str, object],       # logical name -> CapacityServer
        election_groups: List[List[str]], # names sharing one lock
        clients: List[object],            # chaos-driven Client objects
    ) -> List[Violation]:
        out: List[Violation] = []
        out += self._check_single_master(tick, servers, election_groups)
        out += self._check_capacity(tick, servers)
        out += self._check_restores(tick, servers)
        out += self._check_admission(tick, servers)
        self._record_grants(servers)
        out += self._check_lag_never_lead(tick, clients)
        return out

    # -- federation -----------------------------------------------------

    def check_federation(
        self,
        tick: int,
        shard_servers: Dict[str, object],
        straddle: Dict[str, float],
    ) -> List[Violation]:
        """The capacity-sum invariant of the federated tree: for every
        straddling resource, the grants outstanding across ALL shards
        sum to at most the configured capacity — on every tick,
        partition or not. No lease-window slack here: the reconciler's
        contract is that shares sum under capacity and a lost shard's
        share stays charged through its drain window, so the bound
        holds pointwise (doc/federation.md, "The invariant")."""
        out: List[Violation] = []
        for rid, capacity in straddle.items():
            total = 0.0
            holders = []
            for name, server in sorted(shard_servers.items()):
                res = server.resources.get(rid)
                if res is None:
                    continue
                res.store.clean()
                if res.store.sum_has:
                    holders.append(f"{name}={res.store.sum_has:.6f}")
                total += res.store.sum_has
            if total > capacity + EPS:
                out.append(Violation(
                    tick, "fed_capacity_sum", rid,
                    f"Σ shard grants {total:.6f} > configured "
                    f"capacity {capacity:.6f} ({', '.join(holders)})",
                ))
        return out

    # -- admission ------------------------------------------------------

    def _check_admission(self, tick, servers) -> List[Violation]:
        out = []
        for name, server in servers.items():
            adm = getattr(server, "_admission", None)
            if adm is None:
                continue
            gc_bands = [
                band for (method, band) in adm.tallies
                if method == "GetCapacity"
            ]
            top = max(gc_bands) if gc_bands else None
            for (method, band), counts in adm.tallies.items():
                if counts["shed"] == 0:
                    continue
                key = (name, method, band)
                if key in self._reported_admission:
                    continue
                if method in ("ReleaseCapacity", "GetServerCapacity"):
                    self._reported_admission.add(key)
                    out.append(Violation(
                        tick, "releases_never_shed",
                        f"{name}/{method}",
                        f"{counts['shed']} {method} RPC(s) shed — the "
                        "shed matrix forbids shedding this method",
                    ))
                elif (
                    method == "GetCapacity"
                    and band == top
                    and len(set(gc_bands)) > 1
                ):
                    self._reported_admission.add(key)
                    out.append(Violation(
                        tick, "top_band_floor", f"{name}/band{band}",
                        f"top band {band} shed {counts['shed']} "
                        "request(s) while lower bands existed to shed "
                        "first",
                    ))
        return out

    # -- warm restore ---------------------------------------------------

    def _check_restores(self, tick, servers) -> List[Violation]:
        out = []
        for name, server in servers.items():
            summary = getattr(server, "last_restore", None)
            if summary is None or id(summary) in self._checked_restores:
                continue
            self._checked_restores.add(id(summary))
            for rid, info in summary.get("resources", {}).items():
                if (
                    info["capacity"] > 0
                    and info["sum_has"] > info["capacity"] + EPS
                ):
                    out.append(Violation(
                        tick, "restore_capacity", f"{name}/{rid}",
                        f"restored sum(grants)={info['sum_has']:.6f} > "
                        f"capacity={info['capacity']:.6f}",
                    ))
                res = server.resources.get(rid)
                if (
                    info["learning"] == "skip"
                    and res is not None
                    and res.in_learning_mode
                ):
                    out.append(Violation(
                        tick, "warm_learning", f"{name}/{rid}",
                        "restore reported learning skipped but the "
                        "resource is in learning mode",
                    ))
        return out

    # -- single master --------------------------------------------------

    def _check_single_master(self, tick, servers, groups) -> List[Violation]:
        out = []
        for group in groups:
            masters = [n for n in group if servers[n].is_master]
            if len(masters) > 1:
                out.append(Violation(
                    tick, "single_master", ",".join(sorted(masters)),
                    f"{len(masters)} concurrent masters",
                ))
        return out

    # -- capacity -------------------------------------------------------

    def _check_capacity(self, tick, servers) -> List[Violation]:
        now = self._clock()
        out = []
        for name, server in servers.items():
            if not server.is_master:
                continue
            for rid, res in server.resources.items():
                res.store.clean()
                window = self._cap_windows.setdefault(
                    f"{name}/{rid}", _Window(self._lease_length)
                )
                allowed = window.observe(now, res.capacity)
                if res.in_learning_mode:
                    continue  # documented learning-mode slack
                total = res.store.sum_has
                if total > allowed + EPS:
                    out.append(Violation(
                        tick, "capacity", f"{name}/{rid}",
                        f"sum(grants)={total:.6f} > allowed={allowed:.6f}",
                    ))
        return out

    # -- lag but never lead ---------------------------------------------

    def _record_grants(self, servers) -> None:
        """Record every grant each master currently holds, so client
        beliefs can be validated against what was actually issued."""
        now = self._clock()
        cutoff = now - self._lease_length
        live_keys = set()
        for name, server in servers.items():
            for rid, res in server.resources.items():
                length = res._lease_length
                for client, lease in res.store.items():
                    key = (rid, client)
                    win = self._grant_windows.setdefault(key, [])
                    win.append((now, lease.has))
                    self._grant_windows[key] = [
                        (t, v) for t, v in win if t >= cutoff
                    ]
                    ekey = (name, rid, client)
                    live_keys.add(ekey)
                    prev = self._expiries.get(ekey)
                    # Monotonicity holds only under constant config: a
                    # re-templated lease_length (an intermediate's first
                    # parent exchange shortens the self-config default)
                    # legitimately re-anchors expiries.
                    last = None
                    if prev is not None and prev[1] == length:
                        last = prev[0]
                    if last is not None and lease.expiry < last - EPS:
                        # Flagged through check via the stored marker:
                        # expiry regressions are recorded here and
                        # surfaced by _check_lag_never_lead's sweep.
                        self._expiry_regressions.append(
                            (self._clock(), ekey, last, lease.expiry)
                        )
                    self._expiries[ekey] = (lease.expiry, length)
        # Leases released or lapsed may legitimately restart lower.
        for key in list(self._expiries):
            if key not in live_keys:
                del self._expiries[key]

    def _check_lag_never_lead(self, tick, clients) -> List[Violation]:
        out = []
        regressions, self._expiry_regressions = self._expiry_regressions, []
        for _, (name, rid, client), last, new in regressions:
            out.append(Violation(
                tick, "lease_monotonicity", f"{name}/{rid}/{client}",
                f"expiry moved backwards {last:.3f} -> {new:.3f}",
            ))
        for cl in clients:
            for rid, res in cl.resources.items():
                if res.lease is None:
                    # Outage fallback: the client serves safe capacity
                    # (or 0) by construction; nothing to lead with.
                    continue
                believed = res.lease.capacity
                issued = [
                    v for _, v in self._grant_windows.get((rid, cl.id), [])
                ]
                if not issued:
                    # The master's state was wiped (failover) and the
                    # client still holds a pre-wipe lease: allowed to
                    # lag until refresh or expiry.
                    continue
                if not any(abs(believed - v) <= EPS for v in issued):
                    out.append(Violation(
                        tick, "lag_never_lead", f"{rid}/{cl.id}",
                        f"client believes {believed:.6f}, never issued "
                        f"within the window (issued={sorted(set(round(v, 6) for v in issued))})",
                    ))
        return out
