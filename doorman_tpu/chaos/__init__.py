"""Deterministic fault injection and invariant checking.

See doc/chaos.md. The pieces:

  plan.py       FaultPlan / FaultEvent — the seeded, replayable artifact
  clock.py      ChaosClock — virtual time every component shares
  injectors.py  shims at the etcd / lease-KV / gRPC / solver seams
  invariants.py per-tick checkers (capacity, single-master, lag-never-
                lead, reconvergence)
  runner.py     drives the real stack through a plan, emits a verdict
  plans.py      shipped scenarios (master flap, etcd brownout, device
                tunnel outage, intermediate partition)
"""

from doorman_tpu.chaos.clock import ChaosClock
from doorman_tpu.chaos.injectors import (
    ChaosEtcdGateway,
    ChaosGrpcProxy,
    ChaosLeaseKV,
    FaultInjected,
    FaultState,
    PortInjector,
    SolverInjector,
    backend_probe_argv,
)
from doorman_tpu.chaos.invariants import InvariantChecker, Violation
from doorman_tpu.chaos.plan import FaultEvent, FaultPlan
from doorman_tpu.chaos.plans import PLANS, get_plan
from doorman_tpu.chaos.runner import ChaosRunner, SteppedElection, run_plan

__all__ = [
    "ChaosClock",
    "ChaosEtcdGateway",
    "ChaosGrpcProxy",
    "ChaosLeaseKV",
    "ChaosRunner",
    "FaultEvent",
    "FaultInjected",
    "FaultPlan",
    "FaultState",
    "InvariantChecker",
    "PLANS",
    "PortInjector",
    "SolverInjector",
    "SteppedElection",
    "Violation",
    "backend_probe_argv",
    "get_plan",
    "run_plan",
]
