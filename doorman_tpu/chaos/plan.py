"""Fault plans: the replayable artifact of a chaos run.

A FaultPlan is a seeded, timestamped (in TICKS of virtual time) list of
fault events plus the topology the run is driven against. Everything a
run needs is IN the plan — seed, topology, fault schedule, convergence
budget — so a failing run's plan serializes to JSON, ships in a bug
report, and replays byte-identically (tests/test_chaos_plan.py pins the
round trip; the runner pins the replayed event log).

Event model: an event STARTS a fault at `at_tick` for `duration_ticks`
ticks (0 = a one-shot action applied immediately, e.g. expiring the
election lock). Count-limited faults ("drop the next N calls") carry
the budget in params["calls"]; the injector consumes it. `target`
scopes the fault to one injector ("s0" — server s0's KV; "link:root" —
the intermediate<->root gRPC hop); "*" matches every injector of that
kind.
"""

from __future__ import annotations

import json
from dataclasses import asdict, dataclass, field
from typing import Dict, List

# Fault kinds the injectors understand (doorman_tpu/chaos/injectors.py).
KINDS = frozenset(
    {
        # election / lease-KV seam (ChaosLeaseKV)
        "kv_drop",          # every KV round-trip raises (transport fault)
        "kv_delay",         # params: {"seconds": s} real delay per call
        "kv_expire_lock",   # action: drop the lock as if its TTL lapsed
        # etcd gateway seam (ChaosEtcdGateway over the real HTTP dialect)
        "etcd_drop",        # params: {"calls": n} drop the next n round-trips
                            # (omit for "all while active")
        "etcd_delay",       # params: {"seconds": s}
        "etcd_watch_stall", # watches hang until their timeout
        # gRPC seam (ChaosGrpcProxy between client<->server hops)
        "grpc_drop",        # abort UNAVAILABLE
        "grpc_delay",       # params: {"seconds": s}
        "grpc_not_master",  # spurious NOT_MASTER: params: {"master": addr}
        # solver / backend seam (SolverInjector)
        "solver_error",     # device solve raises (tunnel down)
        "solver_slow",      # params: {"seconds": s} per solve
        "resident_overflow",# params: {"calls": n} ResidentOverflow per step
        "grant_corrupt",    # silently scale one row of the solve's
                            # grants; params: {"row": i, "factor": f}
                            # — the shadow auditor's prey
        # host seam
        "port_bind",        # action: bind a loopback port (stale server)
        "backend_probe_fail",  # utils.backend probe argv fails
        # client seam (driven by the runner, not an injector): a swarm
        # of extra clients hammers GetCapacity refreshes every tick
        # while active; params: {"clients": n, "wants": w,
        # "priority": band}. Storm clients release on heal.
        "client_storm",
        # federation seam (driven by the runner's federated beat):
        # target shard server (e.g. "s1") is unreachable from the
        # straddle reconciler while active — its share stops renewing,
        # coasts to its ttl, then the shard decays to zero capacity.
        "shard_partition",
        # fleet seam (setup["federated"]["fleet"] arms a
        # FleetController over the provisioned servers): action —
        # publish a new routing epoch serving params["to"] shards of
        # the pool. Grow re-splits the straddle shares to include the
        # new shard; shrink freezes the departed shard's share and
        # drains it through expiry + lease length (the deliberate
        # partition). params: {"to": m}.
        "fleet_reshard",
        # serving-plane seam (setup["frontend_workers"] arms an inline
        # frontend pool; doorman_tpu/frontend/):
        # a listener worker dies while active — its WatchCapacity
        # streams reset to a redirect (never a silent lapse), its
        # stream shards reassign to survivors; the worker restarts at
        # heal with a fresh ring cursor (no replay). params:
        # {"worker": i}.
        "worker_crash",
        # a worker's ring pump freezes while active (the worker is
        # alive but not draining its ring); a long enough stall laps
        # the reader and the resume pump resets every held stream
        # loudly. params: {"worker": i}.
        "ring_stall",
    }
)


@dataclass(frozen=True)
class FaultEvent:
    at_tick: int
    kind: str
    target: str = "*"
    duration_ticks: int = 1  # 0 = instantaneous action
    params: Dict = field(default_factory=dict)

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}")
        if self.at_tick < 0 or self.duration_ticks < 0:
            raise ValueError("at_tick/duration_ticks must be >= 0")


@dataclass(frozen=True)
class FaultPlan:
    """A complete, self-contained chaos scenario."""

    name: str
    seed: int
    # Topology + config the runner builds (see runner.ChaosRunner):
    #   servers: int            root election candidates (>=1)
    #   clients: int            client count
    #   wants: [float]          per-client demand (len == clients)
    #   capacity: float         the one resource's capacity
    #   safe_capacity: float    optional
    #   mode: "immediate"|"batch"
    #   lease_length/refresh_interval/learning_mode_duration: seconds
    #   election_ttl: float     virtual seconds
    #   intermediate: bool      add an intermediate hop clients attach to
    #   persist: bool           shared snapshot+journal backend across
    #                           the candidates (warm master takeover)
    #   snapshot_interval: float  virtual seconds between snapshots
    setup: Dict
    events: List[FaultEvent] = field(default_factory=list)
    warmup_ticks: int = 5      # fault-free ticks before the first event;
                               # the baseline allocation snapshots here
    total_ticks: int = 30      # ticks driven with the fault schedule
    reconverge_ticks: int = 10 # post-heal budget to match the baseline
    tick_interval: float = 1.0 # virtual seconds per tick

    def __post_init__(self):
        for ev in self.events:
            if ev.at_tick < self.warmup_ticks:
                raise ValueError(
                    f"event {ev.kind!r} at tick {ev.at_tick} lands inside "
                    f"the warmup ({self.warmup_ticks} ticks): the baseline "
                    "snapshot must be fault-free"
                )

    # -- schedule helpers ----------------------------------------------

    def events_at(self, tick: int) -> List[FaultEvent]:
        return [ev for ev in self.events if ev.at_tick == tick]

    @property
    def heal_tick(self) -> int:
        """First tick with every fault expired (actions count as their
        start tick)."""
        end = self.warmup_ticks
        for ev in self.events:
            end = max(end, ev.at_tick + ev.duration_ticks)
        return end

    # -- serialization --------------------------------------------------
    # Canonical form: sorted keys, no whitespace variance. to_json is a
    # fixpoint of from_json∘to_json — the replay artifact is byte-stable.

    def to_dict(self) -> Dict:
        d = asdict(self)
        d["events"] = [asdict(ev) for ev in self.events]
        return d

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), sort_keys=True,
                          separators=(",", ":")) + "\n"

    @classmethod
    def from_dict(cls, d: Dict) -> "FaultPlan":
        d = dict(d)
        d["events"] = [FaultEvent(**ev) for ev in d.get("events", [])]
        return cls(**d)

    @classmethod
    def from_json(cls, text: str) -> "FaultPlan":
        return cls.from_dict(json.loads(text))

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.to_json())

    @classmethod
    def load(cls, path: str) -> "FaultPlan":
        with open(path) as f:
            return cls.from_json(f.read())
