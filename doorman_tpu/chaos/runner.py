"""The chaos runner: drive the real stack through a FaultPlan.

Everything runs in-process on the CPU backend, but nothing is mocked:
real CapacityServer instances serve real gRPC on loopback (through
ChaosGrpcProxy hops), real Client instances refresh leases, the
election runs the real TTL-lock protocol over a LeaseKV, and batch
servers run the real device solve. What makes a run DETERMINISTIC is
that no component owns a timer: the runner advances one shared
ChaosClock tick by tick and explicitly steps every periodic loop
(election renewal, parent refresh, batch tick, client refresh) in a
fixed order, so the same plan + seed replays the same event log
byte-for-byte.

Stepping an election rather than running KVElection's sleep-based loops
keeps the protocol (campaign with acquire, renew every ttl/3, lose on
failed renewal, broadcast the holder) and the EtcdKV renewal-retry
tolerance (one transient transport failure retries; definite losses
never do) while moving the cadence into virtual time.
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import random
from typing import Dict, List, Optional

from doorman_tpu.chaos.clock import ChaosClock
from doorman_tpu.chaos.injectors import (
    ChaosGrpcProxy,
    ChaosLeaseKV,
    FaultInjected,
    FaultState,
    PortInjector,
    SolverInjector,
)
from doorman_tpu.chaos.invariants import InvariantChecker, Violation
from doorman_tpu.chaos.plan import FaultPlan
from doorman_tpu.client.client import Client
from doorman_tpu.client.connection import Connection
from doorman_tpu.obs import metrics as metrics_mod
from doorman_tpu.obs import slo as slo_mod
from doorman_tpu.obs import trace as trace_mod
from doorman_tpu.obs.detect import AnomalyDetector
from doorman_tpu.obs.flightrec import FlightRecorder, store_digest
from doorman_tpu.obs.history import HistoryStore
from doorman_tpu.server.config import parse_yaml_config
from doorman_tpu.server.election import (
    Election,
    InMemoryKV,
    TrivialElection,
    shard_lock_key,
)
from doorman_tpu.server.server import CapacityServer

LOCK = "/chaos/master"
RESOURCE = "r0"
# Events of these kinds happen once when applied, instead of arming a
# fault window on the switchboard.
ACTIONS = frozenset({"kv_expire_lock", "port_bind", "fleet_reshard"})


class SteppedElection(Election):
    """KVElection's TTL-lock state machine, driven by explicit step()
    calls in virtual time (see module docstring)."""

    def __init__(self, kv, lock: str, *, ttl: float, clock):
        self._kv = kv
        self._lock = lock
        self._ttl = ttl
        self._clock = clock
        self._id: Optional[str] = None
        self._cb_master = None
        self._cb_current = None
        self.is_master = False
        self._next_renew = 0.0
        self._last_current: Optional[str] = None

    def __str__(self) -> str:
        return f"stepped kv lock: {self._lock} (ttl {self._ttl}s)"

    async def run(self, id, on_is_master, on_current) -> None:
        self._id = id
        self._cb_master = on_is_master
        self._cb_current = on_current

    async def step(self, *, campaign: bool = True) -> None:
        now = self._clock()
        if self.is_master:
            if now >= self._next_renew:
                if await self._refresh_with_retry():
                    self._next_renew = now + self._ttl / 3.0
                else:
                    self.is_master = False
                    await self._cb_master(False)
        elif campaign:
            try:
                won = await self._kv.acquire(self._lock, self._id, self._ttl)
            except FaultInjected:
                won = False
            if won:
                self.is_master = True
                self._next_renew = now + self._ttl / 3.0
                await self._cb_master(True)
        # The watcher half: broadcast the current holder. A dropped
        # read keeps the last known value (exactly what a partitioned
        # watcher would believe).
        try:
            current = await self._kv.get(self._lock) or ""
        except FaultInjected:
            current = self._last_current or ""
        if current != self._last_current:
            self._last_current = current
            await self._cb_current(current)

    async def abdicate(self) -> None:
        """Graceful step-down (a rolling deploy's drain): flip the
        mastership state and tell the server, without touching the KV —
        the caller decides whether the lock is also released (expire)
        or left to lapse. With step(campaign=False) the candidate stays
        out of the next election until it rejoins."""
        if self.is_master:
            self.is_master = False
            await self._cb_master(False)

    async def _refresh_with_retry(self) -> bool:
        """One transient transport failure retries within the renewal
        window (the stepped mirror of EtcdKV.refresh's tolerance); a
        second failure — or a definite loss — reads as mastership
        lost."""
        for attempt in range(2):
            try:
                return await self._kv.refresh(self._lock, self._id, self._ttl)
            except FaultInjected:
                pass
        return False


async def _cancel_background(server: CapacityServer) -> None:
    """The runner owns all cadence: server-internal timer loops (batch
    tick, parent updater) must not race the stepped schedule."""
    for t in server._tasks:
        t.cancel()
    for t in server._tasks:
        try:
            await t
        except (asyncio.CancelledError, Exception):
            pass
    server._tasks.clear()


class ChaosRunner:
    """Builds the plan's topology, drives it tick by tick, and returns
    a JSON-able verdict."""

    def __init__(self, plan: FaultPlan):
        self.plan = plan
        self.clock = ChaosClock()
        self.state = FaultState(plan.seed)
        self.ports = PortInjector()
        self.bound_ports: List[int] = []
        self.servers: Dict[str, CapacityServer] = {}
        self.proxies: Dict[str, ChaosGrpcProxy] = {}
        self.elections: Dict[str, SteppedElection] = {}
        self.clients: List[Client] = []
        # Streaming leg (setup["streams"]): WatchCapacity subscribers
        # stepped deterministically each tick (stream_step: drain the
        # pushes already in flight, poll fallback when the stream is
        # down or silent); they ride every invariant check but stay out
        # of the baseline/convergence snapshots like the storm swarm.
        self.stream_clients: List[Client] = []
        # Storm swarm (client_storm events): created when the storm
        # arms, refreshed every storm tick AFTER the base clients,
        # closed (releasing) when it clears.
        self.storm_clients: List[Client] = []
        # Serving-plane pools (setup["frontend_workers"]): an inline
        # frontend pool per streaming server — pushes ride per-worker
        # rings and a worker-core pump on the virtual clock, so the
        # worker_crash / ring_stall fault kinds drive the same code the
        # real listener processes run, byte-stably.
        self.frontends: Dict[str, object] = {}
        self._fe_crashed: Dict[str, set] = {}
        self._fe_stalled: Dict[str, set] = {}
        self._frontend_final: Dict[str, dict] = {}
        self._attach: str = ""
        self._admission_last: Dict[str, tuple] = {}
        self.kv: Optional[InMemoryKV] = None
        # Shared persistence backend (setup["persist"]): every election
        # candidate snapshots/journals to the SAME store, modeling the
        # shared filesystem / etcd prefix a real warm-takeover
        # deployment needs.
        self.persist_backend = None
        # Federated topology (setup["federated"]): each server is a
        # root shard with its OWN election lock (shard_lock_key) and
        # its own persist namespace; the coordinator runs the straddle
        # reconciliation beat in the stepped schedule, and the
        # shard_partition fault kind blocks one shard from it.
        self.federation = None  # Optional[federation.FederatedRoots]
        self._shard_backends: Dict[int, object] = {}
        # Blast-radius guard: healthy clients' capacities snapshotted
        # at partition start; a healthy client dropping below it while
        # the fault is active is a shard_blast_radius violation.
        self._fed_guard: Optional[Dict[str, float]] = None
        self._fed_last_shares: Dict[str, dict] = {}
        self._logged_restores: set = set()
        self.log: List[list] = []
        self.violations: List[Violation] = []
        # The run's black box: one record per VIRTUAL tick, built only
        # from deterministic fields (virtual time, masters, admission
        # tallies, store digests) so a violation dump is byte-stable
        # across replays of the same seeded plan. Dumped on the FIRST
        # violation (the trigger that needs explaining); the dump lands
        # in the verdict as `flightrec_dump`.
        self.flightrec = FlightRecorder(
            capacity=plan.total_ticks + 8,
            component=f"chaos:{plan.name}",
            clock=self.clock,
        )
        self.flight_dump: Optional[dict] = None
        # The same per-tick records flow into an in-memory history
        # store (no directory: ring + decimated tiers only), so the
        # verdict can carry the anomaly detector's windowed view of
        # the run — deterministic, because the records are.
        self.history = HistoryStore(
            ring=plan.total_ticks + 8,
            component=f"chaos:{plan.name}",
            clock=self.clock,
        )
        # Last shadow-audit divergence count seen per server, for
        # event-log deltas the tick they fire.
        self._audit_last: Dict[str, int] = {}
        # Fault / violation tallies in the default registry, so a chaos
        # run's damage shows on the same /metrics surface as everything
        # else (and soaks can assert on them).
        reg = metrics_mod.default_registry()
        self._faults_counter = reg.counter(
            "doorman_chaos_faults_injected",
            "Fault events applied by the chaos runner, by kind.",
            labels=("kind",),
        )
        self._violations_counter = reg.counter(
            "doorman_chaos_invariant_violations",
            "Invariant violations observed by the chaos runner.",
            labels=("invariant",),
        )

    def _record_violation(self, v: Violation) -> None:
        self.violations.append(v)
        self._violations_counter.inc(v.invariant)

    # -- setup ----------------------------------------------------------

    def _config_yaml(self) -> str:
        s = self.plan.setup
        safe = s.get("safe_capacity")
        safe_line = f"  safe_capacity: {safe}\n" if safe is not None else ""
        # algorithm_variant selects a portfolio lane sharing a wire
        # kind (e.g. FAIR_SHARE + maxmin -> MAX_MIN_FAIR); it rides the
        # config's `variant` parameter like any real deployment would.
        variant = s.get("algorithm_variant")
        variant_part = (
            ", parameters: [{name: variant, value: "
            f"{variant}" "}]"
            if variant
            else ""
        )
        return (
            "resources:\n"
            f"- identifier_glob: \"*\"\n"
            f"  capacity: {s.get('capacity', 100)}\n"
            + safe_line
            + "  algorithm: {"
            + f"kind: {s.get('algorithm', 'PROPORTIONAL_SHARE')}, "
            + f"lease_length: {s.get('lease_length', 60)}, "
            + f"refresh_interval: {s.get('refresh_interval', 1)}, "
            + f"learning_mode_duration: {s.get('learning_mode_duration', 3)}"
            + variant_part
            + "}\n"
        )

    async def _setup(self) -> None:
        s = self.plan.setup
        self.kv = InMemoryKV(clock=self.clock)
        config = parse_yaml_config(self._config_yaml())
        if s.get("persist"):
            from doorman_tpu.persist.backend import MemoryBackend

            self.persist_backend = MemoryBackend()
        fed = s.get("federated")
        for i in range(int(s.get("servers", 1))):
            name = f"s{i}"
            proxy = ChaosGrpcProxy(self.state, link=f"link:{name}")
            await proxy.start()
            # Federated: each server IS a shard and campaigns for its
            # own shard-suffixed lock — N concurrent masters by design.
            lock = shard_lock_key(LOCK, i) if fed else LOCK
            election = SteppedElection(
                ChaosLeaseKV(self.kv, self.state, name),
                lock, ttl=float(s.get("election_ttl", 3.0)),
                clock=self.clock,
            )
            persist = None
            backend = self.persist_backend
            if backend is not None and fed:
                # Per-shard durability namespace: candidates of one
                # shard share a backend; shards never share.
                from doorman_tpu.persist.backend import MemoryBackend

                backend = self._shard_backends.setdefault(
                    i, MemoryBackend()
                )
            if backend is not None:
                from doorman_tpu.persist import PersistManager

                persist = PersistManager(
                    backend,
                    snapshot_interval=float(
                        s.get("snapshot_interval", 3.0)
                    ),
                    flush_interval=self.plan.tick_interval,
                    clock=self.clock,
                )
            admission = None
            if s.get("admission"):
                from doorman_tpu.admission import Admission

                a = dict(s["admission"])
                # The plan's seeded RNG is the run's ONLY randomness
                # (FaultState docstring); admission's admit draws come
                # from it so shed decisions replay byte-identically.
                admission = Admission(
                    coalesce_window=float(a.pop("coalesce_window", 0.0)),
                    clock=self.clock,
                    rng=self.state.rng,
                    **a,
                )
            server = CapacityServer(
                proxy.address, election,
                mode=s.get("mode", "immediate"),
                tick_interval=self.plan.tick_interval,
                minimum_refresh_interval=0.0,
                clock=self.clock,
                native_store=bool(s.get("native_store", False)),
                persist=persist,
                admission=admission,
                # Streaming leg: every candidate serves WatchCapacity
                # (the runner drives the fanout beat explicitly).
                stream_push=bool(s.get("streams")),
                stream_shards=int(s.get("stream_shards", 1)),
                shard=i if fed else None,
                # Shadow audit (setup["audit_sample"]): comparisons run
                # INLINE on the virtual clock so divergence counts land
                # on deterministic ticks and the verdict stays
                # byte-stable across replays.
                audit_sample=int(s.get("audit_sample", 0)),
                audit_inline=True,
            )
            SolverInjector(self.state, name).install(server)
            await server.start(0, host="127.0.0.1")
            await _cancel_background(server)
            proxy.backend = server
            await server.load_config(config)
            if s.get("frontend_workers") and s.get("streams"):
                self.frontends[name] = server.attach_frontend(
                    int(s["frontend_workers"]),
                    ring_bytes=int(s.get("frontend_ring", 1 << 20)),
                )
                self._fe_crashed[name] = set()
                self._fe_stalled[name] = set()
            self.servers[name] = server
            self.proxies[name] = proxy
            self.elections[name] = election

        if fed and fed.get("fleet"):
            # Fleet runtime: every configured server is PROVISIONED,
            # only the first `active` serve the beat; fleet_reshard
            # events move the boundary live.
            from doorman_tpu.fleet import FleetController

            self.federation = FleetController(
                {
                    i: self.servers[f"s{i}"]
                    for i in range(int(s.get("servers", 1)))
                },
                straddle=fed.get("straddle", ()),
                overrides=fed.get("overrides"),
                active=fed.get("active"),
                share_ttl=float(fed.get("share_ttl", 2.0)),
                clock=self.clock,
            )
        elif fed:
            from doorman_tpu.federation import FederatedRoots, ShardRouter

            router = ShardRouter(
                int(s.get("servers", 1)),
                straddle=fed.get("straddle", ()),
                overrides=fed.get("overrides"),
            )
            self.federation = FederatedRoots(
                router,
                {
                    i: self.servers[f"s{i}"]
                    for i in range(router.n_shards)
                },
                share_ttl=float(fed.get("share_ttl", 2.0)),
                clock=self.clock,
            )

        attach = self.proxies["s0"].address
        if s.get("intermediate"):
            proxy = ChaosGrpcProxy(self.state, link="link:inter")
            await proxy.start()
            inter = CapacityServer(
                proxy.address, TrivialElection(),
                parent_addr=self.proxies["s0"].address,
                mode="immediate",
                minimum_refresh_interval=0.0,
                clock=self.clock,
            )
            # Bounded parent refreshes: the runner retries next tick
            # instead of letting the connection retry-forever inside one.
            inter._parent_conn = Connection(
                inter.parent_addr, minimum_refresh_interval=0.0,
                max_retries=0,
            )
            await inter.start(0, host="127.0.0.1")
            await _cancel_background(inter)
            proxy.backend = inter
            if s.get("skip_intermediate_learning", True):
                # The self-config default template carries a 20s
                # learning window; an intermediate that just booted
                # (not failed over) has no state to relearn.
                inter.became_master_at -= 10_000.0
            self.servers["inter"] = inter
            self.proxies["inter"] = proxy
            attach = proxy.address

        wants = s.get("wants") or [
            10.0 * (i + 1) for i in range(int(s.get("clients", 3)))
        ]
        priorities = s.get("priorities") or [0] * len(wants)
        # Federated: clients place onto shards per the plan (the
        # straddling resource is served by EVERY shard; which one a
        # client talks to is its locality).
        client_shards = (fed or {}).get("client_shards") or [None] * len(
            wants
        )
        self._attach = attach
        self._client_shard: Dict[str, Optional[int]] = {}
        for i, (w, p) in enumerate(zip(wants, priorities)):
            addr = attach
            shard = client_shards[i]
            if shard is not None:
                addr = self.proxies[f"s{int(shard)}"].address
            client = Client(
                addr, f"c{i}", minimum_refresh_interval=0.0,
                max_retries=0, clock=self.clock,
            )
            self._client_shard[client.id] = (
                int(shard) if shard is not None else None
            )
            await client.resource(RESOURCE, float(w), priority=int(p))
            self.clients.append(client)
        stream_wants = s.get("stream_wants") or [
            10.0 for _ in range(int(s.get("streams", 0)))
        ]
        for i, w in enumerate(stream_wants[: int(s.get("streams", 0))]):
            # Seeded retry jitter: shed/backoff pacing replays exactly.
            client = Client(
                attach, f"w{i}", minimum_refresh_interval=0.0,
                max_retries=0, clock=self.clock, stream=True,
                retry_rng=random.Random(self.plan.seed * 1000 + i),
            )
            await client.resource(RESOURCE, float(w))
            self.stream_clients.append(client)

    async def _teardown(self) -> None:
        # Snapshot serving-plane status before the pools close (their
        # ring buffers are released by server.stop()).
        self._frontend_final = {
            name: pool.status()
            for name, pool in sorted(self.frontends.items())
        }
        for client in self.clients + self.stream_clients + self.storm_clients:
            try:
                await client.close()
            except Exception:
                pass
        for proxy in self.proxies.values():
            await proxy.stop()
        for server in self.servers.values():
            try:
                await server.stop()
            except Exception:
                pass
        self.ports.release_all()

    # -- the drive ------------------------------------------------------

    def _apply_event(self, ev, tick: int) -> None:
        if ev.kind == "kv_expire_lock":
            self.kv.expire(LOCK)
        elif ev.kind == "port_bind":
            self.bound_ports.append(self.ports.bind())
        elif ev.kind == "fleet_reshard":
            # Live reshard: publish the new epoch now; this tick's
            # reconcile beat (which runs after events apply) already
            # sees the new active set.
            change = self.federation.reshard(int(ev.params["to"]))
            self.log.append([tick, "fleet_epoch", change.as_log()])
        else:
            self.state.start(ev)
        self._faults_counter.inc(ev.kind)
        self.log.append(
            [tick, "fault", ev.kind, ev.target, ev.duration_ticks]
        )

    def _log_restores(self, tick: int) -> None:
        """Surface each master-takeover restore in the event log (once
        per summary object), keeping the entry deterministic: mode,
        lease count, journal completeness and learning outcomes only —
        no wall-clock or backend specifics."""
        for name, server in self.servers.items():
            lr = getattr(server, "last_restore", None)
            if lr is None or id(lr) in self._logged_restores:
                continue
            self._logged_restores.add(id(lr))
            learning = sorted(
                [rid, info["learning"]]
                for rid, info in lr.get("resources", {}).items()
            )
            self.log.append([
                tick, "restore", name, lr["mode"],
                lr["leases_restored"], bool(lr["clean_down"]), learning,
            ])

    async def _drive_storm(self, tick: int) -> None:
        """The client_storm seam: while the event is active, a swarm of
        extra clients refreshes every tick (after the base clients, so
        the baseline population is first through each admission
        window); when it clears, the swarm closes — releasing its
        leases through the never-shed ReleaseCapacity path."""
        params = self.state.active("client_storm", "*")
        if params is not None:
            if not self.storm_clients:
                n = int(params.get("clients", 10))
                wants = float(params.get("wants", 10.0))
                priority = int(params.get("priority", 0))
                for i in range(n):
                    client = Client(
                        self._attach, f"storm{i}",
                        minimum_refresh_interval=0.0,
                        max_retries=0, clock=self.clock,
                    )
                    await client.resource(
                        RESOURCE, wants, priority=priority
                    )
                    self.storm_clients.append(client)
                self.log.append([tick, "storm_start", n])
            admitted = 0
            for client in self.storm_clients:
                if await client.refresh_once():
                    admitted += 1
            self.log.append(
                [tick, "storm", admitted, len(self.storm_clients)]
            )
        elif self.storm_clients:
            swarm, self.storm_clients = self.storm_clients, []
            for client in swarm:
                await client.close()
            self.log.append([tick, "storm_end", len(swarm)])

    def _drive_frontend(self, tick: int) -> None:
        """The serving-plane fault seam: translate active worker_crash
        / ring_stall events into inline-pool faults, and heal them when
        the events clear. A crash drops the worker's streams to
        redirects the same tick (the clients' next stream_step chases
        them); a restore brings the worker back with a fresh ring
        cursor. A stall freezes the worker's pump; the resume pump
        surfaces the lap and resets loudly (logged by _drive_streams'
        pump entry)."""
        for name, pool in self.frontends.items():
            crashed = self._fe_crashed[name]
            params = self.state.active("worker_crash", name)
            if params is not None:
                worker = int(params.get("worker", 0))
                if worker not in crashed:
                    crashed.add(worker)
                    dropped = pool.crash(worker)
                    self.log.append(
                        [tick, "worker_crash", name, worker, dropped]
                    )
            elif crashed:
                for worker in sorted(crashed):
                    pool.restore(worker)
                    self.log.append(
                        [tick, "worker_restore", name, worker]
                    )
                crashed.clear()
            stalled = self._fe_stalled[name]
            params = self.state.active("ring_stall", name)
            if params is not None:
                worker = int(params.get("worker", 0))
                if worker not in stalled:
                    stalled.add(worker)
                    pool.stall(worker)
                    self.log.append([tick, "ring_stall", name, worker])
            elif stalled:
                for worker in sorted(stalled):
                    pool.unstall(worker)
                    self.log.append([tick, "ring_resume", name, worker])
                stalled.clear()

    async def _drive_streams(self, tick: int) -> None:
        """The streaming leg's per-tick beat: the master fans out lease
        deltas at the tick edge (the runner owns the cadence — server
        background loops are cancelled), then each stream client takes
        one deterministic stream_step (drain pushes, chase redirects,
        fall back to a poll while the stream is down or silent). One
        event-log entry per client per tick where anything happened, so
        the flap's terminate→redirect→poll→re-establish arc is pinned
        byte-for-byte by the determinism check. With a frontend pool
        attached, the fanout's ring frames are pumped to subscribers
        here (where a real worker's poll loop would have woken); pump
        anomalies — laps, deadline-wheel resets — get their own log
        entry."""
        if not self.stream_clients:
            return
        for server in self.servers.values():
            server.push_streams()
        for name, pool in self.frontends.items():
            stats = pool.pump_all()
            if stats["lapped"] or stats["corrupt"] or stats["stalled"]:
                self.log.append([
                    tick, "frontend_pump", name, stats["frames"],
                    stats["lapped"], stats["corrupt"], stats["stalled"],
                ])
        for client in self.stream_clients:
            out = await client.stream_step(drain_timeout=0.05)
            if out["events"] or out["pushes"]:
                self.log.append([
                    tick, "stream", client.id,
                    ",".join(out["events"]) or "push",
                    out["pushes"],
                ])

    def _drive_federation(self, tick: int) -> None:
        """The federated beat: translate active shard_partition faults
        into the coordinator's blocked set, run one reconciliation, and
        log share movements deterministically. Also arms/checks the
        blast-radius guard: while a partition is active, no client of a
        HEALTHY shard may fall below its pre-fault capacity — the whole
        point of per-shard mastership is that one shard's failure is
        one shard's outage."""
        if self.federation is None:
            return
        blocked = {
            shard
            for shard in range(self.federation.router.n_shards)
            if self.state.active("shard_partition", f"s{shard}")
            is not None
        }
        if blocked and not self.federation.blocked:
            # Partition begins: mark the timeline (the trace ring is
            # outside the verdict digests, so replays stay byte-stable)
            # and snapshot the healthy population.
            trace_mod.default_tracer().instant(
                "federation.partition", cat="chaos",
                args={"tick": tick, "shards": sorted(blocked)},
            )
            self._fed_guard = {
                key: value
                for key, value in self._snapshot().items()
                if self._client_shard.get(key.split("/", 1)[0])
                not in blocked
            }
        elif not blocked:
            self._fed_guard = None
        self.federation.blocked = blocked
        installed = self.federation.reconcile_once()
        for rid, shares in sorted(installed.items()):
            rounded = [
                [shard, round(value, 6)]
                for shard, value in sorted(shares.items())
            ]
            if self._fed_last_shares.get(rid) != rounded:
                self._fed_last_shares[rid] = rounded
                self.log.append([tick, "straddle", rid, rounded])

    def _check_blast_radius(self, tick: int) -> List[Violation]:
        """Healthy-shard clients must ride through a sibling shard's
        partition untouched (checked AFTER this tick's refreshes, like
        every other invariant)."""
        if self._fed_guard is None:
            return []
        out = []
        for key, value in self._snapshot().items():
            baseline = self._fed_guard.get(key)
            if baseline is not None and value < baseline - 1e-9:
                out.append(Violation(
                    tick, "shard_blast_radius", key,
                    f"healthy-shard client fell {baseline:.6f} -> "
                    f"{value:.6f} during a sibling shard's partition",
                ))
        return out

    def _log_admission(self, tick: int) -> None:
        """One deterministic event-log entry per server per tick where
        admission activity moved: GetCapacity admitted/shed deltas plus
        the controller level (rounded — the level is exact binary
        arithmetic on plan constants)."""
        for name, server in self.servers.items():
            adm = getattr(server, "_admission", None)
            if adm is None:
                continue
            admitted = shed = 0
            for (method, _band), counts in adm.tallies.items():
                if method == "GetCapacity":
                    admitted += counts["admitted"]
                    shed += counts["shed"]
            last = self._admission_last.get(name, (0, 0))
            if (admitted, shed) != last:
                self._admission_last[name] = (admitted, shed)
                self.log.append([
                    tick, "admission", name,
                    admitted - last[0], shed - last[1],
                    round(adm.controller.level, 6),
                ])

    def _flight_record(self, tick: int, masters: tuple,
                       violations: List[Violation]) -> None:
        """One deterministic black-box record per virtual tick, and the
        violation-triggered dump (first violation only: that is the
        failure the dump exists to explain; later ones are in the ring
        of the same dump or the event log)."""
        rec: dict = {
            "t": self.clock(),
            "tick": tick,
            "masters": list(masters),
            "digests": {
                name: store_digest(server.resources)
                for name, server in sorted(self.servers.items())
            },
        }
        admission = {}
        persist_seq = {}
        for name, server in sorted(self.servers.items()):
            adm = getattr(server, "_admission", None)
            if adm is not None:
                admitted = 0
                shed_by_band: Dict[str, int] = {}
                for (method, band), counts in adm.tallies.items():
                    if method != "GetCapacity":
                        continue
                    admitted += counts["admitted"]
                    if counts["shed"]:
                        shed_by_band[str(band)] = counts["shed"]
                admission[name] = {
                    "level": round(adm.controller.level, 6),
                    "admitted": admitted,
                    "shed_by_band": shed_by_band,
                }
            if server._persist is not None:
                persist_seq[name] = server._persist.journal.seq
        streams = {}
        for name, server in sorted(self.servers.items()):
            if server._streams is not None:
                # Per-tick stream-push load (registry counters reset on
                # read; chaos servers never run tick_once's recorder,
                # so this is the only consumer): deterministic ints —
                # message bytes are protobuf-serialized plan state.
                st = server._streams.take_tick_stats()
                streams[name] = {
                    "subscribers": st["subscribers"],
                    "deltas_pushed": st["deltas_pushed"],
                    "push_bytes": st["push_bytes"],
                }
        if admission:
            rec["admission"] = admission
        if streams:
            rec["streams"] = streams
        if self.frontends:
            # The serving plane on the black box: held streams and
            # crash/restore counts per pool (counters of virtual-clock
            # events, so byte-stable).
            rec["frontend"] = {
                name: {
                    "held": pool.held(),
                    "crashes": pool.crashes,
                    "restores": pool.restores,
                }
                for name, pool in sorted(self.frontends.items())
            }
        if self.federation is not None:
            # The federation beat on the black box: each shard's
            # installed straddle capacity (deterministic plan
            # arithmetic) — a partition reads as one shard's value
            # freezing and then vanishing while the others hold.
            rec["straddle_capacity"] = {
                name: round(
                    server.fed_stats["straddle_capacity"], 6
                )
                for name, server in sorted(self.servers.items())
                if getattr(server, "shard", None) is not None
            }
        if persist_seq:
            rec["persist_seq"] = persist_seq
        audited = [
            server.shadow_audit
            for _, server in sorted(self.servers.items())
            if getattr(server, "shadow_audit", None) is not None
        ]
        if audited:
            rec["audit_divergence"] = sum(a.divergences for a in audited)
        if violations:
            rec["violations"] = [v.as_log() for v in violations]
        self.flightrec.record(**rec)
        self.history.append(dict(rec))
        if violations and self.flight_dump is None:
            self.flight_dump = self.flightrec.dump(
                f"invariant:{violations[0].invariant}"
            )

    def _slo_block(self, converged_at: Optional[int],
                   heal_tick: int) -> dict:
        """Machine-readable SLO verdicts for the run: reconvergence
        ticks vs the plan's budget, and — on admission-enabled plans —
        the top-band goodput floor with the per-band tallies embedded.
        Deltas vs prior rounds come from the trajectory comparator
        (None until a prior BENCH round embedded the same verdict)."""
        plan = self.plan
        specs = [slo_mod.reconvergence_spec(
            plan.reconverge_ticks, name=f"{plan.name}:reconverge_ticks"
        )]
        band_tallies: Dict[int, Dict[str, int]] = {}
        for server in self.servers.values():
            adm = getattr(server, "_admission", None)
            if adm is None:
                continue
            for (method, band), counts in adm.tallies.items():
                if method != "GetCapacity":
                    continue
                entry = band_tallies.setdefault(
                    int(band), {"admitted": 0, "shed": 0, "fast_fail": 0}
                )
                for key in entry:
                    entry[key] += counts.get(key, 0)
        if band_tallies:
            specs.append(slo_mod.top_band_goodput_spec(
                name=f"{plan.name}:top_band_goodput"
            ))
        scalars = {}
        if converged_at is not None:
            scalars["reconverge_ticks"] = float(converged_at - heal_tick)
        verdicts = slo_mod.SloEngine(specs).evaluate(
            slo_mod.SloInputs(scalars=scalars, band_tallies=band_tallies)
        )
        for v in verdicts:
            if (
                v["slo"].endswith(":reconverge_ticks")
                and v["status"] == "no_data"
            ):
                # Never reconverged is a hard fail, not missing data.
                v["status"] = "fail"
                v["detail"] = {"note": "no reconvergence within the run"}
        comparator = slo_mod.TrajectoryComparator()
        for v in verdicts:
            v["delta_vs_prev"] = comparator.slo_delta(v)
        return {
            "ok": all(v["status"] != "fail" for v in verdicts),
            "verdicts": verdicts,
        }

    def _detect_block(self) -> Optional[dict]:
        """Replay the run's history records through the anomaly
        detector: a zero floor on the audit-divergence count (any
        nonzero value is anomalous, no warmup needed) plus a robust-z
        watch on each admission controller's level. Pure sorted-list
        arithmetic over deterministic records, so the block is
        byte-stable across replays. None when the plan arms neither
        the auditor nor admission."""
        recs = self.history.records()
        fields: List[str] = []
        if any("audit_divergence" in r for r in recs):
            fields.append("audit_divergence")
        adm_servers = sorted(
            {n for r in recs for n in r.get("admission", {})}
        )
        fields.extend(f"admission.{n}.level" for n in adm_servers)
        if not fields:
            return None
        return AnomalyDetector.scan_records(
            recs, tuple(fields), floors={"audit_divergence": 0.0}
        )

    def _snapshot(self) -> Dict[str, float]:
        return {
            f"{cl.id}/{rid}": res.current_capacity()
            for cl in self.clients
            for rid, res in cl.resources.items()
        }

    @staticmethod
    def _matches(a: Dict[str, float], b: Dict[str, float]) -> bool:
        return a.keys() == b.keys() and all(
            abs(a[k] - b[k]) <= 1e-9 for k in a
        )

    async def run(self) -> dict:
        plan = self.plan
        await self._setup()
        try:
            checker = InvariantChecker(
                self.clock,
                lease_length=float(plan.setup.get("lease_length", 60)),
            )
            if self.federation is not None:
                # Per-shard mastership: each shard campaigns for its
                # own lock, so each is its own single-master group.
                groups = [
                    [n] for n in self.servers if n.startswith("s")
                ]
            else:
                groups = [[n for n in self.servers if n.startswith("s")]]
            heal_tick = plan.heal_tick
            baseline: Optional[Dict[str, float]] = None
            converged_at: Optional[int] = None
            degraded = False
            last_masters: tuple = ()
            inter = self.servers.get("inter")

            for tick in range(plan.total_ticks):
                self.state.begin_tick(tick)
                for ev in plan.events_at(tick):
                    self._apply_event(ev, tick)
                if tick == heal_tick and plan.events:
                    self.log.append([tick, "heal"])

                for election in self.elections.values():
                    await election.step()
                self._log_restores(tick)
                masters = tuple(sorted(
                    n for n, srv in self.servers.items()
                    if n != "inter" and srv.is_master
                ))
                if masters != last_masters:
                    last_masters = masters
                    self.log.append([tick, "master", list(masters)])

                self._drive_federation(tick)

                if inter is not None:
                    await inter._perform_parent_requests(0)

                for name, server in self.servers.items():
                    if (
                        server.mode == "batch"
                        and server.is_master
                        and server.resources
                    ):
                        try:
                            await server.tick_once()
                        except Exception as e:
                            self.log.append(
                                [tick, "tick_error", name, str(e)]
                            )

                # Shadow-audit deltas land in the event log the tick
                # they fire (chaos auditors run inline, so counts are
                # current once tick_once returns): seeded replays pin
                # WHEN the auditor caught the corruption.
                for name, server in sorted(self.servers.items()):
                    aud = getattr(server, "shadow_audit", None)
                    if aud is None:
                        continue
                    if aud.divergences != self._audit_last.get(name, 0):
                        self._audit_last[name] = aud.divergences
                        self.log.append(
                            [tick, "audit_divergence", name,
                             aud.divergences]
                        )

                for client in self.clients:
                    await client.refresh_once()

                self._drive_frontend(tick)
                await self._drive_streams(tick)
                await self._drive_storm(tick)
                self._log_admission(tick)

                # The durability beat (journal flush + cadenced
                # snapshot) runs AFTER the tick's refreshes so this
                # tick's decides are on disk before the next tick — the
                # freshness bound warm takeover leans on.
                for server in self.servers.values():
                    server.persist_step()

                tick_violations = checker.check_tick(
                    tick, self.servers, groups,
                    # Active storm and stream clients are checked too:
                    # an admitted storm lease — or a pushed stream
                    # lease — is subject to lag-never-lead and the
                    # lease window like any other (baseline/convergence
                    # snapshots stay on the base population only).
                    self.clients + self.stream_clients
                    + self.storm_clients,
                )
                if self.federation is not None:
                    tick_violations = tick_violations + checker.check_federation(
                        tick, self.servers,
                        self.federation.straddle_capacities(),
                    ) + self._check_blast_radius(tick)
                for v in tick_violations:
                    self._record_violation(v)
                    self.log.append([tick] + v.as_log())

                if tick == plan.warmup_ticks - 1:
                    baseline = self._snapshot()
                if baseline is not None and not degraded:
                    # First tick where clients collectively hold LESS
                    # than the baseline: the fault visibly bit (plans
                    # assert this so they cannot pass vacuously).
                    total = sum(self._snapshot().values())
                    if total < sum(baseline.values()) - 1e-9:
                        degraded = True
                        self.log.append([tick, "degraded"])
                if (
                    baseline is not None
                    and converged_at is None
                    and tick >= heal_tick
                    and self._matches(self._snapshot(), baseline)
                ):
                    converged_at = tick
                    self.log.append(
                        [tick, "converged", tick - heal_tick]
                    )

                self._flight_record(tick, masters, tick_violations)
                self.clock.advance(plan.tick_interval)
        finally:
            await self._teardown()

        reconverged = converged_at is not None and (
            converged_at - heal_tick <= plan.reconverge_ticks
        )
        if converged_at is None and baseline is not None:
            self._record_violation(Violation(
                plan.total_ticks, "reconvergence", RESOURCE,
                f"no reconvergence within {plan.total_ticks - heal_tick} "
                f"post-heal ticks (budget {plan.reconverge_ticks})",
            ))
            self.log.append(
                [plan.total_ticks] + self.violations[-1].as_log()
            )
            # The end-of-run violation is a dump trigger like any other
            # (servers are stopped but their stores are still readable).
            self._flight_record(
                plan.total_ticks, last_masters, [self.violations[-1]]
            )
        log_bytes = json.dumps(
            self.log, sort_keys=True, separators=(",", ":")
        ).encode()
        # Final admission tallies per server (None when no server runs
        # the admission front-end): deterministic integers the storm
        # assertions read band by band.
        admission_tallies = {
            name: {
                f"{method}/{band}": dict(counts)
                for (method, band), counts in sorted(
                    server._admission.tallies.items()
                )
            }
            for name, server in self.servers.items()
            if getattr(server, "_admission", None) is not None
        } or None
        return {
            "plan": plan.name,
            "seed": plan.seed,
            "ok": not self.violations and reconverged,
            "ticks": plan.total_ticks,
            # For the Chrome-trace export: one virtual tick maps to this
            # many seconds of trace time (chaos.trace_export).
            "tick_interval": plan.tick_interval,
            "heal_tick": heal_tick,
            "converged_after_heal_ticks": (
                None if converged_at is None else converged_at - heal_tick
            ),
            "violations": [v.as_log() for v in self.violations],
            "admission": admission_tallies,
            # Serving-plane outcome per pooled server (None when the
            # plan arms no frontend pool): worker/ring counters and the
            # final stream placement — deterministic, virtual-clock
            # driven; snapshotted at teardown before the rings close.
            "frontend": self._frontend_final or None,
            # Shadow-audit outcome per audited server (None when the
            # plan doesn't arm the auditor): sample/divergence counts
            # and the bounded detail rows, byte-stable because chaos
            # auditors compare inline on virtual time.
            "audit": {
                name: server.shadow_audit.status()
                for name, server in sorted(self.servers.items())
                if getattr(server, "shadow_audit", None) is not None
            } or None,
            # The anomaly detector's windowed verdict over the run's
            # history records (None when there is nothing to watch).
            "detect": self._detect_block(),
            "history": self.history.status(),
            # Machine-readable SLO verdicts (reconvergence budget,
            # top-band goodput floor with per-band tallies), each with
            # its delta vs the last round that embedded the same verdict.
            "slo": self._slo_block(converged_at, heal_tick),
            # The black box: on any invariant violation the per-tick
            # ring is dumped here (None on a clean run) — its records
            # replay the last N ticks leading into the failure.
            "flightrec_dump": self.flight_dump,
            "event_log": self.log,
            "log_sha256": hashlib.sha256(log_bytes).hexdigest(),
        }


def run_plan(plan: FaultPlan) -> dict:
    """Synchronous convenience: build a runner, drive the plan, return
    the verdict."""
    return asyncio.run(ChaosRunner(plan).run())
