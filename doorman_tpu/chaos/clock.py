"""Controllable virtual clock.

Every lease expiry, learning-mode window, election TTL and parent-lease
deadline in the stack is computed against an injectable `clock`
callable; a chaos run hands all of them THIS clock and advances it one
tick_interval per runner tick, so time-driven behavior (lease lapse,
lock expiry, learning-mode exit) is deterministic and runs at whatever
speed the host can tick — a 60-virtual-second outage costs milliseconds
of wall clock.
"""

from __future__ import annotations


class ChaosClock:
    """Callable like time.time, advanced explicitly by the runner."""

    def __init__(self, start: float = 1_000_000.0):
        self._now = float(start)

    def __call__(self) -> float:
        return self._now

    def now(self) -> float:
        return self._now

    def advance(self, dt: float) -> None:
        if dt < 0:
            raise ValueError("time only moves forward")
        self._now += dt
