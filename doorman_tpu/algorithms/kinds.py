"""Algorithm lane identifiers, shared by the host-side core and the device
kernels. Kept free of jax imports so host-only paths (config validation,
per-request serving) never pay the JAX startup cost."""

from __future__ import annotations

import enum


class AlgoKind(enum.IntEnum):
    """Per-resource algorithm lane. Values 0-3 match the wire enum
    (doorman_tpu.proto Algorithm.Kind); the extra lanes are internal."""

    NO_ALGORITHM = 0
    STATIC = 1
    PROPORTIONAL_SHARE = 2
    FAIR_SHARE = 3
    # The Go-style "equal share + proportional top-up" variant
    # (reference algorithm.go:213-292) in snapshot form.
    PROPORTIONAL_TOPUP = 4
    # Priority-banded weighted max-min with capacity groups (wire kind
    # PRIORITY_BANDS = 4 maps here; the solve_lanes kernels do not carry
    # this lane — BatchSolver routes it to solver.priority instead).
    PRIORITY_BANDS = 5
    # 6 is reserved: the native store engine uses it as DECIDE_LEARN on
    # its per-request decide wire (native/__init__.py), and an AlgoKind
    # aliasing it would silently take the learn path there.
    #
    # The fairness portfolio (selected by `variant` config parameters on
    # the wire FAIR_SHARE / PROPORTIONAL_SHARE kinds; doc/algorithms.md
    # "The fairness portfolio"):
    # Client-granular (unweighted) max-min water-filling, solved by the
    # fast-converging direct fill iteration of arxiv 2310.09699 instead
    # of FAIR_SHARE's bisection (wire FAIR_SHARE + variant=maxmin).
    MAX_MIN_FAIR = 7
    # Balanced fairness (arxiv 1711.02880): insensitive
    # subclient-proportional shares with the recursive cap-peeling
    # formula unrolled to a fixed bound (wire FAIR_SHARE +
    # variant=balanced).
    BALANCED_FAIRNESS = 8
    # Weighted proportional fairness (Kelly log-utility, arxiv
    # 1404.2266): the dual fixpoint on the water level (wire
    # PROPORTIONAL_SHARE + variant=logutil).
    PROPORTIONAL_FAIRNESS = 9
