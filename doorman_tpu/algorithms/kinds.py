"""Algorithm lane identifiers, shared by the host-side core and the device
kernels. Kept free of jax imports so host-only paths (config validation,
per-request serving) never pay the JAX startup cost."""

from __future__ import annotations

import enum


class AlgoKind(enum.IntEnum):
    """Per-resource algorithm lane. Values 0-3 match the wire enum
    (doorman_tpu.proto Algorithm.Kind); the extra lanes are internal."""

    NO_ALGORITHM = 0
    STATIC = 1
    PROPORTIONAL_SHARE = 2
    FAIR_SHARE = 3
    # The Go-style "equal share + proportional top-up" variant
    # (reference algorithm.go:213-292) in snapshot form.
    PROPORTIONAL_TOPUP = 4
    # Priority-banded weighted max-min with capacity groups (wire kind
    # PRIORITY_BANDS = 4 maps here; the solve_lanes kernels do not carry
    # this lane — BatchSolver routes it to solver.priority instead).
    PRIORITY_BANDS = 5
