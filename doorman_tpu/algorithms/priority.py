"""Priority-banded, group-capped allocation — the numpy oracle.

BASELINE.json config 5 ("weighted multi-resource LP: client priorities +
cross-resource caps") made concrete as a water-filling scheme, the
lexicographic max-min relaxation of that LP:

  * Within a resource, clients are served in priority-band order (band 0
    first). Each band gets a weighted max-min (water-filling) share of
    the capacity left over from higher bands — the same fair-share
    semantics as AlgoKind.FAIR_SHARE (doc/algorithms.md), band by band.
    The reference leaves priority interpretation to the algorithm
    (reference doc/design.md:279: "The interpretation of the priority is
    up to the algorithm"; bands on the wire: doorman.proto
    PriorityBandAggregate) — this is doorman-tpu's priority-aware
    algorithm.
  * Resources may share a group cap (a shared upstream: Σ grants over
    the group <= group_cap, on top of each per-resource capacity). The
    coupling is resolved by uniformly scaling each member resource's
    effective capacity by theta in [0, 1], bisected per group to the
    largest feasible value — usage is monotone in theta, so this is
    well-defined and deterministic.

The JAX kernel (doorman_tpu.solver.priority) must match these numbers;
tests drive both with the same tables.
"""

from __future__ import annotations

import numpy as np

from doorman_tpu.algorithms.tick import fair_share_waterfill

THETA_ITERS = 64  # group-cap bisection depth (f64)


def band_waterfill(
    capacity: float, wants: np.ndarray, weights: np.ndarray
) -> np.ndarray:
    """Weighted max-min within one band: the exact sorting-based water
    fill shared with AlgoKind.FAIR_SHARE."""
    wants = np.asarray(wants, np.float64)
    if len(wants) == 0:
        return np.zeros_like(wants)
    if capacity <= 0:
        return np.zeros_like(wants)
    return fair_share_waterfill(capacity, wants, weights)


def priority_alloc(
    capacity: float,
    wants: np.ndarray,
    weights: np.ndarray,
    bands: np.ndarray,
) -> np.ndarray:
    """One resource: bands served lexicographically (0 = highest), each
    water-filled within the capacity the higher bands left over."""
    wants = np.asarray(wants, np.float64)
    weights = np.asarray(weights, np.float64)
    bands = np.asarray(bands)
    gets = np.zeros_like(wants)
    remaining = float(capacity)
    for band in sorted(set(bands.tolist())):
        m = bands == band
        share = band_waterfill(remaining, wants[m], weights[m])
        gets[m] = share
        remaining -= share.sum()
        if remaining <= 0:
            break
    return gets


def grouped_priority_alloc(
    capacities: np.ndarray,  # [R]
    wants: list,  # per resource: [n_r]
    weights: list,
    bands: list,
    group: np.ndarray,  # [R] group id, -1 = uncoupled
    group_cap: np.ndarray,  # [G]
) -> list:
    """All resources, with cross-resource group caps.

    Returns per-resource grant arrays. For each group, theta — the
    uniform scale on members' effective capacities — is bisected to the
    largest value whose total usage fits the group cap."""
    capacities = np.asarray(capacities, np.float64)
    group = np.asarray(group)
    R = len(capacities)

    def solve_all(theta_per_resource):
        return [
            priority_alloc(
                capacities[r] * theta_per_resource[r],
                wants[r], weights[r], bands[r],
            )
            for r in range(R)
        ]

    theta = np.ones(R, np.float64)
    for g in range(len(group_cap)):
        members = np.nonzero(group == g)[0]
        if len(members) == 0:
            continue

        def usage(t):
            total = 0.0
            for r in members:
                total += priority_alloc(
                    capacities[r] * t, wants[r], weights[r], bands[r]
                ).sum()
            return total

        if usage(1.0) <= group_cap[g]:
            continue
        lo, hi = 0.0, 1.0
        for _ in range(THETA_ITERS):
            mid = (lo + hi) / 2.0
            if usage(mid) <= group_cap[g]:
                lo = mid
            else:
                hi = mid
        theta[members] = lo
    return solve_all(theta)
