"""Per-request (incremental) allocation algorithms.

Semantics parity with the reference algorithm suite
(/root/reference/go/server/doorman/algorithm.go:66-313): each algorithm maps
(lease store state, available capacity, one client's request) to a lease, and
assigns it into the store — so the outcome for a client depends on the store
state left behind by previously-processed requests. The batched solver
(doorman_tpu.solver) recasts this as a per-tick snapshot solve; these scalar
forms are the oracle for it and the fallback execution path for single
requests arriving between ticks.

An algorithm here is `fn(store, capacity, request) -> Lease`, produced by a
factory taking the proto Algorithm config (lease_length / refresh_interval),
mirroring the reference's closure design.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Callable, Dict

from doorman_tpu.core.lease import Lease
from doorman_tpu.core.store import LeaseStore
from doorman_tpu.proto import doorman_pb2 as pb

log = logging.getLogger(__name__)

Algorithm = Callable[[LeaseStore, float, "Request"], Lease]


@dataclass(frozen=True)
class Request:
    """One client's capacity request for one resource."""

    client: str
    has: float
    wants: float
    subclients: int = 1
    priority: int = 0


def _params(config: pb.Algorithm) -> tuple[float, float]:
    return float(config.lease_length), float(config.refresh_interval)


def _peek(store: LeaseStore, client: str):
    """(found, lease, sum_has, sum_wants, count) — in ONE store call
    when the store provides the combined read (the native store's
    request path pays a ctypes crossing per primitive read; see
    NativeLeaseStore.peek), else composed from the primitives. A pure
    read combination: semantics identical either way."""
    peek = getattr(store, "peek", None)
    if peek is not None:
        return peek(client)
    return (
        store.has_client(client),
        store.get(client),
        store.sum_has,
        store.sum_wants,
        store.count,
    )


def no_algorithm(config: pb.Algorithm) -> Algorithm:
    """Every client gets exactly what it wants."""
    length, interval = _params(config)

    def algo(store: LeaseStore, capacity: float, r: Request) -> Lease:
        return store.assign(r.client, length, interval, r.wants, r.wants, r.subclients,
                            priority=r.priority)

    return algo


def static(config: pb.Algorithm) -> Algorithm:
    """Every client gets min(configured capacity, wants); the configured
    capacity is per client, not a shared total."""
    length, interval = _params(config)

    def algo(store: LeaseStore, capacity: float, r: Request) -> Lease:
        return store.assign(
            r.client, length, interval, min(capacity, r.wants), r.wants,
            r.subclients, priority=r.priority,
        )

    return algo


def learn(config: pb.Algorithm) -> Algorithm:
    """Learning mode: grant whatever the client reports it already has, so a
    freshly-elected master reconstructs state without overcommitting."""
    length, interval = _params(config)

    def algo(store: LeaseStore, capacity: float, r: Request) -> Lease:
        return store.assign(r.client, length, interval, r.has, r.wants, r.subclients,
                            priority=r.priority)

    return algo


def proportional_share(config: pb.Algorithm) -> Algorithm:
    """Proportional share, simulation semantics (the framework's canonical
    PROPORTIONAL_SHARE; parity target algo_proportional.py:31-65): grant
    wants when total wants fit within capacity, otherwise scale every
    client by capacity / all_wants; always clamped by the free capacity
    (capacity minus leases outstanding to others)."""
    length, interval = _params(config)

    def algo(store: LeaseStore, capacity: float, r: Request) -> Lease:
        _, old, sum_has, sum_wants, _count = _peek(store, r.client)
        # The requester's own outstanding lease does not count against it.
        all_wants = sum_wants - old.wants + r.wants
        sum_leases = sum_has - old.has
        free = max(capacity - sum_leases, 0.0)
        if all_wants < capacity:
            gets = min(r.wants, free)
        else:
            gets = min(r.wants * (capacity / all_wants), free)
        return store.assign(r.client, length, interval, gets, r.wants, r.subclients,
                            priority=r.priority)

    return algo


def proportional_topup(config: pb.Algorithm) -> Algorithm:
    """Proportional share, Go reference semantics (algorithm.go:213-292;
    select with algorithm parameter variant=topup): grant wants when there
    is room; in overload, grant the equal share plus a top-up proportional
    to the client's excess demand, funded by clients requesting under their
    equal share."""
    length, interval = _params(config)

    def algo(store: LeaseStore, capacity: float, r: Request) -> Lease:
        found, old, sum_has, sum_wants, count = _peek(store, r.client)
        if not found:
            count += r.subclients

        equal_share = capacity / count
        equal_share_client = equal_share * r.subclients
        # Capacity not currently promised to anyone else; the hard cap on
        # what this run may grant.
        unused = capacity - sum_has + old.has

        if sum_wants <= capacity or r.wants <= equal_share_client:
            return store.assign(
                r.client, length, interval,
                min(r.wants, unused), r.wants, r.subclients,
                priority=r.priority,
            )

        # Overload: pool the capacity left by clients under their equal
        # share, and the excess demand of those over it, then top up
        # proportionally.
        extra_capacity = 0.0
        extra_need = 0.0
        for client_id, lease in store.items():
            if client_id == r.client:
                wants, subclients = r.wants, r.subclients
            else:
                wants, subclients = lease.wants, lease.subclients
            share = equal_share * subclients
            if wants < share:
                extra_capacity += share - wants
            else:
                extra_need += wants - share

        gets = equal_share_client + (r.wants - equal_share_client) * (
            extra_capacity / extra_need
        )
        return store.assign(
            r.client, length, interval, min(gets, unused), r.wants,
            r.subclients, priority=r.priority,
        )

    return algo


def fair_share(config: pb.Algorithm) -> Algorithm:
    """Weighted fair share with two rounds of redistributing capacity left
    unclaimed by clients under their equal share (the reference's bounded
    approximation of max-min water-filling; the solver also offers the full
    iterative water-fill — see doorman_tpu.solver.fairshare)."""
    length, interval = _params(config)

    def algo(store: LeaseStore, capacity: float, r: Request) -> Lease:
        _, old, sum_has, _sum_wants, count0 = _peek(store, r.client)
        if r.has != old.has:
            log.error(
                "client %s is confused: says it has %s, was assigned %s",
                r.client, r.has, old.has,
            )

        count = count0 - old.subclients + r.subclients
        available = capacity - sum_has + old.has
        equal_share = capacity / count
        deserved = equal_share * r.subclients

        if r.wants <= deserved:
            return store.assign(
                r.client, length, interval,
                min(r.wants, available), r.wants, r.subclients,
                priority=r.priority,
            )

        # Round 1: capacity left by clients under their equal share is
        # contested by the subclients of everyone over it.
        extra = 0.0
        want_extra = r.subclients
        want_extra_clients: Dict[str, Lease] = {}
        for client_id, lease in store.items():
            if client_id == r.client:
                continue
            their_deserved = lease.subclients * equal_share
            if lease.wants < their_deserved:
                extra += their_deserved - lease.wants
            elif lease.wants > their_deserved:
                want_extra += lease.subclients
                want_extra_clients[client_id] = lease

        deserved_extra = (extra / want_extra) * r.subclients
        if r.wants < deserved + deserved_extra:
            return store.assign(
                r.client, length, interval,
                min(r.wants, available), r.wants, r.subclients,
                priority=r.priority,
            )

        # Round 2: clients over their equal share but under share+extra
        # leave part of the extra pool unclaimed; redistribute it once more.
        want_extra_extra = r.subclients
        extra_extra = 0.0
        for client_id, lease in want_extra_clients.items():
            if client_id == r.client:
                continue
            entitled = deserved_extra + deserved
            if lease.wants < entitled:
                extra_extra += entitled - lease.wants
            elif lease.wants > entitled:
                want_extra_extra += lease.subclients

        deserved_extra_extra = (extra_extra / want_extra_extra) * r.subclients
        return store.assign(
            r.client, length, interval,
            min(deserved + deserved_extra + deserved_extra_extra, available),
            r.wants, r.subclients, priority=r.priority,
        )

    return algo


def priority_bands(config: pb.Algorithm) -> Algorithm:
    """Priority-banded weighted max-min (the scalar form of
    doorman_tpu.solver.priority): recompute the whole resource's
    allocation — every stored lease plus this request — with clients
    served in descending wire-priority bands, and grant the requester its
    share. Cross-resource capacity groups are enforced by the batched
    tick solve only; this per-request form sees one resource at a time."""
    import numpy as np

    from doorman_tpu.algorithms.priority import priority_alloc

    length, interval = _params(config)

    def algo(store: LeaseStore, capacity: float, r: Request) -> Lease:
        entries = {
            c: (l.wants, float(l.subclients), l.priority)
            for c, l in store.items()
        }
        entries[r.client] = (r.wants, float(r.subclients), r.priority)
        clients = list(entries)
        wants = np.array([entries[c][0] for c in clients], np.float64)
        weights = np.array([entries[c][1] for c in clients], np.float64)
        prios = [entries[c][2] for c in clients]
        # Dense band ranks: larger wire priority = more important = lower
        # band index.
        levels = sorted(set(prios), reverse=True)
        rank = {p: i for i, p in enumerate(levels)}
        bands = np.array([rank[p] for p in prios], np.int64)
        gets = priority_alloc(capacity, wants, weights, bands)
        # Only the requester's lease is reassigned here, so clamp to the
        # capacity not promised to others — a preempting high-priority
        # client converges as the displaced leases refresh (the same
        # incremental discipline as the other scalar forms; the batched
        # tick reassigns everyone at once and needs no clamp).
        available = max(
            capacity - store.sum_has + store.get(r.client).has, 0.0
        )
        grant = min(float(gets[clients.index(r.client)]), available)
        return store.assign(
            r.client, length, interval, grant, r.wants, r.subclients,
            priority=r.priority,
        )

    return algo


def _portfolio_algorithm(config: pb.Algorithm, solve) -> Algorithm:
    """Per-request form shared by the fairness-portfolio lanes
    (MAX_MIN_FAIR / BALANCED_FAIRNESS / PROPORTIONAL_FAIRNESS): like
    priority_bands, recompute the whole resource's allocation — every
    stored lease plus this request — with the lane's numpy tick oracle,
    and grant the requester its share clamped to the capacity not
    promised to others (the incremental convergence discipline of the
    other scalar forms; the batched tick reassigns everyone at once and
    needs no clamp). `solve` is fn(capacity, wants[], subclients[]) ->
    gets[]."""
    import numpy as np

    length, interval = _params(config)

    def algo(store: LeaseStore, capacity: float, r: Request) -> Lease:
        entries = {
            c: (l.wants, float(l.subclients)) for c, l in store.items()
        }
        entries[r.client] = (r.wants, float(r.subclients))
        clients = list(entries)
        wants = np.array([entries[c][0] for c in clients], np.float64)
        sub = np.array([entries[c][1] for c in clients], np.float64)
        gets = solve(capacity, wants, sub)
        available = max(
            capacity - store.sum_has + store.get(r.client).has, 0.0
        )
        grant = min(float(gets[clients.index(r.client)]), available)
        return store.assign(
            r.client, length, interval, grant, r.wants, r.subclients,
            priority=r.priority,
        )

    return algo


def max_min_fair(config: pb.Algorithm) -> Algorithm:
    """Client-granular (unweighted) max-min water-filling by the
    fast-converging fill iteration (arxiv 2310.09699); wire form
    FAIR_SHARE + parameter variant=maxmin. Oracle:
    algorithms.tick.max_min_fair_tick."""
    from doorman_tpu.algorithms import tick

    return _portfolio_algorithm(
        config, lambda cap, wants, sub: tick.max_min_fair_tick(cap, wants)
    )


def balanced_fairness(config: pb.Algorithm) -> Algorithm:
    """Balanced fairness by the bounded recursive cap-peeling formula
    (arxiv 1711.02880); wire form FAIR_SHARE + parameter
    variant=balanced. Oracle: algorithms.tick.balanced_fairness_tick."""
    from doorman_tpu.algorithms import tick

    return _portfolio_algorithm(config, tick.balanced_fairness_tick)


def proportional_fairness(config: pb.Algorithm) -> Algorithm:
    """Weighted proportional fairness (Kelly log-utility dual fixpoint,
    arxiv 1404.2266); wire form PROPORTIONAL_SHARE + parameter
    variant=logutil. Oracle:
    algorithms.tick.proportional_fairness_tick."""
    from doorman_tpu.algorithms import tick

    return _portfolio_algorithm(config, tick.proportional_fairness_tick)


def get_parameter(config: pb.Algorithm, name: str, default: str | None = None):
    """Fetch a named algorithm parameter (analog of the simulation's
    get_named_parameter, algorithm.py:66-71)."""
    for p in config.parameters:
        if p.name == name:
            return p.value
    return default


_FACTORIES = {
    pb.Algorithm.NO_ALGORITHM: no_algorithm,
    pb.Algorithm.STATIC: static,
    pb.Algorithm.PROPORTIONAL_SHARE: proportional_share,
    pb.Algorithm.FAIR_SHARE: fair_share,
    pb.Algorithm.PRIORITY_BANDS: priority_bands,
}


# The `variant` parameter refines a wire kind into a portfolio lane;
# server.config validates against this table so a typo'd variant fails
# the config load instead of silently selecting the base lane.
VARIANT_FACTORIES = {
    (pb.Algorithm.PROPORTIONAL_SHARE, "topup"): proportional_topup,
    (pb.Algorithm.PROPORTIONAL_SHARE, "logutil"): proportional_fairness,
    (pb.Algorithm.FAIR_SHARE, "maxmin"): max_min_fair,
    (pb.Algorithm.FAIR_SHARE, "balanced"): balanced_fairness,
}


def get_algorithm(config: pb.Algorithm) -> Algorithm:
    """Build the algorithm the config names (registry analog of
    reference algorithm.go:304-313). The `variant` parameter selects
    the portfolio lanes sharing a wire kind: PROPORTIONAL_SHARE
    variant=topup (Go-style equal-share-plus-top-up) or
    variant=logutil (Kelly proportional fairness); FAIR_SHARE
    variant=maxmin (unweighted max-min) or variant=balanced (balanced
    fairness)."""
    variant = get_parameter(config, "variant")
    if variant is not None:
        factory = VARIANT_FACTORIES.get((config.kind, variant))
        if factory is not None:
            return factory(config)
    return _FACTORIES[config.kind](config)
