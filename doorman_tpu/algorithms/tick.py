"""Per-tick (batch snapshot) allocation semantics — numpy oracles.

The TPU solver recomputes every lease of a resource at once from a coherent
snapshot taken at the start of a tick, instead of the reference's
per-request incremental updates. These numpy implementations DEFINE that
batch semantics; the JAX kernels in `doorman_tpu.solver` must match them
bit-for-bit (given exactly-representable inputs), and tests relate them back
to the reference's incremental semantics (they share fixed points).

Semantics notes (decisions the reference leaves implicit, recorded here per
SURVEY.md §7 "hard parts"):

  * Proportional share follows the simulation form
    (/root/reference/simulation/algo_proportional.py:31-65): in overload
    every client is scaled by capacity / all_wants, clamped by the free
    capacity. Two flavors:
      - `proportional_snapshot`: free capacity for every client is computed
        from the pre-tick grants (embarrassingly parallel; the headline
        kernel semantics);
      - `proportional_sequential`: exact replay of the simulation's
        client-by-client order, where earlier grants in the tick shrink the
        free capacity seen by later clients (the parity-oracle mode; the
        solver implements it as a lax.scan lane).
  * Fair share in batch form is FULL weighted max-min water-filling (the
    ideal the reference documents in doc/algorithms.md:59-69); the Go code's
    two-round redistribution (algorithm.go:95-211) is its truncation and is
    kept only as the scalar per-request algorithm. In a whole-tick solve the
    sum constraint is enforced exactly by the water level, so the per-client
    "available" clamp of the incremental form is unnecessary.
  * Static / None / Learn are pointwise.
"""

from __future__ import annotations

import numpy as np

__all__ = [
    "none_tick",
    "static_tick",
    "learn_tick",
    "proportional_snapshot",
    "proportional_sequential",
    "proportional_topup_snapshot",
    "fair_share_waterfill",
    "waterfill_level",
    "waterfill_level_iterative",
    "balanced_theta",
    "max_min_fair_tick",
    "balanced_fairness_tick",
    "proportional_fairness_tick",
    "oracle_row",
    "F32_PARITY_REL_BOUND",
    "FILL_ITERS",
    "BALANCED_ROUNDS",
]

# Fixed unroll bounds for the portfolio's iterative fills (device and
# host run the SAME bounded iteration, which is what makes the parity
# pin bit-level rather than tolerance-level). FILL_ITERS bounds the
# fast-converging water-fill of arxiv 2310.09699 (one bottleneck
# saturates per step at worst, so rows with up to FILL_ITERS distinct
# saturation cascades solve exactly; deeper cascades freeze at the last
# — still feasible — level). BALANCED_ROUNDS bounds the balanced-
# fairness cap-peeling recursion of arxiv 1711.02880 (one ratio class
# peels per round; unconverged rows keep slack, the documented
# insensitivity truncation).
FILL_ITERS = 16
BALANCED_ROUNDS = 8


def none_tick(wants: np.ndarray) -> np.ndarray:
    return wants.copy()


def static_tick(per_client_capacity: float, wants: np.ndarray) -> np.ndarray:
    return np.minimum(per_client_capacity, wants)


def learn_tick(has: np.ndarray) -> np.ndarray:
    return has.copy()


def proportional_snapshot(
    capacity: float, wants: np.ndarray, has_prev: np.ndarray
) -> np.ndarray:
    """Proportional share for one resource, all clients from one snapshot.

    `has_prev` are the grants outstanding from the previous tick; the free
    capacity seen by client i excludes its own previous grant (the sim
    clears the requester's `has` before summing leases).
    """
    all_wants = float(np.sum(wants))
    sum_leases = float(np.sum(has_prev))
    free = np.maximum(capacity - (sum_leases - has_prev), 0.0)
    if all_wants < capacity:
        return np.minimum(wants, free)
    proportion = capacity / all_wants
    return np.minimum(wants * proportion, free)


def proportional_sequential(
    capacity: float, wants: np.ndarray, has_prev: np.ndarray
) -> np.ndarray:
    """Exact replay of the simulation's per-client processing order within
    one tick: client i's free capacity reflects the new grants of clients
    0..i-1 and the previous grants of clients i+1.. ."""
    n = wants.shape[0]
    gets = np.zeros_like(wants)
    all_wants = float(np.sum(wants))
    sum_leases = float(np.sum(has_prev))  # running total of live leases
    proportion = capacity / all_wants if all_wants >= capacity else None
    for i in range(n):
        free = max(capacity - (sum_leases - has_prev[i]), 0.0)
        if proportion is None:
            g = min(wants[i], free)
        else:
            g = min(wants[i] * proportion, free)
        gets[i] = g
        sum_leases += g - has_prev[i]
    return gets


def proportional_topup_snapshot(
    capacity: float,
    wants: np.ndarray,
    has_prev: np.ndarray,
    subclients: np.ndarray,
) -> np.ndarray:
    """Snapshot form of the Go proportional share (equal share plus a top-up
    proportional to excess demand, reference algorithm.go:213-292): clients
    under their equal share (or when total wants fit) get their wants;
    otherwise equal_share_i + (wants_i - equal_share_i) * extra_capacity /
    extra_need. Grants are clamped by the capacity unused as of the
    snapshot. With all clients recomputed from one snapshot the reference's
    request-order dependence disappears."""
    wants = np.asarray(wants, dtype=np.float64)
    has_prev = np.asarray(has_prev, dtype=np.float64)
    sub = np.asarray(subclients, dtype=np.float64)
    count = float(np.sum(sub))
    sum_wants = float(np.sum(wants))
    sum_has = float(np.sum(has_prev))
    equal = (capacity / count) * sub
    # Unlike the Go form this clamps at 0 (a store overcommitted by a
    # previous learning phase must not produce negative grants); the sim's
    # free-capacity rule does the same.
    unused = np.maximum(capacity - (sum_has - has_prev), 0.0)
    if sum_wants <= capacity:
        return np.minimum(wants, unused)
    under = wants < equal
    extra_capacity = float(np.sum(np.where(under, equal - wants, 0.0)))
    extra_need = float(np.sum(np.where(under, 0.0, wants - equal)))
    topped = equal + (wants - equal) * (extra_capacity / extra_need)
    return np.where(
        wants <= equal, np.minimum(wants, unused), np.minimum(topped, unused)
    )


def waterfill_level(
    capacity: float, wants: np.ndarray, weights: np.ndarray
) -> float:
    """Exact water level L for weighted max-min fairness: each client gets
    min(wants_i, L * w_i) and the grants sum to `capacity` (assuming
    sum(wants) >= capacity; otherwise returns max ratio so everyone is
    satisfied). Computed by sorting the saturation ratios wants_i / w_i."""
    w = np.asarray(weights, dtype=np.float64)
    wants = np.asarray(wants, dtype=np.float64)
    if float(np.sum(wants)) <= capacity:
        ratios = np.where(w > 0, wants / np.maximum(w, 1e-300), 0.0)
        return float(np.max(ratios, initial=0.0))
    order = np.argsort(np.where(w > 0, wants / np.maximum(w, 1e-300), np.inf))
    r = (wants / np.maximum(w, 1e-300))[order]
    w_sorted = w[order]
    wants_sorted = wants[order]
    # After the first k clients saturate (get their wants), the rest share
    # the remainder at level L = remaining / remaining_weight; L is valid
    # when r[k-1] <= L <= r[k]. Zero-weight clients sort last (infinite
    # ratio) and can absorb no water: once the weighted clients are all
    # saturated, the level is the largest finite saturation ratio — NOT
    # zero, which would wrongly zero the already-saturated grants.
    remaining = capacity
    remaining_weight = float(np.sum(w_sorted))
    last_ratio = 0.0
    for k in range(len(r)):
        if remaining_weight <= 0:
            return last_ratio
        level = remaining / remaining_weight
        if level <= r[k]:
            return level
        remaining -= wants_sorted[k]
        remaining_weight -= w_sorted[k]
        if np.isfinite(r[k]):
            last_ratio = float(r[k])
    return (
        remaining / remaining_weight if remaining_weight > 0 else last_ratio
    )


def fair_share_waterfill(
    capacity: float, wants: np.ndarray, subclients: np.ndarray
) -> np.ndarray:
    """Full weighted max-min fair share: if total wants fit, grant wants;
    otherwise grant min(wants_i, L * subclients_i) at the exact water level."""
    wants = np.asarray(wants, dtype=np.float64)
    sub = np.asarray(subclients, dtype=np.float64)
    if float(np.sum(wants)) <= capacity:
        return wants.copy()
    level = waterfill_level(capacity, wants, sub)
    return np.minimum(wants, level * sub)


def waterfill_level_iterative(
    capacity: float,
    wants: np.ndarray,
    weights: np.ndarray,
    iters: int = FILL_ITERS,
) -> float:
    """Water level by the fast-converging direct fill iteration (arxiv
    2310.09699): start from the even split, repeatedly freeze the
    saturated set and re-level the remainder. The level is monotonically
    non-decreasing (frozen clients consume less than their level share),
    so `max` IS the convergence mask: a converged row rewrites its own
    level. Exact once every bottleneck cascade has frozen (at most one
    new ratio class per step); truncation keeps the last — still
    feasible — level. This is the oracle arithmetic for the
    MAX_MIN_FAIR (weights = 1) and PROPORTIONAL_FAIRNESS (weights =
    subclients; the Kelly dual fixpoint — on a single capacity the
    KKT point of Σ wᵢ·log(gᵢ) s.t. Σ g ≤ C, g ≤ wants is exactly
    min(wants, ν·w)) device lanes: solver.lanes runs the SAME bounded
    iteration."""
    tiny = np.finfo(np.float64).tiny
    wants = np.asarray(wants, dtype=np.float64)
    w = np.asarray(weights, dtype=np.float64)
    level = capacity / max(float(np.sum(w)), tiny)
    for _ in range(iters):
        sat = wants <= level * w
        sat_wants = float(np.sum(np.where(sat, wants, 0.0)))
        unsat_w = float(np.sum(np.where(sat, 0.0, w)))
        if unsat_w > 0:
            level = max(level, (capacity - sat_wants) / max(unsat_w, tiny))
    return level


def max_min_fair_tick(capacity: float, wants: np.ndarray) -> np.ndarray:
    """Client-granular (unweighted) max-min fairness: gets =
    min(wants, L) at the iterative water level; subclient counts do not
    weight the fill (that is FAIR_SHARE's semantics)."""
    wants = np.asarray(wants, dtype=np.float64)
    if float(np.sum(wants)) <= capacity:
        return wants.copy()
    level = waterfill_level_iterative(
        capacity, wants, np.ones_like(wants)
    )
    return np.minimum(wants, level)


def proportional_fairness_tick(
    capacity: float, wants: np.ndarray, subclients: np.ndarray
) -> np.ndarray:
    """Weighted proportional fairness (Kelly log-utility, arxiv
    1404.2266): maximize Σ subᵢ·log(gᵢ) subject to Σ g ≤ capacity and
    g ≤ wants. The KKT point is min(wants, ν·sub) with the dual level ν
    solved by the bounded fixpoint iteration — on one capacity this
    coincides with FAIR_SHARE's weighted water-fill objective, but the
    level arithmetic is the dual iteration, not the bisection+snap (the
    two lanes agree to ~1 ulp when both converge; doc/algorithms.md)."""
    wants = np.asarray(wants, dtype=np.float64)
    sub = np.asarray(subclients, dtype=np.float64)
    if float(np.sum(wants)) <= capacity:
        return wants.copy()
    level = waterfill_level_iterative(capacity, wants, sub)
    return np.minimum(wants, level * sub)


def balanced_theta(
    capacity: float,
    wants: np.ndarray,
    weights: np.ndarray,
    rounds: int = BALANCED_ROUNDS,
) -> "tuple[float, np.ndarray]":
    """Balanced-fairness binding ratio θ and the cap-fixed class mask,
    by the recursive cap-peeling formula (arxiv 1711.02880, the
    single-pool instantiation): shares are proportional to weights
    (per-class job counts), scaled by the MOST binding constraint —
    the pool (θ = Σx/Ĉ) or some class's rate cap (θ = xᵢ/wantsᵢ).
    Each round the classes achieving the max cap ratio freeze at their
    wants and leave the recursion (exactly one ratio class per round,
    mirroring the paper's one-job-removal recursion); the pool ratio
    takes over when it dominates — the convergence mask is the peel
    set emptying. Truncation after `rounds` leaves capacity unclaimed
    (the insensitivity tax; documented, and why BALANCED_FAIRNESS
    carries no Pareto-efficiency invariant)."""
    tiny = np.finfo(np.float64).tiny
    wants = np.asarray(wants, dtype=np.float64)
    x = np.asarray(weights, dtype=np.float64)
    fixed = np.zeros(wants.shape, dtype=bool)
    remcap = float(capacity)

    def ratios(fixed):
        live = ~fixed
        X = float(np.sum(np.where(live, x, 0.0)))
        cap_ratio = X / max(remcap, tiny)
        ratio = np.where(
            live & (wants > 0), x / np.maximum(wants, tiny), 0.0
        )
        return cap_ratio, ratio, float(np.max(ratio, initial=0.0))

    for _ in range(rounds):
        cap_ratio, ratio, max_ratio = ratios(fixed)
        if max_ratio > cap_ratio:
            peel = (~fixed) & (wants > 0) & (ratio >= max_ratio)
            fixed = fixed | peel
            remcap = remcap - float(np.sum(np.where(peel, wants, 0.0)))
    cap_ratio, _ratio, max_ratio = ratios(fixed)
    return max(cap_ratio, max_ratio), fixed


def balanced_fairness_tick(
    capacity: float, wants: np.ndarray, subclients: np.ndarray
) -> np.ndarray:
    """Balanced fairness for one pool: cap-fixed classes get their
    wants; the rest get their weight share xᵢ/θ at the final binding
    ratio (clamped at wants — θ is not monotone across rounds)."""
    tiny = np.finfo(np.float64).tiny
    wants = np.asarray(wants, dtype=np.float64)
    x = np.asarray(subclients, dtype=np.float64)
    if float(np.sum(wants)) <= capacity:
        return wants.copy()
    theta, fixed = balanced_theta(capacity, wants, x)
    nu = 1.0 / max(theta, tiny)
    return np.where(fixed, wants, np.minimum(wants, x * nu))


# The ONE f32 parity bound (BASELINE.md "parity ladder"): the f32 /
# pallas solve must stay within this of the f64 oracles, relative to the
# row's grant scale. Enforced off-chip by tests/test_f32_parity.py and
# on-chip by bench.gate_pallas_kernels — both import it from here so a
# re-characterization cannot desynchronize the two gates.
F32_PARITY_REL_BOUND = 1e-6


def oracle_row(
    kind: int,
    capacity: float,
    static_capacity: float,
    wants: np.ndarray,
    has: np.ndarray,
    subclients: np.ndarray,
) -> np.ndarray:
    """Dispatch one resource row to its lane oracle — the shared
    comparison helper for every f32/pallas parity check."""
    from doorman_tpu.algorithms.kinds import AlgoKind

    if kind == AlgoKind.NO_ALGORITHM:
        return none_tick(wants)
    if kind == AlgoKind.STATIC:
        return static_tick(static_capacity, wants)
    if kind == AlgoKind.PROPORTIONAL_SHARE:
        return proportional_snapshot(capacity, wants, has)
    if kind == AlgoKind.PROPORTIONAL_TOPUP:
        return proportional_topup_snapshot(
            capacity, wants, has, subclients
        )
    if kind == AlgoKind.FAIR_SHARE:
        return fair_share_waterfill(capacity, wants, subclients)
    if kind == AlgoKind.MAX_MIN_FAIR:
        return max_min_fair_tick(capacity, wants)
    if kind == AlgoKind.BALANCED_FAIRNESS:
        return balanced_fairness_tick(capacity, wants, subclients)
    if kind == AlgoKind.PROPORTIONAL_FAIRNESS:
        return proportional_fairness_tick(capacity, wants, subclients)
    raise ValueError(f"no scalar oracle for algorithm lane {kind}")
