"""Per-tick (batch snapshot) allocation semantics — numpy oracles.

The TPU solver recomputes every lease of a resource at once from a coherent
snapshot taken at the start of a tick, instead of the reference's
per-request incremental updates. These numpy implementations DEFINE that
batch semantics; the JAX kernels in `doorman_tpu.solver` must match them
bit-for-bit (given exactly-representable inputs), and tests relate them back
to the reference's incremental semantics (they share fixed points).

Semantics notes (decisions the reference leaves implicit, recorded here per
SURVEY.md §7 "hard parts"):

  * Proportional share follows the simulation form
    (/root/reference/simulation/algo_proportional.py:31-65): in overload
    every client is scaled by capacity / all_wants, clamped by the free
    capacity. Two flavors:
      - `proportional_snapshot`: free capacity for every client is computed
        from the pre-tick grants (embarrassingly parallel; the headline
        kernel semantics);
      - `proportional_sequential`: exact replay of the simulation's
        client-by-client order, where earlier grants in the tick shrink the
        free capacity seen by later clients (the parity-oracle mode; the
        solver implements it as a lax.scan lane).
  * Fair share in batch form is FULL weighted max-min water-filling (the
    ideal the reference documents in doc/algorithms.md:59-69); the Go code's
    two-round redistribution (algorithm.go:95-211) is its truncation and is
    kept only as the scalar per-request algorithm. In a whole-tick solve the
    sum constraint is enforced exactly by the water level, so the per-client
    "available" clamp of the incremental form is unnecessary.
  * Static / None / Learn are pointwise.
"""

from __future__ import annotations

import numpy as np

__all__ = [
    "none_tick",
    "static_tick",
    "learn_tick",
    "proportional_snapshot",
    "proportional_sequential",
    "proportional_topup_snapshot",
    "fair_share_waterfill",
    "waterfill_level",
    "oracle_row",
    "F32_PARITY_REL_BOUND",
]


def none_tick(wants: np.ndarray) -> np.ndarray:
    return wants.copy()


def static_tick(per_client_capacity: float, wants: np.ndarray) -> np.ndarray:
    return np.minimum(per_client_capacity, wants)


def learn_tick(has: np.ndarray) -> np.ndarray:
    return has.copy()


def proportional_snapshot(
    capacity: float, wants: np.ndarray, has_prev: np.ndarray
) -> np.ndarray:
    """Proportional share for one resource, all clients from one snapshot.

    `has_prev` are the grants outstanding from the previous tick; the free
    capacity seen by client i excludes its own previous grant (the sim
    clears the requester's `has` before summing leases).
    """
    all_wants = float(np.sum(wants))
    sum_leases = float(np.sum(has_prev))
    free = np.maximum(capacity - (sum_leases - has_prev), 0.0)
    if all_wants < capacity:
        return np.minimum(wants, free)
    proportion = capacity / all_wants
    return np.minimum(wants * proportion, free)


def proportional_sequential(
    capacity: float, wants: np.ndarray, has_prev: np.ndarray
) -> np.ndarray:
    """Exact replay of the simulation's per-client processing order within
    one tick: client i's free capacity reflects the new grants of clients
    0..i-1 and the previous grants of clients i+1.. ."""
    n = wants.shape[0]
    gets = np.zeros_like(wants)
    all_wants = float(np.sum(wants))
    sum_leases = float(np.sum(has_prev))  # running total of live leases
    proportion = capacity / all_wants if all_wants >= capacity else None
    for i in range(n):
        free = max(capacity - (sum_leases - has_prev[i]), 0.0)
        if proportion is None:
            g = min(wants[i], free)
        else:
            g = min(wants[i] * proportion, free)
        gets[i] = g
        sum_leases += g - has_prev[i]
    return gets


def proportional_topup_snapshot(
    capacity: float,
    wants: np.ndarray,
    has_prev: np.ndarray,
    subclients: np.ndarray,
) -> np.ndarray:
    """Snapshot form of the Go proportional share (equal share plus a top-up
    proportional to excess demand, reference algorithm.go:213-292): clients
    under their equal share (or when total wants fit) get their wants;
    otherwise equal_share_i + (wants_i - equal_share_i) * extra_capacity /
    extra_need. Grants are clamped by the capacity unused as of the
    snapshot. With all clients recomputed from one snapshot the reference's
    request-order dependence disappears."""
    wants = np.asarray(wants, dtype=np.float64)
    has_prev = np.asarray(has_prev, dtype=np.float64)
    sub = np.asarray(subclients, dtype=np.float64)
    count = float(np.sum(sub))
    sum_wants = float(np.sum(wants))
    sum_has = float(np.sum(has_prev))
    equal = (capacity / count) * sub
    # Unlike the Go form this clamps at 0 (a store overcommitted by a
    # previous learning phase must not produce negative grants); the sim's
    # free-capacity rule does the same.
    unused = np.maximum(capacity - (sum_has - has_prev), 0.0)
    if sum_wants <= capacity:
        return np.minimum(wants, unused)
    under = wants < equal
    extra_capacity = float(np.sum(np.where(under, equal - wants, 0.0)))
    extra_need = float(np.sum(np.where(under, 0.0, wants - equal)))
    topped = equal + (wants - equal) * (extra_capacity / extra_need)
    return np.where(
        wants <= equal, np.minimum(wants, unused), np.minimum(topped, unused)
    )


def waterfill_level(
    capacity: float, wants: np.ndarray, weights: np.ndarray
) -> float:
    """Exact water level L for weighted max-min fairness: each client gets
    min(wants_i, L * w_i) and the grants sum to `capacity` (assuming
    sum(wants) >= capacity; otherwise returns max ratio so everyone is
    satisfied). Computed by sorting the saturation ratios wants_i / w_i."""
    w = np.asarray(weights, dtype=np.float64)
    wants = np.asarray(wants, dtype=np.float64)
    if float(np.sum(wants)) <= capacity:
        ratios = np.where(w > 0, wants / np.maximum(w, 1e-300), 0.0)
        return float(np.max(ratios, initial=0.0))
    order = np.argsort(np.where(w > 0, wants / np.maximum(w, 1e-300), np.inf))
    r = (wants / np.maximum(w, 1e-300))[order]
    w_sorted = w[order]
    wants_sorted = wants[order]
    # After the first k clients saturate (get their wants), the rest share
    # the remainder at level L = remaining / remaining_weight; L is valid
    # when r[k-1] <= L <= r[k]. Zero-weight clients sort last (infinite
    # ratio) and can absorb no water: once the weighted clients are all
    # saturated, the level is the largest finite saturation ratio — NOT
    # zero, which would wrongly zero the already-saturated grants.
    remaining = capacity
    remaining_weight = float(np.sum(w_sorted))
    last_ratio = 0.0
    for k in range(len(r)):
        if remaining_weight <= 0:
            return last_ratio
        level = remaining / remaining_weight
        if level <= r[k]:
            return level
        remaining -= wants_sorted[k]
        remaining_weight -= w_sorted[k]
        if np.isfinite(r[k]):
            last_ratio = float(r[k])
    return (
        remaining / remaining_weight if remaining_weight > 0 else last_ratio
    )


def fair_share_waterfill(
    capacity: float, wants: np.ndarray, subclients: np.ndarray
) -> np.ndarray:
    """Full weighted max-min fair share: if total wants fit, grant wants;
    otherwise grant min(wants_i, L * subclients_i) at the exact water level."""
    wants = np.asarray(wants, dtype=np.float64)
    sub = np.asarray(subclients, dtype=np.float64)
    if float(np.sum(wants)) <= capacity:
        return wants.copy()
    level = waterfill_level(capacity, wants, sub)
    return np.minimum(wants, level * sub)


# The ONE f32 parity bound (BASELINE.md "parity ladder"): the f32 /
# pallas solve must stay within this of the f64 oracles, relative to the
# row's grant scale. Enforced off-chip by tests/test_f32_parity.py and
# on-chip by bench.gate_pallas_kernels — both import it from here so a
# re-characterization cannot desynchronize the two gates.
F32_PARITY_REL_BOUND = 1e-6


def oracle_row(
    kind: int,
    capacity: float,
    static_capacity: float,
    wants: np.ndarray,
    has: np.ndarray,
    subclients: np.ndarray,
) -> np.ndarray:
    """Dispatch one resource row to its lane oracle — the shared
    comparison helper for every f32/pallas parity check."""
    from doorman_tpu.algorithms.kinds import AlgoKind

    if kind == AlgoKind.NO_ALGORITHM:
        return none_tick(wants)
    if kind == AlgoKind.STATIC:
        return static_tick(static_capacity, wants)
    if kind == AlgoKind.PROPORTIONAL_SHARE:
        return proportional_snapshot(capacity, wants, has)
    if kind == AlgoKind.PROPORTIONAL_TOPUP:
        return proportional_topup_snapshot(
            capacity, wants, has, subclients
        )
    if kind == AlgoKind.FAIR_SHARE:
        return fair_share_waterfill(capacity, wants, subclients)
    raise ValueError(f"no scalar oracle for algorithm lane {kind}")
