"""Scalar (per-request) allocation algorithms.

These are the sequential oracles: exact reimplementations of the reference
semantics used (a) by the server between batched ticks, and (b) as the parity
reference for the batched TPU kernels in `doorman_tpu.solver`.
"""

from doorman_tpu.algorithms.kinds import AlgoKind  # noqa: F401
from doorman_tpu.algorithms.scalar import (  # noqa: F401
    Request,
    balanced_fairness,
    get_algorithm,
    get_parameter,
    learn,
    max_min_fair,
    no_algorithm,
    proportional_fairness,
    proportional_share,
    proportional_topup,
    static,
    fair_share,
)
