"""Discrete-event simulation harness.

Capability parity with the reference's `simulation/` tree (scheduler,
server/client models, server jobs with election mishaps, scenarios 1-7,
varz + CSV reporter), redesigned: no module-level singletons — a `Sim`
context owns the clock, scheduler, metrics, and config — and the server
model is built on the framework's own LeaseStore/algorithm semantics
instead of a third implementation.

Used as a deterministic regression suite (scenarios assert convergence and
utilization) and as a load model for the batched solver.
"""

from doorman_tpu.sim.core import Sim, SimClock, Scheduler  # noqa: F401
from doorman_tpu.sim.varz import Counter, Gauge, Varz  # noqa: F401
