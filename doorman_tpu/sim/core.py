"""Simulation kernel: virtual clock + discrete-event scheduler +
pseudo-threads (capability parity with reference simulation/scheduler.py
and utils.py, instance-scoped)."""

from __future__ import annotations

import heapq
import itertools
import logging
import random
from typing import Callable, Dict, List, Optional, Protocol

from doorman_tpu.sim.varz import Varz

log = logging.getLogger("doorman_tpu.sim")


class SimClock:
    """Monotonic virtual clock starting at 0."""

    def __init__(self):
        self.time = 0.0

    def __call__(self) -> float:
        return self.time

    def get_time(self) -> float:
        return self.time

    def set_time(self, t: float) -> None:
        assert t >= self.time, "the clock can only move forward"
        self.time = t


class Thread(Protocol):
    """A pseudo-thread: thread_continue() runs one step and returns the
    interval until its next step."""

    def thread_continue(self) -> float: ...


class Scheduler:
    """Single-threaded discrete-event scheduler over a SimClock: absolute/
    relative one-shot actions, pseudo-threads, and exit finalizers."""

    def __init__(self, clock: SimClock):
        self.clock = clock
        self._heap: List = []  # (time, seq, callable)
        self._seq = itertools.count()
        self._threads: Dict[object, float] = {}  # thread -> next run time
        self._finalizers: List[Callable[[], None]] = []

    def add_absolute(self, when: float, action: Callable[[], None]) -> None:
        if when < self.clock.get_time():
            # Run late instead of trying to move the clock backwards.
            log.warning("scheduling action in the past (t=%s)", when)
            when = self.clock.get_time()
        heapq.heappush(self._heap, (when, next(self._seq), action))

    def add_relative(self, delay: float, action: Callable[[], None]) -> None:
        self.add_absolute(self.clock.get_time() + delay, action)

    def add_thread(self, thread: Thread, delay: float = 0.0) -> None:
        self.update_thread(thread, delay)

    def update_thread(self, thread: Thread, delay: float) -> None:
        self._threads[thread] = self.clock.get_time() + delay

    def add_finalizer(self, fn: Callable[[], None]) -> None:
        self._finalizers.append(fn)

    def _next_time(self) -> Optional[float]:
        times = []
        if self._heap:
            times.append(self._heap[0][0])
        if self._threads:
            times.append(min(self._threads.values()))
        return min(times) if times else None

    def loop(self, duration: float) -> None:
        """Run until the virtual clock advances by `duration`, then run the
        finalizers."""
        until = self.clock.get_time() + duration
        while self.clock.get_time() < until:
            t = self._next_time()
            if t is None:
                break
            t = min(t, until)
            self.clock.set_time(t)
            while self._heap and self._heap[0][0] <= t:
                _, _, action = heapq.heappop(self._heap)
                action()
            for thread, when in list(self._threads.items()):
                if when <= t and thread in self._threads:
                    self.update_thread(thread, thread.thread_continue())
        self.clock.set_time(until)
        for fn in self._finalizers:
            fn()


class Sim:
    """One simulation world: clock, scheduler, metrics, RNG, registries."""

    def __init__(self, seed: int = 0):
        self.clock = SimClock()
        self.scheduler = Scheduler(self.clock)
        # Gauge timers measure on the virtual axis: a sim report is a
        # pure function of (seed, scenario), never of host speed.
        self.varz = Varz(clock=self.clock)
        self.random = random.Random(seed)
        # Populated by the model layer.
        self.server_jobs: List = []
        self.clients: List = []
        # Name sequence numbers for servers/clients, scoped to this Sim so
        # repeated runs in one process stay deterministic.
        self.name_counters: Dict[str, int] = {}

    def next_name(self, kind: str, base: str) -> str:
        key = f"{kind}:{base}"
        self.name_counters[key] = self.name_counters.get(key, 0) + 1
        return f"{base}:{self.name_counters[key]}"

    def random_client(self):
        return self.random.choice(self.clients)

    def random_server_job(self):
        return self.random.choice(self.server_jobs)
