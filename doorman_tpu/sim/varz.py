"""Simulation metrics: counters and min/max/avg gauges with timers
(capability parity with reference simulation/varz.py, but registry-scoped
per Sim instead of process-global)."""

from __future__ import annotations

import math
import time
from typing import Callable, Dict, Iterator


class Counter:
    def __init__(self, name: str):
        self.name = name
        self.value = 0

    def inc(self, n: int = 1) -> None:
        self.value += n


class Gauge:
    def __init__(self, name: str, clock: Callable[[], float] = time.monotonic):
        # The timer clock is injectable so a Sim's gauges measure on its
        # virtual axis (deterministic reports) while a standalone Gauge
        # keeps wall time.
        self.name = name
        self.value = 0.0
        self.n = 0
        self.min_value = math.inf
        self.max_value = -math.inf
        self.average = 0.0
        self._clock = clock
        self._timer_start = 0.0

    def set(self, value: float) -> None:
        self.value = value
        self.min_value = min(self.min_value, value)
        self.max_value = max(self.max_value, value)
        self.n += 1
        self.average += (value - self.average) / self.n

    def start_timer(self) -> None:
        self._timer_start = self._clock()

    def stop_timer(self) -> None:
        self.set(self._clock() - self._timer_start)
        self._timer_start = 0.0


class Varz:
    """Per-simulation metric registry."""

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._clock = clock

    def counter(self, name: str) -> Counter:
        if name not in self._counters:
            self._counters[name] = Counter(name)
        return self._counters[name]

    def gauge(self, name: str) -> Gauge:
        if name not in self._gauges:
            self._gauges[name] = Gauge(name, clock=self._clock)
        return self._gauges[name]

    def counters(self) -> Iterator[Counter]:
        return iter(self._counters.values())

    def gauges(self) -> Iterator[Gauge]:
        return iter(self._gauges.values())
