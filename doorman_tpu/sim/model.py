"""Simulation model: servers, clients, server jobs.

Capability parity with reference simulation/server.py, client.py,
server_job.py and server_state_wrapper.py, rebuilt on the framework core:
each simulated server tracks leases in the framework's LeaseStore (clients
and downstream servers share one store, exactly like the real
CapacityServer) and runs the framework's scalar algorithms; sim-specific
behaviors layered on top are the refresh-interval decay per tree level
(decay^level * refresh), lease expiry clamped to the server's own lease
from below, the 2-second per-client request throttle, learning mode after
an election win, and unmanaged resources granted verbatim.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from doorman_tpu.algorithms import Request, get_algorithm, get_parameter
from doorman_tpu.core.lease import Lease
from doorman_tpu.core.store import LeaseStore
from doorman_tpu.proto import doorman_pb2 as pb
from doorman_tpu.server.config import find_template
from doorman_tpu.sim.core import Sim

log = logging.getLogger("doorman_tpu.sim")

# Reference sim constants (simulation/server.py:27-41).
DEFAULT_LEASE_UNKNOWN_RESOURCE = 300.0
MINIMUM_REQUEST_INTERVAL = 2.0
DEFAULT_REFRESH_INTERVAL = 5.0
DEFAULT_DISCOVERY_INTERVAL = 5.0
END_OF_TIME = 86400.0
DEFAULT_DECAY_FACTOR = 0.5


@dataclass
class SimConfig:
    """Simulation-wide resource configuration: a repository of templates
    (glob-matched, like the server config) whose algorithm parameters may
    carry a decay_factor; ids matching no template are unmanaged."""

    repository: pb.ResourceRepository

    @classmethod
    def default(cls) -> "SimConfig":
        """The reference sim's global config
        (simulation/global_config.py:19-45): resource0 with capacity 500,
        safe capacity 10, ProportionalShare at refresh 8s / lease 60s."""
        repo = pb.ResourceRepository()
        t = repo.resources.add()
        t.identifier_glob = "resource0"
        t.capacity = 500.0
        t.safe_capacity = 10.0
        t.algorithm.kind = pb.Algorithm.PROPORTIONAL_SHARE
        t.algorithm.lease_length = 60
        t.algorithm.refresh_interval = 8
        return cls(repo)

    @classmethod
    def portfolio(cls, wire_kind: int, variant: "str | None") -> "SimConfig":
        """The default config re-pointed at a fairness-portfolio lane
        (doc/algorithms.md "The fairness portfolio"): same capacity /
        lease shape, the algorithm selected by wire kind + `variant`
        parameter — the sim-side half of the per-algorithm scenario
        diversity (chaos parametrizes master_flap_warm the same way)."""
        cfg = cls.default()
        algo = cfg.repository.resources[0].algorithm
        algo.kind = int(wire_kind)
        if variant is not None:
            algo.parameters.add(name="variant", value=variant)
        return cfg

    def find(self, resource_id: str) -> Optional[pb.ResourceTemplate]:
        return find_template(self.repository, resource_id)


def decay_factor(algo: pb.Algorithm) -> float:
    raw = get_parameter(algo, "decay_factor")
    return float(raw) if raw is not None else DEFAULT_DECAY_FACTOR


@dataclass
class ResponseLease:
    capacity: float
    expiry_time: float
    refresh_interval: float


@dataclass
class SimResource:
    """One resource on one simulated server."""

    template: pb.ResourceTemplate
    store: LeaseStore
    learning_expiry: float
    # The server's own capacity lease (from config at the root, from the
    # downstream master otherwise).
    has: Optional[ResponseLease] = None
    last_request: Dict[str, float] = field(default_factory=dict)


class SimServer:
    """One simulated server task (reference simulation/server.py)."""

    def __init__(self, sim: Sim, job, job_name: str, level: int,
                 downstream_job=None, config: Optional[SimConfig] = None):
        assert (level == 0) == (downstream_job is None)
        self.sim = sim
        self.job = job
        self.level = level
        self.downstream_job = downstream_job
        self.config = config or SimConfig.default()
        self.server_id = sim.next_name("server", job_name)
        self.master = None  # downstream master (for level > 0)
        self.election_victory_time: Optional[float] = None
        self.resources: Dict[str, SimResource] = {}
        sim.scheduler.add_thread(self, 0.0)

    # -- mastership ----------------------------------------------------

    def is_master(self) -> bool:
        return self.election_victory_time is not None

    def become_master(self) -> None:
        assert not self.is_master()
        log.info("%s becoming master", self.server_id)
        assert not self.resources
        self.election_victory_time = self.sim.clock.get_time()
        self.sim.scheduler.update_thread(self, 0.0)

    def lose_mastership(self) -> None:
        assert self.is_master()
        log.info("%s losing mastership", self.server_id)
        self.election_victory_time = None
        self.resources = {}
        self.master = None

    # -- resource state ------------------------------------------------

    def _max_lease_duration(self, algo: pb.Algorithm) -> float:
        return float(algo.lease_length)

    def _refresh_interval(self, algo: pb.Algorithm) -> float:
        """Per-level refresh decay (reference algorithm.py:96-99)."""
        return int(
            (decay_factor(algo) ** self.level) * float(algo.refresh_interval)
        )

    def find_resource(self, resource_id: str) -> Optional[SimResource]:
        res = self.resources.get(resource_id)
        if res is not None:
            return res
        template = self.config.find(resource_id)
        if template is None:
            return None
        # Learning mode ends one max lease duration after the election win
        # (reference server_state_wrapper.py:216-217).
        res = SimResource(
            template=template,
            store=LeaseStore(resource_id, clock=self.sim.clock),
            learning_expiry=(
                self.election_victory_time
                + self._max_lease_duration(template.algorithm)
            ),
        )
        self.resources[resource_id] = res
        return res

    def _cleanup(self) -> None:
        now = self.sim.clock.get_time()
        for res in self.resources.values():
            res.store.clean()
            if res.has is not None and res.has.expiry_time <= now:
                res.has = None

    def _create_lease(self, res: SimResource, capacity: float) -> ResponseLease:
        """Lease stamping with the sim's clamping rules
        (reference algorithm.py:108-133)."""
        now = self.sim.clock.get_time()
        algo = res.template.algorithm
        refresh = self._refresh_interval(algo)
        expiry = now + float(algo.lease_length)
        if res.has is not None:
            expiry = min(expiry, res.has.expiry_time)
        if now + refresh >= expiry:
            refresh = max(expiry - now - 1, 1.0)
        return ResponseLease(capacity, expiry, refresh)

    def _decide(self, res: SimResource, client_id: str, wants: float,
                has_capacity: float, subclients: int) -> ResponseLease:
        """Insert the demand and run the resource's algorithm (or the
        learning-mode replay), stamping sim lease rules."""
        now = self.sim.clock.get_time()
        available = res.has.capacity if res.has is not None else 0.0

        if res.learning_expiry >= now:
            gets = has_capacity
            self.sim.varz.counter("server.learning_mode_response").inc()
        else:
            algo = get_algorithm(res.template.algorithm)
            # The framework's scalar algorithms run against the shared
            # store with the server's own lease as the capacity baseline.
            lease = algo(
                res.store, available,
                Request(client_id, has_capacity, wants, subclients),
            )
            gets = lease.has
            self.sim.varz.counter("server.algorithm_runs").inc()

        out = self._create_lease(res, gets)
        # (Re)assign with the clamped expiry so store cleanup follows the
        # sim's lease rules; keep whatever priority the algorithm recorded.
        res.store.assign(
            client_id,
            out.expiry_time - now,
            out.refresh_interval,
            gets,
            wants,
            subclients,
            priority=res.store.get(client_id).priority,
        )
        return out

    # -- RPCs ----------------------------------------------------------

    def Discovery_RPC(self, client_id: str, resource_ids: List[str]):
        """Returns (master_id or None, {resource_id: safe_capacity})."""
        master = self.job.get_master()
        safe = {}
        for rid in resource_ids:
            t = self.config.find(rid)
            if t is not None and t.HasField("safe_capacity"):
                safe[rid] = t.safe_capacity
        if master is None:
            self.sim.varz.counter("server.incomplete_discovery_response").inc()
        return (master.server_id if master else None), safe

    def _handle_capacity(self, caller_id: str, requests, subclients_of) -> (
        Optional[Dict[str, ResponseLease]]
    ):
        """Common GetCapacity/GetServerCapacity path: throttle, update
        state, decide. requests: [(resource_id, wants, has_capacity)]."""
        if not self.is_master():
            self.sim.varz.counter("server.not_master_response").inc()
            return None
        now = self.sim.clock.get_time()
        self._cleanup()
        out: Dict[str, ResponseLease] = {}
        for resource_id, wants, has_capacity in requests:
            res = self.find_resource(resource_id)
            if res is None:
                # Unmanaged resource: grant verbatim.
                log.warning(
                    "%s request for unmanaged resource %s",
                    self.server_id, resource_id,
                )
                out[resource_id] = ResponseLease(
                    wants, now + DEFAULT_LEASE_UNKNOWN_RESOURCE,
                    DEFAULT_REFRESH_INTERVAL,
                )
                continue
            last = res.last_request.get(caller_id)
            if last is not None and now - last < MINIMUM_REQUEST_INTERVAL:
                self.sim.varz.counter("server.throttled_request").inc()
                continue
            res.last_request[caller_id] = now
            out[resource_id] = self._decide(
                res, caller_id, wants, has_capacity, subclients_of(resource_id)
            )
        return out

    def GetCapacity_RPC(self, client_id: str, requests):
        """requests: [(resource_id, wants, has_capacity)]. Returns
        {resource_id: (ResponseLease, safe_capacity or None)} or None when
        not master."""
        grants = self._handle_capacity(client_id, requests, lambda rid: 1)
        if grants is None:
            return None
        out = {}
        for rid, lease in grants.items():
            template = self.config.find(rid)
            safe = (
                template.safe_capacity
                if template is not None and template.HasField("safe_capacity")
                else None
            )
            out[rid] = (lease, safe)
        return out

    def GetServerCapacity_RPC(self, server_id: str, requests):
        """requests: [(resource_id, bands, has_capacity)] where bands is
        [(priority, num_clients, wants)]. Returns {resource_id:
        ResponseLease} or None when not master."""
        flat = []
        subclients = {}
        for resource_id, bands, has_capacity in requests:
            wants_total = sum(w for _, _, w in bands)
            subclients[resource_id] = max(
                sum(n for _, n, _ in bands), 1
            )
            flat.append((resource_id, wants_total, has_capacity))
        return self._handle_capacity(
            server_id, flat, lambda rid: subclients[rid]
        )

    # -- own capacity refresh (the server tree edge) ---------------------

    def _discover_downstream(self) -> bool:
        master_id, _ = self.downstream_job.get_random_task().Discovery_RPC(
            self.server_id, []
        )
        if master_id is None:
            self.master = None
            self.sim.varz.counter("server.discovery_failure").inc()
            return False
        self.master = self.downstream_job.get_task_by_name(master_id)
        return True

    def _get_capacity(self) -> bool:
        now = self.sim.clock.get_time()
        if self.level == 0:
            # Root: capacity comes from the configuration; the old lease is
            # discarded first (no clamping against it) and the refresh
            # interval doubled (reference server.py:221-234).
            for res in self.resources.values():
                res.has = None
                lease = self._create_lease(res, res.template.capacity)
                lease.refresh_interval *= 2
                res.has = lease
            return True
        # Non-root: lease capacity from the downstream master.
        requests = []
        for rid, res in self.resources.items():
            status = res.store
            bands = [(1, max(status.count, 1), status.sum_wants)]
            has_cap = res.has.capacity if res.has is not None else 0.0
            requests.append((rid, bands, has_cap))
        if not requests:
            return True
        grants = self.master.GetServerCapacity_RPC(self.server_id, requests)
        if grants is None:
            return False
        for rid, lease in grants.items():
            self.resources[rid].has = lease
        return True

    def thread_continue(self) -> float:
        if not self.is_master():
            return END_OF_TIME
        if self.level > 0 and self.master is None:
            if not self._discover_downstream():
                return DEFAULT_DISCOVERY_INTERVAL
        if not self._get_capacity():
            self.master = None
            self.sim.varz.counter("server.reschedule_discovery").inc()
            return 0.0
        delay = min(
            (
                res.has.refresh_interval
                for res in self.resources.values()
                if res.has is not None
            ),
            default=DEFAULT_REFRESH_INTERVAL,
        )
        if delay <= 0:
            delay = DEFAULT_REFRESH_INTERVAL
        return delay


class ServerJob:
    """A job of N server tasks with a (randomly elected) master
    (reference simulation/server_job.py)."""

    def __init__(self, sim: Sim, job_name: str, level: int, size: int,
                 downstream_job=None, config: Optional[SimConfig] = None):
        self.sim = sim
        self.job_name = job_name
        self.tasks: Dict[str, SimServer] = {}
        self.master: Optional[SimServer] = None
        for _ in range(size):
            s = SimServer(sim, self, job_name, level, downstream_job, config)
            self.tasks[s.server_id] = s
        self.trigger_master_election()
        sim.server_jobs.append(self)

    def get_master(self) -> Optional[SimServer]:
        return self.master

    def get_task_by_name(self, name: str) -> SimServer:
        return self.tasks[name]

    def get_random_task(self) -> SimServer:
        return self.sim.random.choice(list(self.tasks.values()))

    def lose_master(self) -> None:
        """Fault injection: the master goes away; no successor elected."""
        if self.master is not None:
            self.master.lose_mastership()
            self.master = None

    def trigger_master_election(self) -> None:
        old = self.master
        self.master = self.get_random_task()
        if old is self.master:
            return
        if old is not None:
            old.lose_mastership()
        self.master.become_master()


class SimClient:
    """A simulated client (reference simulation/client.py): discovers the
    master, refreshes all its resources, randomly fluctuates wants."""

    def __init__(self, sim: Sim, name: str, downstream_job: ServerJob):
        self.sim = sim
        self.downstream_job = downstream_job
        self.client_id = sim.next_name("client", name)
        self.master: Optional[SimServer] = None
        # resource_id -> state dict(wants, priority, has: ResponseLease|None,
        #                           safe_capacity)
        self.resources: Dict[str, dict] = {}
        sim.clients.append(self)
        sim.scheduler.add_thread(self, 0.0)

    def add_resource(self, resource_id: str, priority: int, wants: float,
                     fraction: float = 0.0, interval: float = 1.0) -> None:
        assert resource_id not in self.resources
        self.resources[resource_id] = {
            "wants": wants, "priority": priority, "has": None,
            "safe_capacity": None,
        }
        if fraction > 0:
            self._change_wants(resource_id, fraction, interval)
        self.sim.scheduler.update_thread(self, 0.0)

    def _change_wants(self, resource_id: str, fraction: float,
                      interval: float) -> None:
        state = self.resources[resource_id]
        w = state["wants"]
        w += fraction * (1 - 2 * self.sim.random.random()) * w
        state["wants"] = max(w, 0.0)
        self.sim.varz.gauge(f"client.{self.client_id}.wants").set(
            state["wants"]
        )
        self.sim.scheduler.add_relative(
            interval, lambda: self._change_wants(resource_id, fraction, interval)
        )

    def set_wants(self, resource_id: str, wants: float) -> None:
        self.resources[resource_id]["wants"] = wants

    def get_wants(self, resource_id: str) -> float:
        return self.resources[resource_id]["wants"]

    def current_capacity(self, resource_id: str) -> float:
        has = self.resources[resource_id]["has"]
        return has.capacity if has is not None else 0.0

    def _discover(self) -> bool:
        task = self.downstream_job.get_random_task()
        master_id, safe = task.Discovery_RPC(
            self.client_id, list(self.resources)
        )
        for rid, cap in safe.items():
            self.resources[rid]["safe_capacity"] = cap
        if master_id is None:
            self.master = None
            self.sim.varz.counter("client.discovery_failure").inc()
            return False
        self.master = self.downstream_job.get_task_by_name(master_id)
        return True

    def _maybe_lease_expired(self, resource_id: str) -> None:
        state = self.resources.get(resource_id)
        if state is None or state["has"] is None:
            return
        if state["has"].expiry_time <= self.sim.clock.get_time():
            state["has"] = None
            self.sim.varz.counter("client.lease_expired").inc()

    def _get_capacity(self) -> bool:
        if not self.resources:
            return True
        requests = [
            (
                rid,
                state["wants"],
                state["has"].capacity if state["has"] is not None else 0.0,
            )
            for rid, state in self.resources.items()
        ]
        out = self.master.GetCapacity_RPC(self.client_id, requests)
        if out is None:
            self.sim.varz.counter("client.GetCapacity_RPC.failure").inc()
            return False
        for rid, (lease, safe) in out.items():
            state = self.resources[rid]
            state["has"] = lease
            state["safe_capacity"] = safe
            self.sim.scheduler.add_absolute(
                lease.expiry_time, lambda rid=rid: self._maybe_lease_expired(rid)
            )
        return True

    def thread_continue(self) -> float:
        if self.master is None:
            if not self._discover():
                return DEFAULT_DISCOVERY_INTERVAL
        if not self._get_capacity():
            self.master = None
            return 0.0
        delay = min(
            (
                s["has"].refresh_interval
                for s in self.resources.values()
                if s["has"] is not None
            ),
            default=DEFAULT_REFRESH_INTERVAL,
        )
        if delay <= 0:
            self.sim.varz.counter("client.improbable.delay").inc()
            delay = DEFAULT_REFRESH_INTERVAL
        return delay
