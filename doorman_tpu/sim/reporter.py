"""Simulation reporter: samples per-client and per-master state every 5s,
writes a CSV at the end, and computes the utilization/convergence summary
quoted for the reference in doc/design.md:773-799 (capability parity with
reference simulation/reporter.py)."""

from __future__ import annotations

import csv
import logging
from dataclasses import dataclass
from typing import Dict, List, Optional

from doorman_tpu.sim.core import Sim

log = logging.getLogger("doorman_tpu.sim")

REPORT_INTERVAL = 5.0


@dataclass
class Sample:
    time: float
    sum_wants: float
    sum_has: float
    capacity: float
    clients_with_lease: int


class Reporter:
    def __init__(self, sim: Sim, warmup: float = 90.0):
        self.sim = sim
        self.resource_id: Optional[str] = None
        self.filename: Optional[str] = None
        self.samples: List[Sample] = []
        # Ignore the learning/convergence phase when averaging utilization
        # (the reference quotes post-learning averages).
        self.warmup = warmup
        sim.scheduler.add_finalizer(self.finalize)

    def schedule(self, resource_id: str) -> None:
        self.resource_id = resource_id
        self.sim.scheduler.add_relative(REPORT_INTERVAL, self._tick)

    def set_filename(self, name: str) -> None:
        self.filename = name

    def _tick(self) -> None:
        self.sim.scheduler.add_relative(REPORT_INTERVAL, self._tick)
        rid = self.resource_id
        sum_wants = 0.0
        sum_has = 0.0
        holders = 0
        for client in self.sim.clients:
            state = client.resources.get(rid)
            if state is None:
                continue
            sum_wants += state["wants"]
            if state["has"] is not None:
                sum_has += state["has"].capacity
                holders += 1
        capacity = 0.0
        for job in self.sim.server_jobs:
            master = job.get_master()
            if master is None or master.level != 0:
                continue
            res = master.resources.get(rid)
            if res is not None:
                capacity = res.template.capacity
        self.samples.append(
            Sample(
                self.sim.clock.get_time(), sum_wants, sum_has, capacity, holders
            )
        )

    def summary(self) -> Dict[str, float]:
        """Post-warmup averages: utilization = handed-out / capacity among
        samples where demand exceeded capacity; overage tracks shortfall
        events (handed out > capacity)."""
        post = [
            s for s in self.samples
            if s.time >= self.warmup and s.capacity > 0
        ]
        if not post:
            return {"utilization": 0.0, "samples": 0, "overage_events": 0,
                    "max_overage": 0.0, "avg_overage": 0.0}
        overloaded = [s for s in post if s.sum_wants >= s.capacity]
        basis = overloaded or post
        utilization = sum(
            min(s.sum_has, s.capacity) / s.capacity for s in basis
        ) / len(basis)
        over = [s for s in post if s.sum_has > s.capacity * 1.001]
        # Shortfall statistics quoted by the reference design doc
        # (count / max / average overage, design.md:795-799; reference
        # reporter.py:136-263 computes them from the same samples).
        return {
            "utilization": utilization,
            "samples": len(post),
            "overage_events": len(over),
            "max_overage": max((s.sum_has for s in over), default=0.0),
            "avg_overage": (
                sum(s.sum_has for s in over) / len(over) if over else 0.0
            ),
        }

    def finalize(self) -> None:
        if self.filename:
            path = f"{self.filename}.csv"
            with open(path, "w", newline="") as f:
                w = csv.writer(f)
                w.writerow(
                    ["time", "sum_wants", "sum_has", "capacity", "holders"]
                )
                for s in self.samples:
                    w.writerow(
                        [s.time, s.sum_wants, s.sum_has, s.capacity,
                         s.clients_with_lease]
                    )
            w2 = csv.writer(open(path, "a", newline=""))
            w2.writerow([])
            for c in self.sim.varz.counters():
                w2.writerow(["counter", c.name, c.value])
            for g in self.sim.varz.gauges():
                w2.writerow(
                    ["gauge", g.name, g.value, g.min_value, g.max_value,
                     g.average]
                )
            log.info("report written to %s", path)
