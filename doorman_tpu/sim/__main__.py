"""CLI: python -m doorman_tpu.sim <scenario|all> [--run-for S] [--seed N]
[--csv]. `all` runs scenarios 1-7 sequentially (one JSON summary line
each), the counterpart of the reference's run_all_scenarios.sh."""

from __future__ import annotations

import argparse
import json
import logging


def main() -> None:
    parser = argparse.ArgumentParser(description="doorman-tpu simulation")
    from doorman_tpu.sim.scenarios import SCENARIOS

    parser.add_argument(
        "scenario", nargs="?", default=None,
        choices=sorted(SCENARIOS) + ["all"],
    )
    parser.add_argument("--list-scenarios", action="store_true",
                        help="list scenarios with one-line docs and exit")
    parser.add_argument("--run-for", type=float, default=None)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--csv", action="store_true", help="write CSV report")
    parser.add_argument("--verbose", action="store_true")
    args = parser.parse_args()

    if args.list_scenarios:
        from doorman_tpu.sim.scenarios import registry_lines

        for name, doc in registry_lines(SCENARIOS):
            print(f"{name:12s} {doc}")
        return
    if args.scenario is None:
        parser.error("a scenario is required (or --list-scenarios)")

    logging.basicConfig(
        level=logging.INFO if args.verbose else logging.WARNING,
        format="%(levelname)s %(message)s",
    )

    from doorman_tpu.sim.scenarios import run_scenario

    scenarios = list("1234567") if args.scenario == "all" else [args.scenario]
    for scenario in scenarios:
        sim, reporter = run_scenario(
            scenario, args.run_for, args.seed, write_csv=args.csv
        )
        summary = reporter.summary()
        summary["scenario"] = scenario
        summary["simulated_seconds"] = sim.clock.get_time()
        print(json.dumps(summary), flush=True)


if __name__ == "__main__":
    main()
