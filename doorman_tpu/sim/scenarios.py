"""The seven reference scenarios (capability parity with reference
simulation/scenario_*.py), parameterized by a Sim context.

1: convergence — one root job x3 tasks, 5 clients with fluctuating demand.
2: master loss at T=120, re-election at T=140 (before lease expiry).
3: master loss at T=120, re-election at T=190 (after lease expiry).
4: two-level tree (root + one DC job).
5: three-level tree — root, 3 regions x 3 DCs x 5 clients = 45 clients.
6: demand spike to 1000 on two clients at T=150.
7: scenario 5 plus a random mishap every 60s for a simulated hour.
"""

from __future__ import annotations

from typing import Callable, Dict

from doorman_tpu.sim.core import Sim
from doorman_tpu.sim.model import ServerJob, SimClient
from doorman_tpu.sim.reporter import Reporter


def scenario_one(sim: Sim, reporter: Reporter) -> None:
    """Convergence: one root job x3 tasks, 5 clients, fluctuating demand."""
    job = ServerJob(sim, "root", 0, 3)
    for _ in range(5):
        c = SimClient(sim, "client", job)
        c.add_resource("resource0", 0, 110, 0.1, 10)
    reporter.schedule("resource0")
    reporter.set_filename("scenario_one")


def _master_loss(sim: Sim, reporter: Reporter, reelect_at: float) -> None:
    job = ServerJob(sim, "root", 0, 3)
    for _ in range(5):
        c = SimClient(sim, "client", job)
        c.add_resource("resource0", 0, 110, 0.1, 10)
    sim.scheduler.add_absolute(120, job.lose_master)
    sim.scheduler.add_absolute(reelect_at, job.trigger_master_election)
    reporter.schedule("resource0")


def scenario_two(sim: Sim, reporter: Reporter) -> None:
    """Master loss at T=120, re-election at T=140 (before lease expiry)."""
    # Re-election before the 60s leases expire: clients keep capacity.
    _master_loss(sim, reporter, reelect_at=140)
    reporter.set_filename("scenario_two")


def scenario_three(sim: Sim, reporter: Reporter) -> None:
    """Master loss at T=120, re-election at T=190 (after lease expiry)."""
    # Re-election after lease expiry: clients drop to zero, then recover.
    _master_loss(sim, reporter, reelect_at=190)
    reporter.set_filename("scenario_three")


def scenario_four(sim: Sim, reporter: Reporter) -> None:
    """Two-level tree: root plus one DC job."""
    root = ServerJob(sim, "root", 0, 3)
    dc = ServerJob(sim, "dc", 1, 3, root)
    for _ in range(5):
        c = SimClient(sim, "client", dc)
        c.add_resource("resource0", 0, 110, 0.1, 10)
    reporter.schedule("resource0")
    reporter.set_filename("scenario_four")


def scenario_five(sim: Sim, reporter: Reporter, num_clients: int = 5) -> None:
    """Three-level tree: root, 3 regions x 3 DCs x 5 clients each."""
    root = ServerJob(sim, "root", 0, 3)
    for i in range(1, 4):
        region = ServerJob(sim, f"region:{i}", 1, 3, root)
        for j in range(1, 4):
            dc = ServerJob(sim, f"dc:{i}:{j}", 2, 3, region)
            for _ in range(num_clients):
                c = SimClient(sim, f"client:{i}:{j}", dc)
                c.add_resource("resource0", 0, 15, 0.1, 10)
    reporter.schedule("resource0")
    reporter.set_filename("scenario_five")


def scenario_six(sim: Sim, reporter: Reporter) -> None:
    """Demand spike to 1000 on two clients at T=150."""
    job = ServerJob(sim, "root", 0, 3)
    clients = []
    for _ in range(5):
        c = SimClient(sim, "client", job)
        c.add_resource("resource0", 0, 50, 0.1, 10)
        clients.append(c)

    def spike():
        for c in clients[:2]:
            c.set_wants("resource0", 1000.0)

    sim.scheduler.add_absolute(150, spike)
    reporter.schedule("resource0")
    reporter.set_filename("scenario_six")


def scenario_seven(sim: Sim, reporter: Reporter) -> None:
    """Scenario 5 plus a random mishap every 60s for a simulated hour."""
    scenario_five(sim, reporter)
    reporter.set_filename("scenario_seven")

    def spike_client():
        client = sim.random_client()
        client.set_wants(
            "resource0", client.get_wants("resource0") + 100
        )
        sim.varz.counter("mishap.spike").inc()

    def trigger_election():
        sim.random_server_job().trigger_master_election()
        sim.varz.counter("mishap.election").inc()

    def lose_master():
        job = sim.random_server_job()
        delay = sim.random.randint(0, 60)
        job.lose_master()
        sim.scheduler.add_relative(delay, job.trigger_master_election)
        sim.varz.counter("mishap.lose_master").inc()

    def random_mishap():
        sim.scheduler.add_relative(60, random_mishap)
        # The reference's weighted pick, reproduced exactly
        # (scenario_seven.py:54-78): m = randint(0, 14) walked against
        # the weight map {5: spike, 10: election, 15: lose_master} in
        # Python 2 dict iteration order — which for these small-int
        # keys is [10, 5, 15] (hash slots 2, 5, 7) — picking the entry
        # once the cumulative weight reaches m. Effective distribution:
        # election 1/15, spike 10/15, lose_master 4/15. Spikes dominate
        # the reference's mishap hour; a uniform pick would inject ~5x
        # more master elections and misstate recovery behavior.
        m = sim.random.randint(0, 14)
        n = 0
        for weight, mishap in (
            (10, trigger_election),
            (5, spike_client),
            (15, lose_master),
        ):
            if n >= m:
                mishap()
                return
            n += weight

    sim.scheduler.add_absolute(60, random_mishap)


def _scenario_one_lane(wire_kind: str, variant: "str | None"):
    """scenario_one re-pointed at a fairness-portfolio lane: the same
    convergence arc (5 clients, fluctuating demand, one 110-capacity
    pool in overload) must hold whichever lane apportions it — the
    sim-side half of the per-algorithm scenario diversity."""
    from doorman_tpu.proto import doorman_pb2 as pb
    from doorman_tpu.sim.model import SimConfig

    def scenario(sim: Sim, reporter: Reporter) -> None:
        config = SimConfig.portfolio(
            getattr(pb.Algorithm, wire_kind), variant
        )
        job = ServerJob(sim, "root", 0, 3, config=config)
        for _ in range(5):
            c = SimClient(sim, "client", job)
            c.add_resource("resource0", 0, 110, 0.1, 10)
        reporter.schedule("resource0")
        reporter.set_filename(f"scenario_one_{variant or 'fair'}")

    scenario.__doc__ = (
        f"Scenario-one convergence arc on the {variant or 'fair'} lane."
    )
    return scenario


SCENARIOS: Dict[str, Callable[[Sim, Reporter], None]] = {
    "1": scenario_one,
    "2": scenario_two,
    "3": scenario_three,
    "4": scenario_four,
    "5": scenario_five,
    "6": scenario_six,
    "7": scenario_seven,
    # The fairness portfolio over the scenario-one convergence arc.
    "1_fair": _scenario_one_lane("FAIR_SHARE", None),
    "1_maxmin": _scenario_one_lane("FAIR_SHARE", "maxmin"),
    "1_balanced": _scenario_one_lane("FAIR_SHARE", "balanced"),
    "1_logutil": _scenario_one_lane("PROPORTIONAL_SHARE", "logutil"),
}

DEFAULT_DURATION: Dict[str, float] = {"7": 3600.0}


def registry_lines(registry: "Dict[str, Callable]") -> "list":
    """[(name, one-line doc), ...] for a scenario registry — what a
    CLI's --list-scenarios prints. The one-liner is the factory
    docstring's first line (the registry convention shared by the sim
    and workload scenario libraries)."""
    import inspect

    return [
        (name, (inspect.getdoc(fn) or "").splitlines()[0]
         if inspect.getdoc(fn) else "")
        for name, fn in sorted(registry.items())
    ]


def run_scenario(name: str, run_for: float | None = None, seed: int = 0,
                 write_csv: bool = False):
    """Run one scenario; returns (sim, reporter) for inspection."""
    sim = Sim(seed=seed)
    reporter = Reporter(sim)
    scenario = SCENARIOS[str(name)]
    scenario(sim, reporter)
    if not write_csv:
        reporter.set_filename(None)
    duration = run_for if run_for is not None else DEFAULT_DURATION.get(
        str(name), 300.0
    )
    sim.scheduler.loop(duration)
    return sim, reporter
