"""Adaptive QPS rate limiter: derives `wants` from the observed rate of
wait() calls.

Capability parity with reference go/ratelimiter/adaptive_ratelimiter.go:
every `window` seconds (default 10) the recorded wait() entry times are
aggregated per second and recency-weighted (most recent second has weight
N, the oldest weight 1; the weighted sum is normalized by N(N+1)/2 scaled
by the entry count) and the result is sent to resource.ask().
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Callable, List, Optional

from doorman_tpu.client.client import ClientResource
from doorman_tpu.ratelimiter.qps import QPSRateLimiter

log = logging.getLogger(__name__)

DEFAULT_WINDOW = 10.0


def wants_estimate(entries: List[float], window: float, now: float) -> float:
    """Recency-weighted wants estimate over entry timestamps
    (adaptive_ratelimiter.go:131-156). Mutates nothing; expired entries
    should already be cleared by the caller."""
    live = [t for t in entries if now - t < window]
    if not live:
        return 0.0
    n = int(window)
    frequency = {}
    for t in live:
        age = int(now - t)
        frequency[age] = frequency.get(age, 0) + 1
    weighted = sum(
        frequency.get(age, 0) * (n - age) for age in range(n)
    )
    k = len(live)
    return weighted / (k * (k + 1) / 2)


class AdaptiveQPSRateLimiter:
    def __init__(
        self,
        resource: ClientResource,
        window: float = DEFAULT_WINDOW,
        clock: Callable[[], float] = time.time,
    ):
        # `clock` is the injectable time seam (chaos hands every
        # component its virtual ChaosClock); entry timestamps and window
        # expiry both read it so a replayed run ages entries identically.
        self._resource = resource
        self._limiter = QPSRateLimiter(resource)
        self._window = window
        self._clock = clock
        self._entries: List[float] = []
        self._task = asyncio.create_task(self._run())

    async def _run(self) -> None:
        while True:
            await asyncio.sleep(self._window)
            now = self._clock()
            self._entries = [t for t in self._entries if now - t < self._window]
            wants = wants_estimate(self._entries, self._window, now)
            if wants > 0:
                try:
                    await self._resource.ask(wants)
                except Exception:
                    log.exception("resource.ask failed")

    async def wait(self, timeout: Optional[float] = None) -> None:
        self._entries.append(self._clock())
        await self._limiter.wait(timeout)

    async def close(self) -> None:
        self._task.cancel()
        try:
            await self._task
        except asyncio.CancelledError:
            pass
        await self._limiter.close()


def new_adaptive_qps(
    resource: ClientResource,
    window: float = DEFAULT_WINDOW,
    clock: Callable[[], float] = time.time,
) -> AdaptiveQPSRateLimiter:
    return AdaptiveQPSRateLimiter(resource, window, clock=clock)
