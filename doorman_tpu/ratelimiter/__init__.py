"""Rate limiters that convert capacity leases into admission control."""

from doorman_tpu.ratelimiter.qps import QPSRateLimiter, new_qps  # noqa: F401
from doorman_tpu.ratelimiter.adaptive import (  # noqa: F401
    AdaptiveQPSRateLimiter,
    new_adaptive_qps,
)
