"""QPS rate limiter: converts a resource's capacity (queries per second)
into a blocking `wait()`.

Capability parity with reference go/ratelimiter/ratelimiter.go:65-231:
  * capacity < 0 -> unlimited (wait returns immediately)
  * capacity == 0 -> blocked (wait blocks until capacity changes)
  * capacity <= 10 -> one release per 1000/capacity ms
  * capacity > 10 -> the 1-second interval is divided into subintervals of
    at least 20 ms (at most `rate` of them) and the rate is spread across
    them, with the integer remainder distributed one-per-subinterval — this
    reproduces the reference's burstiness smoothing exactly.

Releases do not accumulate: a subinterval's unconsumed budget expires with
it (the reference's unbuffered unfreeze channel has the same property).
"""

from __future__ import annotations

import asyncio
from typing import Optional

from doorman_tpu.client.client import ClientResource


class RateLimiterClosed(Exception):
    pass


class QPSRateLimiter:
    def __init__(self, resource: ClientResource):
        self._resource = resource
        self._rate = 0  # releases per subinterval; -1 unlimited, 0 blocked
        self._interval = 1.0  # subinterval length, seconds
        self._subintervals = 1
        self._leftover = 0
        self._budget = 0
        self._released_subintervals = 0
        self._leftover_remaining = 0
        self._cond = asyncio.Condition()
        self._closed = False
        self._task = asyncio.create_task(self._run())

    # -- configuration ---------------------------------------------------

    def _recalculate(self, rate: int, interval_ms: int) -> None:
        self._subintervals = 1
        self._leftover = 0
        new_rate, new_interval_ms = rate, interval_ms
        if rate > 1 and interval_ms >= 20:
            self._subintervals = min(rate, interval_ms // 20)
            new_rate = rate // self._subintervals
            self._leftover = rate % self._subintervals
            new_interval_ms = int(new_rate * interval_ms / rate)
        self._rate = new_rate
        self._interval = new_interval_ms / 1000.0

    def _update(self, capacity: float) -> None:
        if capacity < 0:
            self._rate = -1
        elif capacity == 0:
            self._rate = 0
        elif capacity <= 10:
            self._recalculate(1, int(1000.0 / capacity))
        else:
            self._recalculate(int(capacity), 1000)
        self._released_subintervals = 0
        self._leftover_remaining = self._leftover
        # Permits computed under the old capacity must not survive the
        # change (the reference's unbuffered unfreeze channel cannot carry
        # permits across an update either).
        self._budget = 0

    @property
    def unlimited(self) -> bool:
        return self._rate < 0

    @property
    def blocked(self) -> bool:
        return self._rate == 0

    # -- main loop -------------------------------------------------------

    async def _run(self) -> None:
        capacity_q = self._resource.capacity()
        while True:
            if self.blocked or self.unlimited:
                # Nothing to time; wait for a capacity change.
                capacity = await capacity_q.get()
                async with self._cond:
                    self._update(capacity)
                    self._cond.notify_all()
                continue
            # Timed subinterval; a capacity update interrupts it.
            try:
                capacity = await asyncio.wait_for(
                    capacity_q.get(), timeout=self._interval
                )
                async with self._cond:
                    self._update(capacity)
                    self._cond.notify_all()
                continue
            except asyncio.TimeoutError:
                pass
            async with self._cond:
                budget = self._rate
                if self._released_subintervals < self._subintervals:
                    if self._leftover_remaining > 0:
                        step = self._leftover_remaining // self._rate + 1
                        budget += step
                        self._leftover_remaining -= step
                    self._released_subintervals += 1
                else:
                    self._released_subintervals = 0
                    self._leftover_remaining = self._leftover
                # Budget does not accumulate across subintervals.
                self._budget = budget
                self._cond.notify_all()

    async def wait(self, timeout: Optional[float] = None) -> None:
        """Block until this operation may run. Raises RateLimiterClosed
        after close(), asyncio.TimeoutError on timeout."""

        async def acquire() -> None:
            async with self._cond:
                while True:
                    if self._closed:
                        raise RateLimiterClosed()
                    if self.unlimited:
                        return
                    if not self.blocked and self._budget > 0:
                        self._budget -= 1
                        return
                    await self._cond.wait()

        if timeout is None:
            await acquire()
        else:
            await asyncio.wait_for(acquire(), timeout)

    async def close(self) -> None:
        self._closed = True
        self._task.cancel()
        try:
            await self._task
        except asyncio.CancelledError:
            pass
        async with self._cond:
            self._cond.notify_all()


def new_qps(resource: ClientResource) -> QPSRateLimiter:
    return QPSRateLimiter(resource)
