"""Lease value type.

Capability parity with the reference's lease record
(/root/reference/go/server/doorman/store.go:20-36): expiry, refresh interval,
granted capacity (has), requested capacity (wants), subclient count.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class Lease:
    """A capacity lease granted to one client for one resource.

    Times are absolute seconds since the epoch (matching the wire format);
    durations are in seconds.
    """

    expiry: float = 0.0
    refresh_interval: float = 0.0
    has: float = 0.0
    wants: float = 0.0
    subclients: int = 0
    # Wire priority of the client for this resource (doorman.proto
    # ResourceRequest.priority); interpreted only by priority-aware
    # algorithms, recorded for all.
    priority: int = 0

    @property
    def is_zero(self) -> bool:
        return self.expiry == 0.0


ZERO_LEASE = Lease()
