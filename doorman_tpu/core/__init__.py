"""Host-side core: leases, lease stores, resources, snapshots."""

from doorman_tpu.core.lease import Lease, ZERO_LEASE  # noqa: F401
from doorman_tpu.core.store import LeaseStore  # noqa: F401
