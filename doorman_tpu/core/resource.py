"""Resource: one rate-limited entity — config template + lease store +
algorithm binding + learning-mode clock.

Capability parity with /root/reference/go/server/doorman/resource.go:37-210.
Python server handlers run on a single asyncio loop, so the reference's
RWMutex discipline collapses away; the injected clock serves the simulation
harness and tests.
"""

from __future__ import annotations

import fnmatch
import time
from typing import Callable, Optional

from doorman_tpu.algorithms import scalar
from doorman_tpu.core.lease import Lease
from doorman_tpu.core.store import LeaseStore
from doorman_tpu.proto import doorman_pb2 as pb
from doorman_tpu.algorithms.kinds import AlgoKind


# variant -> internal lane, per wire kind (the config-epoch seam: a
# variant flip re-maps the kind vector, which the solver's config
# mirror detects exactly like a wire-kind change).
_VARIANT_LANES = {
    (int(pb.Algorithm.PROPORTIONAL_SHARE), "topup"):
        int(AlgoKind.PROPORTIONAL_TOPUP),
    (int(pb.Algorithm.PROPORTIONAL_SHARE), "logutil"):
        int(AlgoKind.PROPORTIONAL_FAIRNESS),
    (int(pb.Algorithm.FAIR_SHARE), "maxmin"):
        int(AlgoKind.MAX_MIN_FAIR),
    (int(pb.Algorithm.FAIR_SHARE), "balanced"):
        int(AlgoKind.BALANCED_FAIRNESS),
}


def algo_kind_for(template: pb.ResourceTemplate) -> int:
    """Map a config template to the solver lane. The `variant`
    parameter refines PROPORTIONAL_SHARE (topup = Go-style top-up,
    logutil = Kelly proportional fairness) and FAIR_SHARE (maxmin =
    unweighted max-min, balanced = balanced fairness) into their
    portfolio lanes; the wire PRIORITY_BANDS kind maps to its internal
    lane id (the wire value collides with the internal top-up lane
    number)."""
    kind = int(template.algorithm.kind)
    variant = scalar.get_parameter(template.algorithm, "variant")
    if variant is not None:
        lane = _VARIANT_LANES.get((kind, variant))
        if lane is not None:
            return lane
    if kind == int(pb.Algorithm.PRIORITY_BANDS):
        return int(AlgoKind.PRIORITY_BANDS)
    return kind


def static_param(template: pb.ResourceTemplate) -> float:
    """STATIC's per-client capacity is the template capacity (the reference
    reuses the capacity field with per-client meaning, algorithm.go:75-85)."""
    return float(template.capacity)


class Resource:
    """A resource as the master sees it."""

    def __init__(
        self,
        resource_id: str,
        template: pb.ResourceTemplate,
        *,
        learning_mode_end: float = 0.0,
        clock: Callable[[], float] = time.time,
        store_factory: Optional[Callable[[str], LeaseStore]] = None,
    ):
        self.id = resource_id
        self._clock = clock
        # store_factory lets the server back all resources with the native
        # C++ engine (doorman_tpu.native); default is the Python store.
        self.store = (
            store_factory(resource_id)
            if store_factory is not None
            else LeaseStore(resource_id, clock=clock)
        )
        # Bound once: the store never changes for a Resource's lifetime,
        # and the request paths should not pay a getattr per request.
        self._decide_fast = getattr(self.store, "decide_fast", None)
        self._refresh_grant = getattr(self.store, "refresh_grant", None)
        self.learning_mode_end = learning_mode_end
        # Expiry of the capacity lease this (intermediate) server holds from
        # its parent; None on the root. Expired parent lease => capacity 0.
        self.parent_expiry: Optional[float] = None
        self.template: pb.ResourceTemplate = None  # set by load_config
        self._algorithm: scalar.Algorithm = None
        self._learner: scalar.Algorithm = None
        self.load_config(template, None)

    def load_config(
        self, template: pb.ResourceTemplate, parent_expiry: Optional[float]
    ) -> None:
        self.template = template
        self.parent_expiry = parent_expiry
        self._algorithm = scalar.get_algorithm(template.algorithm)
        self._learner = scalar.learn(template.algorithm)
        # Per-request decide parameters, read once per config load:
        # protobuf field access (and the variant-parameter scan in
        # algo_kind_for) costs microseconds — too slow to repeat on
        # every request of the native fast path.
        self._decide_kind = algo_kind_for(template)
        self._lease_length = float(template.algorithm.lease_length)
        self._refresh_interval = float(template.algorithm.refresh_interval)

    @property
    def capacity(self) -> float:
        """Current capacity; zero when the parent lease has expired
        (resource.go:62-72)."""
        if self.parent_expiry is not None and self.parent_expiry < self._clock():
            return 0.0
        return self.template.capacity

    @property
    def in_learning_mode(self) -> bool:
        return self.learning_mode_end > self._clock()

    def decide(self, request: scalar.Request) -> Lease:
        """Per-request (immediate-mode) decision: sweep expired leases then
        run the configured algorithm — or the learner during learning mode
        (resource.go:100-113). Native stores run the whole decide as one
        locked C call (sweep + algorithm + upsert, bit-identical grants —
        native/store.cc::dm_decide); PRIORITY_BANDS and Python stores
        take the scalar path."""
        fast = self._decide_fast
        if fast is not None:
            kind = (
                self.store.DECIDE_LEARN
                if self.in_learning_mode
                else self._decide_kind
            )
            result = fast(
                kind, self.capacity, self._lease_length,
                self._refresh_interval, request.has, request.wants,
                request.subclients, request.priority, request.client,
            )
            if result is not None:
                lease, confused, old_has = result
                if confused:
                    scalar.log.error(
                        "client %s is confused: says it has %s, was "
                        "assigned %s", request.client, request.has, old_has,
                    )
                return lease
        self.store.clean()
        if self.in_learning_mode:
            return self._learner(self.store, self.capacity, request)
        return self._algorithm(self.store, self.capacity, request)

    def release(self, client: str) -> None:
        self.store.release(client)

    def matches(self, template: pb.ResourceTemplate) -> bool:
        glob = template.identifier_glob
        return glob == self.id or fnmatch.fnmatchcase(self.id, glob)

    def safe_capacity(self) -> float:
        """Configured safe capacity, or the dynamic fallback
        capacity / known clients (resource.go:81-96)."""
        if self.template.HasField("safe_capacity"):
            return self.template.safe_capacity
        count = max(self.store.count, 1)
        return self.template.capacity / count

    def status(self) -> dict:
        return {
            "id": self.id,
            "sum_has": self.store.sum_has,
            "sum_wants": self.store.sum_wants,
            "count": self.store.count,
            "capacity": self.capacity,
            "in_learning_mode": self.in_learning_mode,
            "algorithm": pb.Algorithm.Kind.Name(self.template.algorithm.kind),
        }
