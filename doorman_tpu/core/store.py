"""Per-resource lease store with O(1) running aggregates.

Capability parity with the reference store
(/root/reference/go/server/doorman/store.go:68-213): client -> lease map with
running sum_has / sum_wants / subclient count, expiry sweep, and a read-only
status view. Differences by design:

  - the clock is injected (defaults to time.time) so the simulation harness
    and tests can run on virtual time;
  - iteration order over clients is insertion order (Python dict), which is
    deterministic — the Go map iteration is randomized. The batch solver
    relies on this determinism for reproducible packing.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterator, List, Tuple

from doorman_tpu.core.lease import Lease, ZERO_LEASE


@dataclass
class ClientLeaseStatus:
    client_id: str
    lease: Lease


@dataclass
class ResourceLeaseStatus:
    id: str
    sum_has: float
    sum_wants: float
    leases: List[ClientLeaseStatus] = field(default_factory=list)


class LeaseStore:
    """The set of outstanding leases for one resource."""

    def __init__(self, id: str, clock: Callable[[], float] = time.time):
        self.id = id
        self._clock = clock
        self._leases: Dict[str, Lease] = {}
        self._sum_wants = 0.0
        self._sum_has = 0.0
        self._count = 0  # total subclients

    def __len__(self) -> int:
        return len(self._leases)

    @property
    def count(self) -> int:
        """Total number of subclients across all leases."""
        return self._count

    @property
    def sum_has(self) -> float:
        return self._sum_has

    @property
    def sum_wants(self) -> float:
        return self._sum_wants

    def get(self, client: str) -> Lease:
        return self._leases.get(client, ZERO_LEASE)

    def has_client(self, client: str) -> bool:
        return client in self._leases

    def subclients(self, client: str) -> int:
        return self._leases.get(client, ZERO_LEASE).subclients

    def assign(
        self,
        client: str,
        lease_length: float,
        refresh_interval: float,
        has: float,
        wants: float,
        subclients: int,
        priority: int = 0,
    ) -> Lease:
        """Record capacity `has` given to `client`; updates running sums by
        delta and stamps a fresh expiry of now + lease_length."""
        old = self._leases.get(client, ZERO_LEASE)
        self._sum_has += has - old.has
        self._sum_wants += wants - old.wants
        self._count += subclients - old.subclients
        lease = Lease(
            expiry=self._clock() + lease_length,
            refresh_interval=refresh_interval,
            has=has,
            wants=wants,
            subclients=subclients,
            priority=priority,
        )
        self._leases[client] = lease
        return lease

    def bulk_assign(
        self,
        clients,
        lease_length: float,
        refresh_interval: float,
        has,
        wants,
        subclients=None,
        priority=None,
    ) -> None:
        """assign() per row, in input order (the vector population's
        grouped-commit path). Same running-sum accumulation order and
        same clock stamp per row as the equivalent assign loop — the
        native store implements this contract as one C call."""
        n = len(clients)
        subs = subclients if subclients is not None else [1] * n
        prio = priority if priority is not None else [0] * n
        for i in range(n):
            self.assign(
                clients[i], lease_length, refresh_interval,
                float(has[i]), float(wants[i]), int(subs[i]),
                int(prio[i]),
            )

    def regrant(self, client: str, has: float) -> None:
        """Update only the granted capacity of an existing lease — the
        batched tick's write-back. Expiry and refresh are NOT touched:
        they advance only when the client itself refreshes (reference
        semantics, store.go:153-181 + Decide stamping the requester
        only), so a client that stops refreshing expires after one
        lease length even while the server stays busy."""
        old = self._leases.get(client)
        if old is None:
            return  # released mid-solve
        self._sum_has += has - old.has
        self._leases[client] = Lease(
            expiry=old.expiry,
            refresh_interval=old.refresh_interval,
            has=has,
            wants=old.wants,
            subclients=old.subclients,
            priority=old.priority,
        )

    def release(self, client: str) -> None:
        lease = self._leases.pop(client, None)
        if lease is None:
            return
        self._sum_wants -= lease.wants
        self._sum_has -= lease.has
        self._count -= lease.subclients

    def clean(self) -> int:
        """Remove expired leases; returns how many were removed."""
        now = self._clock()
        expired = [c for c, l in self._leases.items() if now > l.expiry]
        for client in expired:
            self.release(client)
        return len(expired)

    def restore(self, client: str, lease: Lease) -> None:
        """Insert a lease verbatim — absolute expiry preserved — for the
        persistence restore path (doorman_tpu/persist). assign() would
        re-stamp expiry from the clock, silently extending every
        restored lease by a full lease length."""
        old = self._leases.get(client, ZERO_LEASE)
        self._sum_has += lease.has - old.has
        self._sum_wants += lease.wants - old.wants
        self._count += lease.subclients - old.subclients
        self._leases[client] = lease

    def dump_rows(self) -> List[Tuple[str, float, float, float, float, int, int]]:
        """Drain API for snapshotting: every lease as one
        (client, expiry, refresh_interval, has, wants, subclients,
        priority) row. The native store implements the same contract as
        a single bulk C call (dm_dump), so snapshot serialization never
        walks a million-lease store lease-by-lease through Python
        attribute access."""
        return [
            (c, l.expiry, l.refresh_interval, l.has, l.wants,
             l.subclients, l.priority)
            for c, l in self._leases.items()
        ]

    def items(self) -> Iterator[Tuple[str, Lease]]:
        return iter(self._leases.items())

    def map(self, fn: Callable[[str, Lease], None]) -> None:
        for client, lease in self._leases.items():
            fn(client, lease)

    def band_aggregates(self) -> List[Tuple[int, float, int]]:
        """(priority, wants-sum, subclient-count) per distinct priority,
        ascending (same contract as the native store's C fast path)."""
        bands: Dict[int, List[float]] = {}
        for lease in self._leases.values():
            acc = bands.setdefault(lease.priority, [0.0, 0])
            acc[0] += lease.wants
            acc[1] += lease.subclients
        return [
            (p, bands[p][0], int(bands[p][1])) for p in sorted(bands)
        ]

    def lease_status(self) -> ResourceLeaseStatus:
        return ResourceLeaseStatus(
            id=self.id,
            sum_has=self._sum_has,
            sum_wants=self._sum_wants,
            leases=[
                ClientLeaseStatus(client_id=c, lease=l)
                for c, l in self._leases.items()
            ],
        )
