"""Snapshot packing: host lease stores -> padded device batches.

The master mutates lease stores between ticks (requests arriving over gRPC);
at each tick the whole (client x resource) table is snapshotted into an
edge list, solved on device in one shot, and the resulting grants written
back. Padding rounds the edge and resource counts up to size buckets
(powers of two) so XLA compiles one executable per bucket, not per tick.

This replaces the reference's per-resource goroutine fan-out
(/root/reference/go/server/doorman/server.go:800-817) with a data-parallel
batch; the snapshot boundary also gives the clean answer to the
mid-tick-report hazard called out in SURVEY.md §7: requests that arrive
while a solve is in flight mutate the NEXT snapshot.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, List, Sequence, Tuple

import numpy as np

from doorman_tpu.solver.kernels import EdgeBatch, ResourceBatch


def _bucket(n: int, minimum: int = 64) -> int:
    """Round up to the next power of two (>= minimum) to bound recompiles."""
    size = minimum
    while size < n:
        size *= 2
    return size


@dataclass
class ResourceSpec:
    """Host-side description of one resource entering a tick."""

    resource_id: str
    capacity: float
    algo_kind: int  # AlgoKind
    learning: bool = False
    static_capacity: float = 0.0


@dataclass
class Snapshot:
    """A packed tick: device-ready batches plus the index maps needed to
    scatter results back to (resource, client) pairs.

    Two flavors share this type: the Python-store pack carries explicit
    `edge_keys`; the native-engine pack (doorman_tpu.native) instead
    carries the raw `ridx`/`cids` handle arrays plus the engine, and
    resolves names only when asked."""

    edges: EdgeBatch
    resources: ResourceBatch
    # Parallel to the packed edge order (Python pack):
    edge_keys: List[Tuple[str, str]]  # (resource_id, client_id)
    resource_ids: List[str]
    num_edges: int
    # Per-segment learning flags as captured at pack time (parallel to
    # resource_ids); apply() keeps the store's live `has` for these
    # instead of the snapshot-stale solved value.
    learning: "List[bool] | None" = None
    # Native pack only:
    engine: object = None
    ridx: "np.ndarray | None" = None  # [num_edges] segment per edge
    cids: "np.ndarray | None" = None  # [num_edges] client handles
    # Dense-layout pack (BatchSolver engine path): the [R, K] DenseBatch
    # plus the host-side lane index `pos` (parallel to ridx) and the
    # filled extent (n_rows, kfill) — the download slices to the filled
    # region and the flat-edge gather runs host-side (a 1M-element
    # device gather serializes on TPU; a numpy fancy index does not).
    # When set, `edges`/`resources` are None — the dense solve replaces
    # the edge-list executable.
    dense: object = None
    pos: "np.ndarray | None" = None
    dense_fill: "Tuple[int, int] | None" = None
    # PRIORITY_BANDS resources ride in their own dense part (built and
    # consumed by BatchSolver; None when the tick has none).
    priority_part: object = None

    def keys(self) -> List[Tuple[str, str]]:
        """(resource_id, client_id) per packed edge, either flavor."""
        if self.edge_keys:
            return self.edge_keys
        if self.engine is None:
            return []
        name = self.engine.client_name
        return [
            (self.resource_ids[int(r)], name(int(c)))
            for r, c in zip(self.ridx, self.cids)
        ]

    def unpack(self, gets: np.ndarray) -> Dict[Tuple[str, str], float]:
        """Map a solved gets[E] array back to {(resource_id, client_id):
        grant}."""
        arr = np.asarray(gets)
        return {
            key: float(arr[i]) for i, key in enumerate(self.keys())
        }


def pack_snapshot(
    specs: Sequence[ResourceSpec],
    rows: Callable[[str], Sequence[Tuple[str, float, float, int]]],
    *,
    dtype=np.float64,
    edge_bucket_min: int = 64,
    resource_bucket_min: int = 16,
    to_device: Callable[[np.ndarray], object] | None = None,
) -> Snapshot:
    """Pack resources into a Snapshot.

    `rows(resource_id)` yields (client_id, wants, has, subclients) tuples —
    typically LeaseStore.items() adapted by the server. Edges are laid out
    resource-major, so segment ids arrive sorted (the kernels rely on it).
    """
    edge_keys: List[Tuple[str, str]] = []
    wants_l: List[float] = []
    has_l: List[float] = []
    sub_l: List[float] = []
    rid_l: List[int] = []

    for r, spec in enumerate(specs):
        for client_id, wants, has, subclients in rows(spec.resource_id):
            edge_keys.append((spec.resource_id, client_id))
            rid_l.append(r)
            wants_l.append(wants)
            has_l.append(has)
            sub_l.append(subclients)

    return pack_edge_arrays(
        specs,
        np.asarray(rid_l, np.int32),
        np.asarray(wants_l, dtype),
        np.asarray(has_l, dtype),
        np.asarray(sub_l, dtype),
        dtype=dtype,
        edge_bucket_min=edge_bucket_min,
        resource_bucket_min=resource_bucket_min,
        to_device=to_device,
        edge_keys=edge_keys,
    )


def pack_edge_arrays(
    specs: Sequence[ResourceSpec],
    rid: np.ndarray,
    wants: np.ndarray,
    has: np.ndarray,
    sub: np.ndarray,
    *,
    dtype=np.float64,
    edge_bucket_min: int = 64,
    resource_bucket_min: int = 16,
    to_device: Callable[[np.ndarray], object] | None = None,
    edge_keys: List[Tuple[str, str]] | None = None,
    engine: object = None,
    cids: np.ndarray | None = None,
) -> Snapshot:
    """Pad already-flat edge arrays into a Snapshot. The list-based
    `pack_snapshot` and the native engine's bulk pack both land here."""
    n = len(rid)
    E = _bucket(max(n, 1), edge_bucket_min)
    R = _bucket(max(len(specs), 1), resource_bucket_min)

    def fpad(xs: np.ndarray) -> np.ndarray:
        arr = np.zeros(E, dtype=dtype)
        arr[:n] = xs
        return arr

    rid_pad = np.full(E, R - 1, dtype=np.int32)
    rid_pad[:n] = rid
    active = np.zeros(E, dtype=bool)
    active[:n] = True

    cap = np.zeros(R, dtype=dtype)
    kind = np.zeros(R, dtype=np.int32)
    learning = np.zeros(R, dtype=bool)
    static_cap = np.zeros(R, dtype=dtype)
    for r, spec in enumerate(specs):
        cap[r] = spec.capacity
        kind[r] = int(spec.algo_kind)
        learning[r] = spec.learning
        static_cap[r] = spec.static_capacity

    dev = to_device if to_device is not None else (lambda a: a)
    edges = EdgeBatch(
        resource=dev(rid_pad),
        wants=dev(fpad(wants)),
        has=dev(fpad(has)),
        subclients=dev(fpad(sub)),
        active=dev(active),
    )
    resources = ResourceBatch(
        capacity=dev(cap),
        algo_kind=dev(kind),
        learning=dev(learning),
        static_capacity=dev(static_cap),
    )
    return Snapshot(
        edges=edges,
        resources=resources,
        edge_keys=edge_keys or [],
        resource_ids=[s.resource_id for s in specs],
        num_edges=n,
        learning=[bool(s.learning) for s in specs],
        engine=engine,
        ridx=rid if engine is not None else None,
        cids=cids,
    )
