"""The federated intermediate: subtree aggregation as a device tick.

An intermediate server's upstream beat is an aggregation: sum every
(child, resource) want into per-band per-resource totals, send ONE
GetServerCapacity per resource to the resource's owning root shard, and
redistribute the granted lease downstream (the local solve does the
redistribution — parent grants re-template local capacity exactly as in
the single-root tree). The reference does the summation as a Python/Go
loop over leases; at subtree scale that loop IS the beat's cost, so here
it runs as a device-backed tick on the engine seam:

`AggregationTickAdapter` keeps the (child x resource) wants/weights
tables device-resident and follows the tick-engine dispatch/collect
surface (solver/engine.py: the same phase vocabulary, the same
PhaseRecorder streams, drivable by PipelinedTicker) — dispatch scatters
the dirty rows and launches the jitted band-masked summation
("aggregate" in PHASES), collect lands the [band, resource] totals.
`FederatedIntermediate` is a CapacityServer whose updater fans the
resulting per-resource aggregates out to the per-shard masters resolved
through ShardDiscovery.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from doorman_tpu.client.connection import Connection
from doorman_tpu.obs import trace as trace_mod
from doorman_tpu.obs.phases import PhaseRecorder
from doorman_tpu.proto import doorman_pb2 as pb
from doorman_tpu.server import config as config_mod
from doorman_tpu.server.server import (
    DEFAULT_PRIORITY,
    CapacityServer,
    default_resource_template,
)
from doorman_tpu.solver.engine import PHASES, ceil_to, place
from doorman_tpu.utils.backoff import MAX_BACKOFF, MIN_BACKOFF, VERY_LONG_TIME, backoff

log = logging.getLogger(__name__)

# Child-slot padding granularity (multiple-of, not power-of-two: the
# host<->device link prices bytes — solver/engine.ceil_to's argument).
SLOT_PAD = 64
ROW_PAD = 16


@dataclass
class AggHandle:
    """One in-flight aggregation tick."""

    out: object  # device [B, R] wants-sums and [B, R] weight-sums
    bands: Tuple[int, ...]
    row_ids: Tuple[str, ...]
    n_real: int
    dispatched_at: float = 0.0
    collected: bool = False


class AggregationTickAdapter:
    """(child x resource) wants table + band-masked device summation
    behind the tick-engine dispatch/collect surface."""

    component = "federation"

    def __init__(
        self,
        *,
        dtype=np.float64,
        device=None,
        clock: Callable[[], float] = time.time,
    ):
        self._dtype = np.dtype(dtype)
        self._device = device
        self._clock = clock
        self.ticks = 0
        self.idle_ticks = 0
        self.last_tick_seconds = 0.0
        self.phase_s: Dict[str, float] = {name: 0.0 for name in PHASES}
        # Host mirrors; device tables rebuilt when the (rows, slots,
        # bands) layout moves, scattered into when only values do.
        self._rows: Dict[str, int] = {}  # resource id -> row index
        self._row_ids: List[str] = []
        self._wants_h: Optional[np.ndarray] = None  # [R_pad, K_pad]
        self._weights_h: Optional[np.ndarray] = None
        self._bands_h: Optional[np.ndarray] = None  # int32 band per slot
        self._wants_d = None
        self._weights_d = None
        self._bands_d = None
        self._band_vals: Tuple[int, ...] = ()
        self._dirty: set = set()
        self._layout_dirty = True
        self._agg_fns: Dict[tuple, Callable] = {}

    # -- staging -------------------------------------------------------

    def update(
        self,
        resource_id: str,
        wants: Sequence[float],
        weights: Sequence[float],
        bands: Sequence[int],
    ) -> None:
        """Stage one resource's current child rows (from the store's
        bulk drain); the row uploads on the next dispatch. Rows wider
        than the current slot pad trigger a layout rebuild."""
        wants = np.asarray(wants, self._dtype)
        weights = np.asarray(weights, self._dtype)
        bands = np.asarray(bands, np.int32)
        row = self._rows.get(resource_id)
        if row is None:
            row = len(self._row_ids)
            self._rows[resource_id] = row
            self._row_ids.append(resource_id)
            self._layout_dirty = True
        k_pad = 0 if self._wants_h is None else self._wants_h.shape[1]
        if len(wants) > k_pad:
            self._layout_dirty = True
        new_bands = set(int(b) for b in np.unique(bands)) - set(
            self._band_vals
        )
        if new_bands:
            self._band_vals = tuple(
                sorted(set(self._band_vals) | new_bands)
            )
            self._layout_dirty = True
        if self._layout_dirty:
            self._staged = getattr(self, "_staged", {})
            self._staged[resource_id] = (wants, weights, bands)
            return
        self._write_row(row, wants, weights, bands)
        self._dirty.add(row)

    def _write_row(self, row, wants, weights, bands) -> None:
        k = len(wants)
        self._wants_h[row, :] = 0.0
        self._weights_h[row, :] = 0.0
        self._bands_h[row, :] = -1
        self._wants_h[row, :k] = wants
        self._weights_h[row, :k] = weights
        self._bands_h[row, :k] = bands

    def _rebuild(self, ph: PhaseRecorder) -> None:
        staged = getattr(self, "_staged", {})
        widths = [len(w) for (w, _s, _b) in staged.values()]
        if self._wants_h is not None:
            widths.append(self._wants_h.shape[1])
        k_pad = ceil_to(max(widths, default=1), SLOT_PAD)
        r_pad = ceil_to(max(len(self._row_ids), 1), ROW_PAD)
        old_wants, old_weights, old_bands = (
            self._wants_h, self._weights_h, self._bands_h,
        )
        self._wants_h = np.zeros((r_pad, k_pad), self._dtype)
        self._weights_h = np.zeros((r_pad, k_pad), self._dtype)
        self._bands_h = np.full((r_pad, k_pad), -1, np.int32)
        if old_wants is not None:
            r, k = old_wants.shape
            self._wants_h[:r, :k] = old_wants
            self._weights_h[:r, :k] = old_weights
            self._bands_h[:r, :k] = old_bands
        for rid, (wants, weights, bands) in staged.items():
            self._write_row(self._rows[rid], wants, weights, bands)
        self._staged = {}
        # Whole-table upload: rebuilds are rare (layout growth only).
        self._wants_d = place(self._wants_h, device=self._device)
        self._weights_d = place(self._weights_h, device=self._device)
        self._bands_d = place(self._bands_h, device=self._device)
        self._dirty.clear()
        self._layout_dirty = False
        ph.lap("rebuild")

    def _agg_fn(self, key) -> Callable:
        fn = self._agg_fns.get(key)
        if fn is not None:
            return fn
        import jax
        import jax.numpy as jnp
        from functools import partial

        band_vals = np.asarray(self._band_vals, np.int32)

        @partial(jax.jit, donate_argnums=(0, 1, 2))
        def aggregate(wants, weights, bands, idx, w_rows, s_rows, b_rows):
            wants = wants.at[idx].set(w_rows)
            weights = weights.at[idx].set(s_rows)
            bands = bands.at[idx].set(b_rows)
            # [B, R, K] band mask -> [B, R] per-band sums; B is tiny
            # (wire priorities in use), R*K is the table — one masked
            # reduction per band on the VPU, no host loop anywhere.
            mask = bands[None, :, :] == jnp.asarray(band_vals)[:, None, None]
            wants_sum = jnp.sum(
                jnp.where(mask, wants[None], 0.0), axis=2
            )
            weight_sum = jnp.sum(
                jnp.where(mask, weights[None], 0.0), axis=2
            )
            return wants, weights, bands, wants_sum, weight_sum

        self._agg_fns[key] = aggregate
        return aggregate

    # -- the tick surface ----------------------------------------------

    def dispatch(self, *_args, **_kwargs) -> AggHandle:
        now = self._clock()
        ph = PhaseRecorder(self.component, self.phase_s)
        if self._layout_dirty:
            self._rebuild(ph)
        dirty = sorted(self._dirty)
        self._dirty.clear()
        ph.lap("drain")
        if self._wants_h is None:
            self.idle_ticks += 1
            return AggHandle(
                out=None, bands=(), row_ids=(), n_real=0,
                dispatched_at=now,
            )
        if not dirty:
            # No movement: scatter a shaped no-op (row 0 rewrites its
            # own values; the donated tables still round-trip).
            dirty = [0]
        idx = np.asarray(dirty, np.int64)
        # Pad the dirty batch to a multiple so the jit cache stays
        # bounded (shapes per table <= rows / ROW_PAD); the pad repeats
        # the last row, and a duplicate-index scatter of identical
        # values is idempotent.
        pad_n = ceil_to(len(idx), ROW_PAD)
        if pad_n != len(idx):
            idx = np.concatenate(
                [idx, np.full(pad_n - len(idx), idx[-1], np.int64)]
            )
        w_rows = self._wants_h[idx]
        s_rows = self._weights_h[idx]
        b_rows = self._bands_h[idx]
        ph.lap("pack")
        idx_d = place(idx, device=self._device)
        w_d = place(w_rows, device=self._device)
        s_d = place(s_rows, device=self._device)
        b_d = place(b_rows, device=self._device)
        ph.lap("upload")
        key = (
            self._wants_h.shape, len(idx), self._band_vals,
            str(self._dtype),
        )
        fn = self._agg_fn(key)
        (self._wants_d, self._weights_d, self._bands_d,
         wants_sum, weight_sum) = fn(
            self._wants_d, self._weights_d, self._bands_d,
            idx_d, w_d, s_d, b_d,
        )
        ph.lap("aggregate")
        return AggHandle(
            out=(wants_sum, weight_sum),
            bands=self._band_vals,
            row_ids=tuple(self._row_ids),
            n_real=len(self._row_ids),
            dispatched_at=now,
        )

    def collect(
        self, handle: AggHandle
    ) -> Dict[str, List[Tuple[int, float, int]]]:
        """Land one tick's [band, resource] totals as
        {resource_id: [(priority, wants, num_clients), ...]} — the
        store.band_aggregates contract, computed on device."""
        if handle.collected:
            return {}
        handle.collected = True
        if handle.out is None:
            self.ticks += 1
            self.last_tick_seconds = self._clock() - handle.dispatched_at
            return {}
        ph = PhaseRecorder(self.component, self.phase_s)
        wants_sum = np.asarray(handle.out[0], np.float64)
        weight_sum = np.asarray(handle.out[1], np.float64)
        ph.lap("download")
        out: Dict[str, List[Tuple[int, float, int]]] = {}
        nonzero = np.nonzero(wants_sum[:, : handle.n_real])
        for b, r in zip(*nonzero):
            out.setdefault(handle.row_ids[r], []).append(
                (
                    int(handle.bands[b]),
                    float(wants_sum[b, r]),
                    int(round(weight_sum[b, r])),
                )
            )
        for bands in out.values():
            bands.sort()
        ph.lap("apply")
        self.ticks += 1
        self.last_tick_seconds = self._clock() - handle.dispatched_at
        return out

    def step(self, *_args, **_kwargs):
        return self.collect(self.dispatch())


class FederatedIntermediate(CapacityServer):
    """An intermediate whose parent is a FEDERATION: upstream demand is
    aggregated on device and fanned out per owning root shard, with
    per-shard masters resolved through the discovery cache. Local
    serving (clients, downstream servers, admission, streams) is the
    ordinary CapacityServer."""

    def __init__(
        self,
        server_id: str,
        election,
        *,
        router,
        discovery,
        agg_dtype=np.float64,
        agg_device=None,
        **kwargs,
    ):
        # Any truthy parent_addr arms the intermediate role (default
        # template + updater loop); the federated updater never dials
        # it — every upstream hop goes through the router + discovery.
        super().__init__(
            server_id, election, parent_addr="federated:", **kwargs
        )
        self.router = router
        self.discovery = discovery
        self._agg = AggregationTickAdapter(
            dtype=agg_dtype, device=agg_device, clock=self._clock
        )
        self._shard_conns: Dict[int, Connection] = {}

    @property
    def aggregator(self) -> AggregationTickAdapter:
        return self._agg

    async def _shard_connection(self, shard: int) -> Connection:
        conn = self._shard_conns.get(shard)
        if conn is None:
            addr = await self.discovery.master(shard)
            conn = Connection(
                addr,
                minimum_refresh_interval=self.minimum_refresh_interval,
                max_retries=0,
                tls=self.parent_tls,
                tls_ca=self.parent_tls_ca,
            )
            conn.on_redirect = (
                lambda addr, s=shard: self.discovery.note_master(s, addr)
            )
            self._shard_conns[shard] = conn
        return conn

    async def stop(self) -> None:
        for conn in self._shard_conns.values():
            try:
                await conn.close()
            except Exception:
                pass
        self._shard_conns.clear()
        await super().stop()

    def _aggregate_demand(self) -> Dict[str, list]:
        """One device aggregation tick over every local resource with
        demand: stage each store's bulk-drained rows and land the
        per-band totals. The summation is the device's; Python only
        assembles the staged rows (one bulk dump_rows per store — a C
        call on the native engine)."""
        with trace_mod.default_tracer().span(
            "federation.aggregate", cat="federation",
            args={"server": self.id, "resources": len(self.resources)},
        ):
            for rid, res in self.resources.items():
                if res.store.sum_wants <= 0:
                    continue
                res.store.clean()
                rows = res.store.dump_rows()
                self._agg.update(
                    rid,
                    [r[4] for r in rows],  # wants
                    [max(float(r[5]), 1.0) for r in rows],  # subclients
                    [r[6] for r in rows],  # priority
                )
            return self._agg.step()

    def _build_shard_requests(
        self,
    ) -> Dict[int, pb.GetServerCapacityRequest]:
        """Per-shard upstream requests from the device-landed
        aggregates (the federated analog of
        _build_server_capacity_request)."""
        aggregates = self._aggregate_demand()
        requests: Dict[int, pb.GetServerCapacityRequest] = {}

        def request_for(shard: int) -> pb.GetServerCapacityRequest:
            req = requests.get(shard)
            if req is None:
                req = pb.GetServerCapacityRequest(server_id=self.id)
                requests[shard] = req
            return req

        for resource_id, bands in sorted(aggregates.items()):
            res = self.resources.get(resource_id)
            if res is None:
                continue
            req = request_for(self.router.shard_of(resource_id))
            rr = req.resource.add()
            rr.resource_id = resource_id
            if res.parent_expiry is not None and res.capacity > 0:
                rr.has.capacity = res.capacity
                rr.has.expiry_time = int(res.parent_expiry)
            for priority, wants, num_clients in bands:
                if wants <= 0:
                    continue
                band = rr.wants.add()
                band.priority = priority
                band.num_clients = max(int(num_clients), 1)
                band.wants = wants
        if not requests:
            # Probe request to the home tier so at least one link stays
            # warm (the single-parent probe, shard-routed).
            req = request_for(0)
            rr = req.resource.add()
            rr.resource_id = "*"
            band = rr.wants.add()
            band.priority = DEFAULT_PRIORITY
            band.num_clients = 1
            band.wants = 0.0
        return requests

    async def _perform_parent_requests(self, retry_number: int):
        """One federated upstream exchange: fan the per-shard requests
        out, merge every shard's grants into one template load. A shard
        that fails keeps its resources on their previous (expiring)
        parent lease — the blast radius of one root shard is its own
        resources, never the subtree."""
        requests = self._build_shard_requests()
        responses = []
        failures = 0
        for shard in sorted(requests):
            request = requests[shard]
            try:
                conn = await self._shard_connection(shard)
                with trace_mod.default_tracer().span(
                    "server.parent_refresh", cat="server",
                    args={"server": self.id, "shard": shard},
                ):
                    out = await conn.execute(
                        lambda stub, req=request: stub.GetServerCapacity(
                            req, metadata=trace_mod.grpc_metadata()
                        )
                    )
                responses.append(out)
                self.fed_stats["upstream_rpcs"] += 1
            except Exception:
                failures += 1
                log.exception(
                    "%s: GetServerCapacity to shard %d failed",
                    self.id, shard,
                )
                # Next exchange re-resolves this shard's master.
                self.discovery.invalidate(shard)
                self._shard_conns.pop(shard, None)
        if failures and not responses:
            return (
                backoff(MIN_BACKOFF, MAX_BACKOFF, retry_number),
                retry_number + 1,
            )

        interval = VERY_LONG_TIME
        templates: List[pb.ResourceTemplate] = []
        expiry_times: Dict[str, float] = {}
        for out in responses:
            for presponse in out.response:
                if presponse.resource_id not in self.resources:
                    if presponse.resource_id != "*":
                        log.error(
                            "%s: response for unknown resource %r",
                            self.id, presponse.resource_id,
                        )
                    continue
                expiry_times[presponse.resource_id] = float(
                    presponse.gets.expiry_time
                )
                tpl = pb.ResourceTemplate(
                    identifier_glob=presponse.resource_id,
                    capacity=presponse.gets.capacity,
                    safe_capacity=presponse.safe_capacity,
                )
                tpl.algorithm.CopyFrom(presponse.algorithm)
                templates.append(tpl)
                interval = min(
                    interval, float(presponse.gets.refresh_interval)
                )
        templates.append(default_resource_template())
        try:
            await self.load_config(
                pb.ResourceRepository(resources=templates), expiry_times
            )
        except config_mod.ConfigError:
            log.exception(
                "%s: loading shard-derived config failed", self.id
            )
            return (
                backoff(MIN_BACKOFF, MAX_BACKOFF, retry_number),
                retry_number + 1,
            )
        if interval < self.minimum_refresh_interval or interval == VERY_LONG_TIME:
            interval = self.minimum_refresh_interval
        return interval, (retry_number + 1 if failures else 0)
