"""POP-style straddling-capacity reconciliation.

A straddling resource's configured capacity is split across every root
shard; each shard solves its LOCAL clients against its share through the
completely ordinary tick/decide machinery. What makes the split
near-lossless (POP, arxiv 2110.11927) is a small per-tick reconciliation
step: every shard reports a compact demand summary (sums and — for
FAIR_SHARE — the piecewise-linear demand curve's breakpoints, NOT
per-client rows), the resource's home reconciler recomputes the shard
shares from the merged summaries, and slack freed on one shard is
re-offered to the others next tick.

Share math per algorithm lane (doc/federation.md derives these):

  * NO_ALGORITHM / STATIC — pointwise per-client semantics: the
    capacity template is a per-client parameter, not a shared total, so
    every shard keeps the FULL configured value and the capacity-sum
    invariant does not apply (a single root overcommits identically).
  * PROPORTIONAL_SHARE (and the topup variant) — demand-proportional:
    under total demand W <= C each shard gets its demand plus an even
    split of the slack (so a local spike next tick is not capped at
    yesterday's demand); in overload c_s = W_s * (C / W), which makes
    the local solve's scale factor c_s / W_s recover the global C / W —
    the single-root allocation (bit-identical whenever that quotient
    round-trips exactly, e.g. any dyadic global ratio; within 1 ulp
    otherwise).
  * FAIR_SHARE — the exact global water level L is computed from the
    merged breakpoint curves (waterfill_level over pseudo-clients, one
    per distinct wants/weight ratio per shard — merging equal-ratio
    clients preserves the level exactly), and each shard's share is its
    own curve evaluated at L. The local water-fill then re-derives a
    level within 1 ulp of L, so grants match the single root to 1 ulp.
  * MAX_MIN_FAIR / PROPORTIONAL_FAIRNESS — same curve decomposition,
    but the global level comes from the lane's OWN bounded fill
    iteration (algorithms.tick.waterfill_level_iterative) so the level
    a shard re-derives locally is the reconciler's: MAX_MIN_FAIR's
    curve aggregates client-granular (weight 1), PROPORTIONAL_FAIRNESS
    by wants/subclients.
  * BALANCED_FAIRNESS — the bounded cap-peeling recursion
    (algorithms.tick.balanced_theta) runs over the merged
    pseudo-clients; a shard's share is the sum of its own clients'
    balanced grants (wants when cap-fixed, weight/θ otherwise). The
    local recursion re-peels the shard's restriction of the global
    fixed set, recovering the global allocation whenever it converges
    within BALANCED_ROUNDS.

Failure containment: a shard the reconciler cannot reach keeps serving
its LAST granted share until that share's expiry (the share is installed
as a parent-style capacity lease), then decays to zero capacity — the
blast radius of a partitioned shard is that shard alone. Its frozen
share keeps counting against the pool until expiry PLUS the resource's
lease length (grants issued under the stale share live that long), so
the hard invariant Σ shard shares <= configured capacity — and with it
Σ shard grants <= configured capacity — holds on every tick, partition
or not. Only after that drain window is the lost shard's slack
re-offered to the survivors.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Set, Tuple

import numpy as np

from doorman_tpu.algorithms.kinds import AlgoKind
from doorman_tpu.algorithms.tick import (
    balanced_theta,
    waterfill_level,
    waterfill_level_iterative,
)

__all__ = [
    "ShardSummary",
    "StraddleReconciler",
    "summarize_resource",
    "CAPACITY_SPLIT_KINDS",
]

# Lanes whose capacity is a shared total the reconciler splits; the
# pointwise lanes (NO_ALGORITHM, STATIC) keep the full template value on
# every shard. PRIORITY_BANDS straddling is not supported: band
# preemption is a cross-client coupling the compact summaries cannot
# carry — route banded resources whole (ShardRouter overrides).
CAPACITY_SPLIT_KINDS = frozenset({
    int(AlgoKind.PROPORTIONAL_SHARE),
    int(AlgoKind.PROPORTIONAL_TOPUP),
    int(AlgoKind.FAIR_SHARE),
    int(AlgoKind.MAX_MIN_FAIR),
    int(AlgoKind.BALANCED_FAIRNESS),
    int(AlgoKind.PROPORTIONAL_FAIRNESS),
})

# Lanes whose breakpoint curve aggregates by the CLIENT-granular ratio
# wants/1 rather than wants/subclients: MAX_MIN_FAIR's fill ignores
# subclient weights, so merging by the weighted ratio would fuse
# clients that saturate at different levels.
_UNWEIGHTED_KINDS = frozenset({int(AlgoKind.MAX_MIN_FAIR)})


@dataclass(frozen=True)
class ShardSummary:
    """One shard's compact per-resource demand report: sums plus the
    fair-share demand curve aggregated by saturation ratio. O(distinct
    ratios), never O(clients)."""

    shard: int
    wants: float = 0.0
    has: float = 0.0
    weight: float = 0.0  # Σ subclients
    # ((wants/weight ratio, Σ wants at that ratio, Σ weight), ...) —
    # sorted by ratio; enough to evaluate Σ min(w_i, L * sub_i) for any
    # level L without per-client data.
    breakpoints: Tuple[Tuple[float, float, float], ...] = ()

    def demand_at_level(self, level: float) -> float:
        """Σ min(wants_i, level * weight_i) over this shard's clients —
        exact from the breakpoint curve (clients at one ratio saturate
        together)."""
        total = 0.0
        for ratio, wants, weight in self.breakpoints:
            total += wants if ratio <= level else level * weight
        return total


def summarize_resource(
    resource, shard: int, kind: "int | None" = None
) -> ShardSummary:
    """Build a shard's summary from its live store rows. The caller
    sweeps expiries first (store.clean()) so lapsed leases do not haunt
    the demand curve; dump_rows is the stores' bulk drain (one C call on
    the native engine). `kind` selects the lane's weighting for the
    breakpoint curve: MAX_MIN_FAIR aggregates client-granular (weight
    1 per client); the weighted lanes aggregate by wants/subclients."""
    unweighted = kind is not None and int(kind) in _UNWEIGHTED_KINDS
    by_ratio: Dict[float, list] = {}
    wants_sum = 0.0
    has_sum = 0.0
    weight_sum = 0.0
    for (_client, _expiry, _refresh, has, wants, subclients,
         _priority) in resource.store.dump_rows():
        weight = 1.0 if unweighted else (float(subclients) or 1.0)
        ratio = wants / weight
        acc = by_ratio.setdefault(ratio, [0.0, 0.0])
        acc[0] += wants
        acc[1] += weight
        wants_sum += wants
        has_sum += has
        weight_sum += weight
    return ShardSummary(
        shard=shard,
        wants=wants_sum,
        has=has_sum,
        weight=weight_sum,
        breakpoints=tuple(
            (r, by_ratio[r][0], by_ratio[r][1]) for r in sorted(by_ratio)
        ),
    )


@dataclass
class _ShareState:
    value: float
    expiry: float


class StraddleReconciler:
    """The per-resource reconciliation state machine (one per straddling
    resource, owned by the resource's home shard — in-process harnesses
    hold them all in FederatedRoots)."""

    def __init__(
        self,
        resource_id: str,
        capacity: float,
        kind: int,
        *,
        share_ttl: float,
        lease_length: float = 0.0,
    ):
        if int(kind) == int(AlgoKind.PRIORITY_BANDS):
            raise ValueError(
                f"straddling resource {resource_id!r} uses "
                "PRIORITY_BANDS: band preemption does not decompose "
                "into compact per-shard summaries — route it whole "
                "(ShardRouter overrides) instead of straddling it"
            )
        self.resource_id = resource_id
        self.capacity = float(capacity)
        self.kind = int(kind)
        self.share_ttl = float(share_ttl)
        self.lease_length = float(lease_length)
        # Last summary and last granted share per shard; unreachable
        # shards coast on these until the drain window closes.
        self._summaries: Dict[int, ShardSummary] = {}
        self._shares: Dict[int, _ShareState] = {}
        # Per-reconcile stats for flight recorders / status pages.
        self.last: dict = {}

    # -- the reconciliation step ---------------------------------------

    def reconcile(
        self,
        summaries: Dict[int, ShardSummary],
        now: float,
        *,
        unreachable: Optional[Set[int]] = None,
    ) -> Dict[int, float]:
        """One step: fold the reachable shards' fresh summaries in,
        compute every reachable shard's new share, and return the
        shares to install ({shard: capacity}). Unreachable shards get
        nothing installed (nothing could deliver it) but their frozen
        shares stay charged against the pool through the drain window."""
        unreachable = set(unreachable or ())
        self._summaries.update(summaries)
        live = sorted(summaries.keys() - unreachable)
        frozen = 0.0
        for shard, share in list(self._shares.items()):
            if shard in live:
                continue
            if now >= share.expiry + self.lease_length:
                # Share lapsed AND every grant issued under it has
                # drained: the slack is finally safe to re-offer.
                del self._shares[shard]
                self._summaries.pop(shard, None)
            else:
                frozen += share.value
        shares = self._compute_shares(live, max(self.capacity - frozen, 0.0))
        expiry = now + self.share_ttl
        for shard, value in shares.items():
            self._shares[shard] = _ShareState(value, expiry)
        self.last = {
            "live": list(live),
            "frozen": round(frozen, 6),
            "shares": {s: round(v, 6) for s, v in sorted(shares.items())},
        }
        return shares

    def _compute_shares(self, live, pool: float) -> Dict[int, float]:
        if not live:
            return {}
        if self.kind not in CAPACITY_SPLIT_KINDS:
            # Pointwise lanes: the template value is per-client config;
            # every shard keeps the full configured value.
            return {shard: self.capacity for shard in live}
        summaries = [self._summaries[s] for s in live]
        wants = [s.wants for s in summaries]
        total = float(sum(wants))
        if total <= pool:
            # Underloaded: demand plus an even split of the slack, so a
            # shard-local spike next tick is not capped at this tick's
            # demand (the POP re-offer in its quiet form).
            slack = (pool - total) / len(live)
            return {
                s.shard: s.wants + slack for s in summaries
            }
        if self.kind == int(AlgoKind.FAIR_SHARE):
            return self._fair_shares(summaries, pool)
        if self.kind in (
            int(AlgoKind.MAX_MIN_FAIR),
            int(AlgoKind.PROPORTIONAL_FAIRNESS),
        ):
            return self._level_shares(summaries, pool)
        if self.kind == int(AlgoKind.BALANCED_FAIRNESS):
            return self._balanced_shares(summaries, pool)
        # Proportional lanes: the global scale factor, distributed so
        # each local solve recovers it (c_s / W_s == pool / total up to
        # the quotient round-trip).
        prop = pool / total
        shares = {s.shard: s.wants * prop for s in summaries}
        return self._clamp(shares, pool)

    @staticmethod
    def _merged(summaries):
        """Flat (wants, weights, shard-slice) arrays over every shard's
        breakpoint pseudo-clients. A pseudo-client is exact for every
        portfolio fill: its saturation test W <= L·U is equivalent to
        the common per-client ratio r <= L, and its sums enter the
        level updates exactly as the per-client sums do."""
        wants = np.array(
            [w for s in summaries for (_r, w, _wt) in s.breakpoints],
            np.float64,
        )
        weights = np.array(
            [wt for s in summaries for (_r, _w, wt) in s.breakpoints],
            np.float64,
        )
        slices = []
        pos = 0
        for s in summaries:
            n = len(s.breakpoints)
            slices.append(slice(pos, pos + n))
            pos += n
        return wants, weights, slices

    def _fair_shares(self, summaries, pool: float) -> Dict[int, float]:
        """Exact global water level over the merged breakpoint curves,
        then each shard's share is its own curve at that level."""
        wants, weights, _slices = self._merged(summaries)
        if wants.size == 0:
            return {s.shard: pool / len(summaries) for s in summaries}
        level = waterfill_level(pool, wants, weights)
        shares = {
            s.shard: s.demand_at_level(level) for s in summaries
        }
        return self._clamp(shares, pool)

    def _level_shares(self, summaries, pool: float) -> Dict[int, float]:
        """MAX_MIN_FAIR / PROPORTIONAL_FAIRNESS: the global level from
        the lane's OWN bounded fill iteration over the merged
        pseudo-clients (matching the local solves' arithmetic, so the
        level each shard re-derives from its share is the global one to
        ~1 ulp), then each shard's share is its curve at that level.
        MAX_MIN_FAIR's curve is client-granular (weight 1; see
        summarize_resource), so one demand_at_level serves both."""
        wants, weights, _slices = self._merged(summaries)
        if wants.size == 0:
            return {s.shard: pool / len(summaries) for s in summaries}
        level = waterfill_level_iterative(pool, wants, weights)
        shares = {
            s.shard: s.demand_at_level(level) for s in summaries
        }
        return self._clamp(shares, pool)

    def _balanced_shares(self, summaries, pool: float) -> Dict[int, float]:
        """BALANCED_FAIRNESS: run the bounded cap-peeling recursion
        over the merged pseudo-clients to get the global binding ratio
        θ and the cap-fixed set, then each shard's share is the sum of
        its own pseudo-clients' balanced grants — wants when fixed,
        min(wants, weight/θ) otherwise. The local recursion at that
        share re-peels the shard's restriction of the fixed set (same
        ratios, fewer classes per round), recovering the global
        allocation whenever it converges within BALANCED_ROUNDS."""
        tiny = np.finfo(np.float64).tiny
        wants, weights, slices = self._merged(summaries)
        if wants.size == 0:
            return {s.shard: pool / len(summaries) for s in summaries}
        theta, fixed = balanced_theta(pool, wants, weights)
        nu = 1.0 / max(theta, tiny)
        gets = np.where(fixed, wants, np.minimum(wants, weights * nu))
        shares = {
            s.shard: float(np.sum(gets[slices[i]]))
            for i, s in enumerate(summaries)
        }
        return self._clamp(shares, pool)

    def _clamp(self, shares: Dict[int, float], pool: float) -> Dict[int, float]:
        """The hard invariant: Σ shares never exceeds the pool. The
        share math sums to the pool mathematically; floating summation
        can land an ulp over, and the invariant is a contract, not a
        tolerance — shave any excess off the largest share."""
        total = sum(shares.values())
        if total > pool and shares:
            top = max(shares, key=lambda s: shares[s])
            shares[top] = max(shares[top] - (total - pool), 0.0)
        return shares

    def status(self) -> dict:
        return {
            "resource": self.resource_id,
            "capacity": self.capacity,
            "share_ttl": self.share_ttl,
            "shares": {
                s: {"value": st.value, "expiry": st.expiry}
                for s, st in sorted(self._shares.items())
            },
            "last": self.last,
        }
