"""FederatedRoots: the root-shard coordinator.

Owns the shard map for one federated deployment's root tier: N ordinary
CapacityServers, each master of its own shard (per-shard election lock,
per-shard persist namespace), plus one StraddleReconciler per straddling
resource. `reconcile_once()` is the POP reconciliation beat: sweep +
summarize every reachable shard's straddling stores, recompute the
shares, and install each share on its shard as a parent-style capacity
lease (CapacityServer.set_straddle_share) that EXPIRES if the
reconciler stops renewing it — which is the whole failure story: a
partitioned shard coasts on its last share until the ttl lapses, then
decays to zero capacity, and nobody else moves.

This class is the in-process harness (tests, bench, chaos) and the
reference implementation of the beat; a wire deployment runs the same
step over GetServerCapacity — each shard reports its summary to the
resource's home shard and receives its share as the response lease
(doc/federation.md, "Deploying the beat over RPC").
"""

from __future__ import annotations

import logging
import time
from typing import Callable, Dict, Optional, Set

from doorman_tpu.core.resource import algo_kind_for
from doorman_tpu.federation.reconcile import (
    StraddleReconciler,
    summarize_resource,
)
from doorman_tpu.federation.router import ShardRouter
from doorman_tpu.obs import trace as trace_mod
from doorman_tpu.server import config as config_mod

log = logging.getLogger(__name__)

# A share must outlive the gap between reconcile beats with margin, or
# healthy shards flap to zero capacity between renewals.
DEFAULT_SHARE_TTL = 10.0


class FederatedRoots:
    """Coordinator over {shard index -> CapacityServer}."""

    def __init__(
        self,
        router: ShardRouter,
        servers: Dict[int, object],
        *,
        share_ttl: float = DEFAULT_SHARE_TTL,
        clock: Callable[[], float] = time.time,
    ):
        if set(servers) != set(range(router.n_shards)):
            raise ValueError(
                f"servers {sorted(servers)} do not cover shards "
                f"[0, {router.n_shards})"
            )
        self.router = router
        self.servers = servers
        self.share_ttl = float(share_ttl)
        self._clock = clock
        # Partition seam: shards listed here are unreachable from the
        # reconciler (the chaos runner's shard_partition fault toggles
        # it; a wire deployment's RPC failures feed the same set).
        self.blocked: Set[int] = set()
        self._reconcilers: Dict[str, StraddleReconciler] = {}
        self.beats = 0

    def _reconciler(self, resource_id: str) -> Optional[StraddleReconciler]:
        rec = self._reconcilers.get(resource_id)
        if rec is not None:
            return rec
        # Capacity + lane come from the home shard's configured
        # template — the one copy of config the whole straddle answers
        # to (shards share one repository in a sane deployment).
        home = self.servers[self.router.shard_of(resource_id)]
        if home.config is None:
            return None
        tpl = config_mod.find_template(home.config, resource_id)
        if tpl is None:
            return None
        rec = StraddleReconciler(
            resource_id,
            float(tpl.capacity),
            algo_kind_for(tpl),
            share_ttl=self.share_ttl,
            lease_length=float(tpl.algorithm.lease_length),
        )
        self._reconcilers[resource_id] = rec
        return rec

    def reconcile_once(self) -> dict:
        """One reconciliation beat over every straddling resource.
        Returns {resource_id: {shard: share}} for the shares installed
        this beat (the chaos runner logs it; status pages read
        `status()`)."""
        self.beats += 1
        now = self._clock()
        installed: Dict[str, Dict[int, float]] = {}
        with trace_mod.default_tracer().span(
            "federation.reconcile", cat="federation",
            args={"straddle": len(self.router.straddle),
                  "blocked": len(self.blocked)},
        ):
            for rid in sorted(self.router.straddle):
                rec = self._reconciler(rid)
                if rec is None:
                    continue
                summaries = {}
                unreachable = set(self.blocked)
                for shard, server in self.servers.items():
                    if shard in unreachable:
                        continue
                    if not server.is_master:
                        # A masterless shard is unreachable in the same
                        # sense as a partitioned one: its share must
                        # freeze, not reset.
                        unreachable.add(shard)
                        continue
                    res = server.resources.get(rid)
                    if res is not None:
                        res.store.clean()
                        summaries[shard] = summarize_resource(
                            res, shard, kind=rec.kind
                        )
                    else:
                        from doorman_tpu.federation.reconcile import (
                            ShardSummary,
                        )

                        summaries[shard] = ShardSummary(shard=shard)
                shares = rec.reconcile(
                    summaries, now, unreachable=unreachable
                )
                for shard, value in shares.items():
                    self.servers[shard].set_straddle_share(
                        rid, value, now + self.share_ttl
                    )
                installed[rid] = shares
        return installed

    def straddle_capacities(self) -> Dict[str, float]:
        """{resource_id: configured capacity} for every straddling
        resource with a built reconciler — the capacity-sum invariant's
        bound (chaos.invariants.check_federation)."""
        return {
            rid: rec.capacity
            for rid, rec in self._reconcilers.items()
        }

    def status(self) -> dict:
        return {
            "router": self.router.status(),
            "share_ttl": self.share_ttl,
            "beats": self.beats,
            "blocked": sorted(self.blocked),
            "straddle": {
                rid: rec.status()
                for rid, rec in sorted(self._reconcilers.items())
            },
        }
