"""FederatedClient: one logical client over N root shards.

Holds one ordinary background-refresh Client per shard it actually
talks to (created lazily through ShardDiscovery), routes each claimed
resource to its owning shard (ShardRouter), and fans a refresh batch out
as one bulk GetCapacity PER OWNING SHARD — the per-shard clients keep
every existing behavior (lease expiry fallback, retry-after pacing,
stream mode) because they ARE the existing client.

Redirect handling: each per-shard connection's mastership chase reports
into the discovery cache (`Connection.on_redirect` ->
`ShardDiscovery.note_master`), so a shard flip updates every routing
decision at RPC speed and a re-resolution storm never forms.

Straddling resources: a straddling resource is served by EVERY shard;
which shard a given client attaches to is a placement decision (client
locality), taken once at claim time via the `shard=` override and
defaulting to the resource's home shard.
"""

from __future__ import annotations

import logging
import time
from typing import Callable, Dict, Iterable, Optional

from doorman_tpu.client.client import Client, ClientResource
from doorman_tpu.federation.discovery import ShardDiscovery
from doorman_tpu.federation.router import ShardRouter

log = logging.getLogger(__name__)


class FederatedClient:
    """The federated analog of Client.connect(): claim resources, let
    the per-shard refresh loops run. Pass `background=False` to drive
    refreshes explicitly with `refresh_once()` (stepped harnesses)."""

    def __init__(
        self,
        router: ShardRouter,
        discovery: ShardDiscovery,
        *,
        client_id: Optional[str] = None,
        background: bool = True,
        clock: Callable[[], float] = time.time,
        **client_kwargs,
    ):
        self.router = router
        self.discovery = discovery
        self.id = client_id
        self._background = background
        self._clock = clock
        self._client_kwargs = dict(client_kwargs)
        self._clients: Dict[int, Client] = {}
        self._closed = False

    async def _client(self, shard: int) -> Client:
        client = self._clients.get(shard)
        if client is not None:
            return client
        addr = await self.discovery.master(shard)
        if self._background:
            client = await Client.connect(
                addr, self.id, clock=self._clock, **self._client_kwargs
            )
        else:
            client = Client(
                addr, self.id, clock=self._clock, **self._client_kwargs
            )
        if self.id is None:
            # One logical identity across every shard: adopt the first
            # per-shard client's generated id (ids are per-shard lease
            # namespaces, so sharing it cannot collide).
            self.id = client.id
        # Invalidate-on-redirect: the connection's mastership chase is
        # the freshest resolution there is.
        client.conn.on_redirect = (
            lambda addr, s=shard: self.discovery.note_master(s, addr)
        )
        self._clients[shard] = client
        return client

    async def resource(
        self,
        resource_id: str,
        wants: float,
        priority: int = 0,
        *,
        shard: Optional[int] = None,
    ) -> ClientResource:
        """Claim a resource on its owning shard. `shard=` overrides
        placement for straddling resources (every shard serves them;
        pick the local one); overriding a NON-straddling resource onto
        a foreign shard is a routing error and raises."""
        owner = self.router.shard_of(resource_id)
        if shard is None:
            shard = owner
        elif shard != owner and not self.router.is_straddling(resource_id):
            raise ValueError(
                f"resource {resource_id!r} is owned by shard {owner}, "
                f"not {shard}; only straddling resources take a "
                "placement override"
            )
        client = await self._client(shard)
        return await client.resource(resource_id, wants, priority=priority)

    async def apply_epoch(
        self, router: ShardRouter, moved: Iterable[str] = ()
    ) -> dict:
        """Adopt a new routing epoch (fleet reshard). Swaps the router
        and re-homes exactly this client's claims on the `moved`
        resources: the live ClientResource object — lease included —
        migrates to the new owner's per-shard client, so the next
        refresh reports the same `has` there and the new owner's
        learning-mode warm-up carries the grant across (lease
        continuity; doc/federation.md). Everything else is untouched:
        unmoved shards' clients keep their connections and cache
        entries, so an epoch bump causes at most one Discovery
        resolution (the new shard), never a stampede."""
        self.router = router
        rehomed = []
        for rid in moved:
            if router.is_straddling(rid):
                continue
            new_shard = router.shard_of(rid)
            for shard, client in list(self._clients.items()):
                if shard == new_shard:
                    continue
                res = client.resources.pop(rid, None)
                if res is None:
                    continue
                target = await self._client(new_shard)
                res._client = target
                target.resources[rid] = res
                target._wake.set()
                rehomed.append(rid)
        return {"rehomed": rehomed}

    async def refresh_once(self) -> bool:
        """One fan-out refresh: every shard client runs one bulk
        refresh cycle; True when every shard's RPC succeeded. Stepped
        harnesses drive this (background=False)."""
        ok = True
        for client in self._clients.values():
            if client.resources:
                ok = await client.refresh_once() and ok
        return ok

    def current_capacity(self, resource_id: str) -> float:
        for client in self._clients.values():
            res = client.resources.get(resource_id)
            if res is not None:
                return res.current_capacity()
        raise KeyError(resource_id)

    async def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        for client in self._clients.values():
            try:
                await client.close()
            except Exception:
                log.exception("shard client close failed")
        self._clients.clear()

    def status(self) -> dict:
        return {
            "id": self.id,
            "shards": {
                shard: {
                    "master": client.master(),
                    "resources": sorted(client.resources),
                }
                for shard, client in sorted(self._clients.items())
            },
            "discovery": self.discovery.status(),
        }
