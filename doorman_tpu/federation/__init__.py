"""doorman_tpu.federation — POP-sharded multi-master roots.

The step from "one master, 1M clients" to "a region, tens of millions":
the resource space partitions across N root shards (router.py), each an
ordinary CapacityServer with its own per-shard mastership
(election.shard_lock_key) and persist namespace
(persist.parse_backend(namespace=...)); clients and intermediates fan
their batches out to the owning shards through a jittered-TTL master
cache (discovery.py, client.py); intermediates aggregate their subtree
on device (aggregate.py); and resources whose capacity straddles shards
reconcile POP-style each tick (reconcile.py, roots.py) with the hard
invariant that the sum of shard grants never exceeds the configured
capacity and convergence to the single-root allocation. doc/federation.md
is the design note; tests/test_federation.py is the conformance suite.
"""

from doorman_tpu.federation.aggregate import (  # noqa: F401
    AggregationTickAdapter,
    FederatedIntermediate,
)
from doorman_tpu.federation.client import FederatedClient  # noqa: F401
from doorman_tpu.federation.discovery import (  # noqa: F401
    ShardDiscovery,
    ShardResolveError,
)
from doorman_tpu.federation.reconcile import (  # noqa: F401
    ShardSummary,
    StraddleReconciler,
    summarize_resource,
)
from doorman_tpu.federation.roots import FederatedRoots  # noqa: F401
from doorman_tpu.federation.router import (  # noqa: F401
    ShardRouter,
    stable_shard,
)

__all__ = [
    "AggregationTickAdapter",
    "FederatedClient",
    "FederatedIntermediate",
    "FederatedRoots",
    "ShardDiscovery",
    "ShardResolveError",
    "ShardRouter",
    "ShardSummary",
    "StraddleReconciler",
    "stable_shard",
    "summarize_resource",
]
