"""Client-side per-shard master resolution with a jittered-TTL cache.

Every federated caller (clients fanning a refresh batch, intermediates
fanning upstream requests) needs "who is shard k's master right now".
Resolving that with a Discovery RPC per refresh would turn every shard
flip into a Discovery stampede — the exact herd admission control exists
to prevent, self-inflicted. The cache rules:

  * a resolution is reused until its deadline; deadlines carry ±jitter
    so a fleet whose caches were warmed together does not re-resolve
    together;
  * a mastership redirect observed on a live connection IS a
    resolution — `note_master` replaces the cache entry in place
    (invalidate-on-redirect), so the flip propagates at RPC speed with
    zero extra Discovery traffic;
  * `invalidate` drops one shard's entry (a failed dial) without
    touching the others.

Resolution itself walks the shard's seed addresses and asks Discovery;
a seed that answers "not master, the master is X" resolves to X without
another hop (the reference's Discovery contract).
"""

from __future__ import annotations

import logging
import random
import time
from typing import Callable, Dict, Mapping, Optional, Sequence, Tuple, Union

import grpc

from doorman_tpu.proto import doorman_pb2 as pb
from doorman_tpu.proto.grpc_api import CapacityStub

log = logging.getLogger(__name__)

DEFAULT_TTL = 30.0
DEFAULT_JITTER = 0.2  # fraction of ttl, both directions
RESOLVE_TIMEOUT = 5.0


class ShardResolveError(ConnectionError):
    """No seed of the shard produced a usable master address."""


class ShardDiscovery:
    """The per-shard master cache. `seeds` maps shard index to one seed
    address or a list of them (any election candidate works as a seed —
    non-masters answer Discovery with the master's address)."""

    def __init__(
        self,
        seeds: Mapping[int, Union[str, Sequence[str]]],
        *,
        ttl: float = DEFAULT_TTL,
        jitter: float = DEFAULT_JITTER,
        clock: Callable[[], float] = time.time,
        rng: Optional[random.Random] = None,
        resolver: Optional[Callable] = None,
    ):
        """`resolver(shard, seed_addrs) -> addr` substitutes the gRPC
        Discovery walk (tests; a wire deployment's service-mesh lookup).
        `rng` is the jitter seam — pass a seeded random.Random for
        deterministic replays (unseeded only when nothing is injected)."""
        self._seeds: Dict[int, Tuple[str, ...]] = {}
        for shard, addrs in seeds.items():
            if isinstance(addrs, str):
                addrs = (addrs,)
            self._seeds[int(shard)] = tuple(addrs)
        self.ttl = float(ttl)
        self.jitter = float(jitter)
        self._clock = clock
        self._rng = rng if rng is not None else random.Random()
        self._resolver = resolver or self._grpc_resolve
        self._cache: Dict[int, Tuple[str, float]] = {}
        # Counters the stampede tests (and status pages) read.
        self.resolutions = 0
        self.hits = 0
        self.invalidations = 0

    def _deadline(self, now: float) -> float:
        spread = self.ttl * self.jitter
        return now + self.ttl + self._rng.uniform(-spread, spread)

    async def master(self, shard: int) -> str:
        """The shard's master address — cached, or freshly resolved."""
        now = self._clock()
        entry = self._cache.get(shard)
        if entry is not None and now < entry[1]:
            self.hits += 1
            return entry[0]
        seeds = self._seeds.get(shard)
        if not seeds:
            raise ShardResolveError(f"no seeds configured for shard {shard}")
        addr = await self._resolver(shard, seeds)
        self.resolutions += 1
        self._cache[shard] = (addr, self._deadline(now))
        return addr

    def add_seeds(
        self, seeds: Mapping[int, Union[str, Sequence[str]]]
    ) -> None:
        """Extend the seed map in place — the fleet supervisor calls
        this when a reshard activates shards that did not exist when
        the cache was built. Existing entries are replaced; cached
        resolutions are NOT touched (a new seed list says nothing
        about who is master right now)."""
        for shard, addrs in seeds.items():
            if isinstance(addrs, str):
                addrs = (addrs,)
            self._seeds[int(shard)] = tuple(addrs)

    def note_master(self, shard: int, addr: str) -> None:
        """Invalidate-on-redirect: a live connection just learned the
        shard's real master from a mastership redirect — that IS the
        freshest possible resolution, so the cache takes it instead of
        scheduling a Discovery round."""
        self.invalidations += 1
        self._cache[shard] = (addr, self._deadline(self._clock()))

    def invalidate(self, shard: int) -> None:
        """Drop one shard's entry (a dial against it failed); the next
        `master()` call re-resolves just that shard."""
        if self._cache.pop(shard, None) is not None:
            self.invalidations += 1

    async def _grpc_resolve(self, shard: int, seeds: Sequence[str]) -> str:
        last_error: Optional[Exception] = None
        for seed in seeds:
            try:
                async with grpc.aio.insecure_channel(seed) as channel:
                    out = await CapacityStub(channel).Discovery(
                        pb.DiscoveryRequest(), timeout=RESOLVE_TIMEOUT
                    )
                if out.is_master:
                    return seed
                addr = out.mastership.master_address
                if addr:
                    return addr
            except Exception as e:
                last_error = e
                log.warning(
                    "shard %d seed %s discovery failed: %r", shard, seed, e
                )
        raise ShardResolveError(
            f"shard {shard}: no seed produced a master "
            f"(last error: {last_error!r})"
        )

    def status(self) -> dict:
        now = self._clock()
        return {
            "ttl": self.ttl,
            "jitter": self.jitter,
            "resolutions": self.resolutions,
            "hits": self.hits,
            "invalidations": self.invalidations,
            "cache": {
                shard: {"addr": addr, "fresh_for": round(dl - now, 3)}
                for shard, (addr, dl) in sorted(self._cache.items())
            },
        }
