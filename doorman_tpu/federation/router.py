"""Resource -> root-shard routing for the federated capacity tree.

The reference scales past one master with a tree of intermediates; this
layer scales the ROOT itself: the resource space is partitioned across N
root shards, each an ordinary CapacityServer winning its own per-shard
mastership (election.shard_lock_key) and persisting to its own
journal/snapshot namespace (persist.parse_backend(namespace=...)). The
router is the one place that decides ownership, shared verbatim by
clients (fan a refresh batch out to the owning shards), intermediates
(one upstream GetServerCapacity per resource, to the owner), and the
straddle reconciler (which shard is a straddling resource's home).

Routing is a STABLE hash — blake2b over the resource id, mod the shard
count — so every client, intermediate, and operator tool in a
deployment computes the same owner with no coordination, across
processes and Python versions (never the process-seeded builtin
`hash`). Explicit overrides pin named resources to chosen shards
(operational escape hatch: drain a shard, co-locate a family), and
`straddle` names the resources whose capacity is SPLIT across every
shard — POP-style (arxiv 2110.11927): each shard solves its local
subproblem against a reconciled capacity share, and the small
reconciliation step (federation/reconcile.py) converges the shares to
the single-root allocation.
"""

from __future__ import annotations

import hashlib
from typing import Dict, Iterable, List, Mapping, Optional, Sequence, Tuple

__all__ = ["ShardRouter", "stable_shard"]


def stable_shard(resource_id: str, n_shards: int) -> int:
    """The stable hash route: blake2b(resource_id) mod n_shards.

    8 digest bytes keep the modulo bias unmeasurable at any plausible
    shard count while staying a single int conversion."""
    if n_shards <= 0:
        raise ValueError(f"n_shards must be positive, got {n_shards}")
    digest = hashlib.blake2b(
        resource_id.encode("utf-8"), digest_size=8
    ).digest()
    return int.from_bytes(digest, "big") % n_shards


class ShardRouter:
    """The resource->shard map: stable hash + explicit overrides +
    the straddle set.

    * `shard_of(rid)` — the single OWNING shard (a straddling
      resource's owner is its home shard: the one that runs its
      reconciler in a wire deployment).
    * `owners(rid)` — every shard holding capacity for the resource:
      just the owner for normal resources, all shards for straddling
      ones.
    * `split(rids)` — partition a request batch by owning shard (the
      client/intermediate fan-out shape).
    """

    def __init__(
        self,
        n_shards: int,
        *,
        overrides: Optional[Mapping[str, int]] = None,
        straddle: Iterable[str] = (),
    ):
        if n_shards <= 0:
            raise ValueError(f"n_shards must be positive, got {n_shards}")
        self.n_shards = int(n_shards)
        self.overrides: Dict[str, int] = dict(overrides or {})
        for rid, shard in self.overrides.items():
            if not 0 <= shard < self.n_shards:
                raise ValueError(
                    f"override {rid!r} -> shard {shard} outside "
                    f"[0, {self.n_shards})"
                )
        self.straddle = frozenset(straddle)

    def shard_of(self, resource_id: str) -> int:
        override = self.overrides.get(resource_id)
        if override is not None:
            return override
        return stable_shard(resource_id, self.n_shards)

    def is_straddling(self, resource_id: str) -> bool:
        return resource_id in self.straddle

    def owners(self, resource_id: str) -> Tuple[int, ...]:
        if resource_id in self.straddle:
            return tuple(range(self.n_shards))
        return (self.shard_of(resource_id),)

    def split(
        self, resource_ids: Sequence[str]
    ) -> Dict[int, List[str]]:
        """Partition a batch by owning shard, preserving request order
        within each shard (response merge order stays deterministic)."""
        out: Dict[int, List[str]] = {}
        for rid in resource_ids:
            out.setdefault(self.shard_of(rid), []).append(rid)
        return out

    def status(self) -> dict:
        return {
            "n_shards": self.n_shards,
            "overrides": dict(self.overrides),
            "straddle": sorted(self.straddle),
        }
