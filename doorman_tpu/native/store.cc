// Native lease-store engine: the host-runtime hot path in C++.
//
// Capability parity with the Python LeaseStore
// (doorman_tpu/core/store.py, itself mirroring reference
// /root/reference/go/server/doorman/store.go:68-213): per-resource
// client -> lease maps with O(1) running sum_has / sum_wants / subclient
// aggregates, expiry sweep, and a bulk resource-major edge dump feeding
// the batch solver's snapshot packer without per-lease Python overhead.
//
// One Engine holds every resource of a server, so a tick's snapshot is a
// single dm_pack call. String ids are interned once at the boundary
// (dm_resource / dm_client); all per-request operations afterwards are
// integer-keyed. The clock is injected from the caller (absolute expiry
// stamps, `now` for sweeps) so simulated time works identically to the
// Python store.
//
// Iteration/packing order is deterministic: insertion order, perturbed
// only by swap-remove on release/expiry — the same guarantee the Python
// store documents for reproducible packing.

#include <algorithm>
#include <cstdint>
#include <limits>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct Lease {
  double expiry;
  double refresh_interval;
  double has;
  double wants;
  int32_t subclients;
  int64_t priority;
};

struct ResourceStore {
  std::unordered_map<int64_t, size_t> index;  // client handle -> slot
  std::vector<int64_t> clients;               // slot -> client handle
  std::vector<Lease> leases;                  // slot -> lease
  double sum_has = 0.0;
  double sum_wants = 0.0;
  int64_t count = 0;  // total subclients
  // Membership epoch: bumped whenever the client->slot mapping changes
  // (insert, release, expiry sweep). The device-resident solver records
  // the epoch it uploaded and skips write-backs whose rows went stale
  // while the solve was in flight.
  uint64_t version = 0;
  // Set when the row changed beyond wants (membership, has, subclients,
  // priority) since the last drain2: such rows need a full re-upload,
  // while wants-only churn — the steady-state refresh traffic — ships
  // just the wants lane over the (slow) host<->device link.
  uint8_t dirty_full = 0;
  // Lower bound on every lease's expiry: the per-tick engine-wide sweep
  // skips the whole resource while now <= min_expiry, turning the O(all
  // leases) scan into O(resources) in steady state. Writes only tighten
  // it (removals and later re-stamps leave it loose); a sweep that does
  // scan recomputes it exactly from the survivors.
  double min_expiry = std::numeric_limits<double>::infinity();

  // Wide-resource (chunked) tracking: a resource wider than the dense
  // bucket cap is split across consecutive device rows of
  // engine.chunk_width slots each. Dirtiness is per SLOT (a single
  // client's wants change must not re-upload a million-lease table),
  // write-back validity per CHUNK (chunk_version bumps whenever the
  // slot<->client mapping inside that chunk changes, so an in-flight
  // apply skips exactly the chunks whose slot order went stale).
  bool chunk_tracked = false;
  std::vector<uint64_t> chunk_version;
  std::vector<uint8_t> slot_dirty;        // 0 clean, 1 wants-only, 2 full
  std::vector<int64_t> slot_dirty_list;
};

struct Engine {
  std::vector<ResourceStore> resources;
  std::unordered_map<std::string, int32_t> resource_ids;
  std::unordered_map<std::string, int64_t> client_ids;
  int64_t next_client = 0;
  // Dirty tracking for delta uploads: a resource is dirty when any
  // solver-visible input changed (wants/has/subclients/priority or
  // membership) since the last drain. Pure expiry refreshes with
  // unchanged demand do NOT dirty a row — the steady-state refresh
  // storm must not defeat delta uploads.
  std::vector<uint8_t> dirty_flags;
  std::vector<int32_t> dirty_list;
  // Chunk width for wide-resource tracking (0 = disabled). Resources
  // opted in via dm_chunk_config get slot-granular dirty lists and
  // per-chunk membership versions on top of the per-resource flags
  // above; the two channels are independent, so the narrow resident
  // solver's drains never consume (or get consumed by) the wide
  // solver's.
  int64_t chunk_width = 0;
  std::vector<int32_t> slot_dirty_rids;  // tracked rids with dirty slots
  // One writer (tick thread) and many RPC-handler calls share the
  // engine once the server moves prepare/apply off the event loop;
  // every exported call locks. ctypes releases the GIL during calls,
  // so a long pack blocks only callers touching this engine.
  std::mutex mu;
};

// Mark one slot of a chunk-tracked resource dirty (level 1 = wants-only,
// 2 = full: has/subclients/priority or slot content changed). Levels
// only upgrade until the next drain.
inline void mark_slot(Engine *e, int32_t rid, ResourceStore &r, size_t slot,
                      uint8_t level) {
  if (!r.chunk_tracked || e->chunk_width <= 0) return;
  if (r.slot_dirty.size() <= slot) r.slot_dirty.resize(slot + 1, 0);
  if (!r.slot_dirty[slot]) {
    if (r.slot_dirty_list.empty()) e->slot_dirty_rids.push_back(rid);
    r.slot_dirty_list.push_back(static_cast<int64_t>(slot));
  }
  if (level > r.slot_dirty[slot]) r.slot_dirty[slot] = level;
}

// The slot<->client mapping inside `slot`'s chunk changed (insert,
// swap-remove): an in-flight dense apply of that chunk would write
// grants against the wrong clients, so its version moves.
inline void bump_chunk(Engine *e, ResourceStore &r, size_t slot) {
  if (!r.chunk_tracked || e->chunk_width <= 0) return;
  const size_t c = slot / static_cast<size_t>(e->chunk_width);
  if (r.chunk_version.size() <= c) r.chunk_version.resize(c + 1, 0);
  ++r.chunk_version[c];
}

// Swap-remove `slot`, maintaining aggregates, the membership epoch, and
// — for chunk-tracked resources — the chunk versions and slot dirt of
// both touched chunks (the removed slot and the moved-from last slot).
inline void remove_slot(Engine *e, int32_t rid, ResourceStore &r,
                        size_t slot) {
  const Lease &l = r.leases[slot];
  r.sum_has -= l.has;
  r.sum_wants -= l.wants;
  r.count -= l.subclients;
  r.index.erase(r.clients[slot]);
  const size_t last = r.clients.size() - 1;
  if (slot != last) {
    r.clients[slot] = r.clients[last];
    r.leases[slot] = r.leases[last];
    r.index[r.clients[slot]] = slot;
    mark_slot(e, rid, r, slot, 2);
  }
  r.clients.pop_back();
  r.leases.pop_back();
  ++r.version;
  r.dirty_full = 1;
  bump_chunk(e, r, slot);
  bump_chunk(e, r, last);
  // The vacated last slot goes inactive on device; ship its (zeroed)
  // state so a stale lease doesn't keep solving there.
  mark_slot(e, rid, r, last, 2);
}

// Shared expiry sweep: skipped entirely while nothing can be expired
// (the min_expiry lower bound), else swap-removes lapsed leases and
// recomputes the exact bound from the survivors.
inline int64_t sweep_resource(Engine *e, int32_t rid, ResourceStore &r,
                              double now) {
  if (!(now > r.min_expiry)) return 0;
  int64_t removed = 0;
  double new_min = std::numeric_limits<double>::infinity();
  for (size_t slot = 0; slot < r.leases.size();) {
    if (now > r.leases[slot].expiry) {
      remove_slot(e, rid, r, slot);  // swap-remove: re-check the slot
      ++removed;
    } else {
      if (r.leases[slot].expiry < new_min) new_min = r.leases[slot].expiry;
      ++slot;
    }
  }
  r.min_expiry = new_min;
  return removed;
}

// API convention: every extern entry point treats an out-of-range
// resource handle as a no-op (skip / return 0 / zero-fill) — a
// Python-level bookkeeping bug must degrade to a miss at this ctypes
// boundary, never to an out-of-bounds access.
inline bool valid_rid(const Engine *e, int32_t rid) {
  return rid >= 0 && rid < static_cast<int32_t>(e->resources.size());
}

inline void mark_dirty(Engine *e, int32_t rid) {
  if (e->dirty_flags.size() < e->resources.size())
    e->dirty_flags.resize(e->resources.size(), 0);
  if (!e->dirty_flags[rid]) {
    e->dirty_flags[rid] = 1;
    e->dirty_list.push_back(rid);
  }
}

// Shared upsert body (dm_assign and dm_bulk_assign): insert or replace
// the client's lease, maintaining the running aggregates by delta.
// Returns 1 if the client already held a lease, 0 if new.
inline int32_t upsert(Engine *e, int32_t rid, int64_t cid,
                      const Lease &fresh) {
  ResourceStore &r = e->resources[rid];
  auto it = r.index.find(cid);
  if (it == r.index.end()) {
    const size_t slot = r.clients.size();
    r.index.emplace(cid, slot);
    r.clients.push_back(cid);
    r.leases.push_back(fresh);
    r.sum_has += fresh.has;
    r.sum_wants += fresh.wants;
    r.count += fresh.subclients;
    ++r.version;
    r.dirty_full = 1;
    mark_dirty(e, rid);
    bump_chunk(e, r, slot);
    mark_slot(e, rid, r, slot, 2);
    if (fresh.expiry < r.min_expiry) r.min_expiry = fresh.expiry;
    return 0;
  }
  Lease &l = r.leases[it->second];
  const bool full_changed = l.has != fresh.has ||
                            l.subclients != fresh.subclients ||
                            l.priority != fresh.priority;
  if (full_changed) r.dirty_full = 1;
  if (full_changed || l.wants != fresh.wants) {
    mark_dirty(e, rid);
    mark_slot(e, rid, r, it->second, full_changed ? 2 : 1);
  }
  r.sum_has += fresh.has - l.has;
  r.sum_wants += fresh.wants - l.wants;
  r.count += fresh.subclients - l.subclients;
  l = fresh;
  if (fresh.expiry < r.min_expiry) r.min_expiry = fresh.expiry;
  return 1;
}

}  // namespace

extern "C" {

Engine *dm_engine_new() { return new Engine(); }

void dm_engine_free(Engine *e) { delete e; }

// Get-or-create the resource store for `id`; returns its handle.
int32_t dm_resource(Engine *e, const char *id) {
  std::lock_guard<std::mutex> lock(e->mu);
  auto it = e->resource_ids.find(id);
  if (it != e->resource_ids.end()) return it->second;
  const int32_t rid = static_cast<int32_t>(e->resources.size());
  e->resource_ids.emplace(id, rid);
  e->resources.emplace_back();
  e->dirty_flags.push_back(0);
  return rid;
}

// Intern a client id; returns its handle (stable for the engine's life).
int64_t dm_client(Engine *e, const char *id) {
  std::lock_guard<std::mutex> lock(e->mu);
  auto it = e->client_ids.find(id);
  if (it != e->client_ids.end()) return it->second;
  const int64_t cid = e->next_client++;
  e->client_ids.emplace(id, cid);
  return cid;
}

// Upsert a lease; running sums update by delta. Returns 1 if the client
// already held a lease, 0 if this is a new entry.
int32_t dm_assign(Engine *e, int32_t rid, int64_t cid, double expiry,
                  double refresh_interval, double has, double wants,
                  int32_t subclients, int64_t priority) {
  std::lock_guard<std::mutex> lock(e->mu);
  if (!valid_rid(e, rid)) return 0;
  return upsert(e, rid, cid,
                Lease{expiry, refresh_interval, has, wants, subclients,
                      priority});
}

// Bulk upsert: one call assigns n leases (snapshot load / state
// transfer; the per-call ctypes overhead of dm_assign dominates it for
// large n). rid[i] are engine resource handles per edge; out-of-range
// handles are skipped. Returns the number assigned.
int64_t dm_bulk_assign(Engine *e, const int32_t *rid, const int64_t *cid,
                       const double *expiry, const double *refresh,
                       const double *has, const double *wants,
                       const int32_t *subclients, const int64_t *priority,
                       int64_t n) {
  std::lock_guard<std::mutex> lock(e->mu);
  int64_t assigned = 0;
  for (int64_t i = 0; i < n; ++i) {
    if (!valid_rid(e, rid[i])) continue;
    upsert(e, rid[i], cid[i],
           Lease{expiry[i], refresh[i], has[i], wants[i], subclients[i],
                 priority[i]});
    ++assigned;
  }
  return assigned;
}

// Update ONLY the granted capacity of an existing lease — the
// single-lease form of the apply write-back (same semantics: no expiry
// or refresh change, and NO dirty marking: a grant delivery is the
// solver writing its own output, not new demand; marking it dirty
// would force a full re-upload next tick and defeat the idle fast
// path). Returns 1 if the client held a lease, else 0.
int32_t dm_regrant(Engine *e, int32_t rid, int64_t cid, double has) {
  std::lock_guard<std::mutex> lock(e->mu);
  if (!valid_rid(e, rid)) return 0;
  ResourceStore &r = e->resources[rid];
  auto it = r.index.find(cid);
  if (it == r.index.end()) return 0;
  Lease &l = r.leases[it->second];
  r.sum_has += has - l.has;
  l.has = has;
  return 1;
}

// Returns 1 if the client held a lease (now removed), else 0.
int32_t dm_release(Engine *e, int32_t rid, int64_t cid) {
  std::lock_guard<std::mutex> lock(e->mu);
  if (!valid_rid(e, rid)) return 0;
  ResourceStore &r = e->resources[rid];
  auto it = r.index.find(cid);
  if (it == r.index.end()) return 0;
  remove_slot(e, rid, r, it->second);
  mark_dirty(e, rid);
  return 1;
}

// Sweep leases with expiry < now (strict: `now > expiry` like the Python
// store); returns how many were removed.
int64_t dm_clean(Engine *e, int32_t rid, double now) {
  std::lock_guard<std::mutex> lock(e->mu);
  if (!valid_rid(e, rid)) return 0;
  ResourceStore &r = e->resources[rid];
  const int64_t removed = sweep_resource(e, rid, r, now);
  if (removed) mark_dirty(e, rid);
  return removed;
}

// Engine-wide expiry sweep in one call; returns total removed.
int64_t dm_clean_all(Engine *e, double now) {
  std::lock_guard<std::mutex> lock(e->mu);
  int64_t removed = 0;
  for (size_t rid = 0; rid < e->resources.size(); ++rid) {
    ResourceStore &r = e->resources[rid];
    const int64_t here =
        sweep_resource(e, static_cast<int32_t>(rid), r, now);
    if (here) mark_dirty(e, static_cast<int32_t>(rid));
    removed += here;
  }
  return removed;
}

// Drain the dirty-resource list: writes up to `cap` dirty handles to
// `out`, clears the flags (incl. dirty_full, reported in full_out:
// full_out[i]=1 means the row changed beyond wants since its last
// drain and needs a full re-upload), returns the count written.
int64_t dm_drain_dirty2(Engine *e, int32_t *out, uint8_t *full_out,
                        int64_t cap) {
  std::lock_guard<std::mutex> lock(e->mu);
  const int64_t n =
      std::min<int64_t>(cap, static_cast<int64_t>(e->dirty_list.size()));
  for (int64_t i = 0; i < n; ++i) {
    const int32_t rid = e->dirty_list[i];
    out[i] = rid;
    e->dirty_flags[rid] = 0;
    full_out[i] = e->resources[rid].dirty_full;
    e->resources[rid].dirty_full = 0;
  }
  e->dirty_list.erase(e->dirty_list.begin(), e->dirty_list.begin() + n);
  return n;
}

// Dense row pack: for each of n resources, write its leases into row i
// of the [n, K] slabs (slot-major, zero padding beyond the count).
// counts_out[i] is the resource's FULL lease count (callers detect
// K overflow when counts_out[i] > K); versions_out[i] its membership
// epoch at pack time.
void dm_pack_rows(Engine *e, const int32_t *rids, int64_t n, int64_t K,
                  double *wants, double *has, double *sub, uint8_t *act,
                  int32_t *counts_out, uint64_t *versions_out) {
  std::lock_guard<std::mutex> lock(e->mu);
  for (int64_t i = 0; i < n; ++i) {
    double *w = wants + i * K;
    double *h = has + i * K;
    double *s = sub + i * K;
    uint8_t *a = act + i * K;
    if (!valid_rid(e, rids[i])) {
      std::fill(w, w + K, 0.0);
      std::fill(h, h + K, 0.0);
      std::fill(s, s + K, 0.0);
      std::fill(a, a + K, uint8_t{0});
      counts_out[i] = 0;
      versions_out[i] = 0;
      continue;
    }
    const ResourceStore &r = e->resources[rids[i]];
    const int64_t filled =
        std::min<int64_t>(K, static_cast<int64_t>(r.leases.size()));
    for (int64_t j = 0; j < filled; ++j) {
      const Lease &l = r.leases[j];
      w[j] = l.wants;
      h[j] = l.has;
      s[j] = l.subclients;
      a[j] = 1;
    }
    std::fill(w + filled, w + K, 0.0);
    std::fill(h + filled, h + K, 0.0);
    std::fill(s + filled, s + K, 0.0);
    std::fill(a + filled, a + K, uint8_t{0});
    counts_out[i] = static_cast<int32_t>(r.leases.size());
    versions_out[i] = r.version;
  }
}

// Per-priority-band aggregates of one resource: writes up to `cap`
// distinct (priority, wants-sum, subclient-count) triples in ascending
// priority order; returns the number of bands. Feeds the intermediate
// server's upstream aggregation without per-lease Python objects.
int64_t dm_band_aggregates(Engine *e, int32_t rid, int64_t *prio_out,
                           double *wants_out, int64_t *num_out,
                           int64_t cap) {
  std::lock_guard<std::mutex> lock(e->mu);
  if (!valid_rid(e, rid)) return 0;
  const ResourceStore &r = e->resources[rid];
  // O(L) accumulate + O(B log B) sort: this runs under the engine
  // mutex for million-lease stores, so no per-lease band scan.
  std::unordered_map<int64_t, std::pair<double, int64_t>> acc;
  for (const Lease &l : r.leases) {
    auto &slot = acc[l.priority];
    slot.first += l.wants;
    slot.second += l.subclients;
  }
  std::vector<std::pair<int64_t, std::pair<double, int64_t>>> bands(
      acc.begin(), acc.end());
  std::sort(bands.begin(), bands.end());
  const int64_t n = std::min<int64_t>(
      cap, static_cast<int64_t>(bands.size()));
  for (int64_t i = 0; i < n; ++i) {
    prio_out[i] = bands[i].first;
    wants_out[i] = bands[i].second.first;
    num_out[i] = bands[i].second.second;
  }
  return n;
}

// Bulk demand refresh: update wants and stamp expiry/refresh for n
// leases, PRESERVING each lease's current has/subclients/priority —
// the store effect of a client's periodic GetCapacity refresh. Missing
// clients and out-of-range handles are skipped. Returns the number
// refreshed.
int64_t dm_bulk_refresh(Engine *e, const int32_t *rid, const int64_t *cid,
                        const double *expiry, const double *refresh,
                        const double *wants, int64_t n) {
  std::lock_guard<std::mutex> lock(e->mu);
  int64_t refreshed = 0;
  for (int64_t i = 0; i < n; ++i) {
    if (!valid_rid(e, rid[i])) continue;
    ResourceStore &r = e->resources[rid[i]];
    auto it = r.index.find(cid[i]);
    if (it == r.index.end()) continue;
    Lease &l = r.leases[it->second];
    if (l.wants != wants[i]) {
      mark_dirty(e, rid[i]);
      mark_slot(e, rid[i], r, it->second, 1);
    }
    r.sum_wants += wants[i] - l.wants;
    l.wants = wants[i];
    l.expiry = expiry[i];
    l.refresh_interval = refresh[i];
    if (expiry[i] < r.min_expiry) r.min_expiry = expiry[i];
    ++refreshed;
  }
  return refreshed;
}

// Dense grant write-back: grants is [n, K] row-major in the slot order
// of each resource AT UPLOAD TIME. A row only applies when the
// resource's membership epoch still equals expected_version[i] — rows
// that changed while the solve was in flight are skipped (their change
// dirtied the row, so the next tick re-solves and re-delivers them).
// Writes ONLY the granted capacity: lease expiry/refresh advance when
// the client itself refreshes (the decide path), never on delivery —
// otherwise a crashed client's lease would be renewed forever by the
// tick and its capacity never reclaimed (reference semantics: Decide
// stamps the requester only, store.go:153-181). keep_has[i] != 0
// preserves even has (learning-mode replay). Returns rows applied.
int64_t dm_apply_dense(Engine *e, const int32_t *rids, int64_t n,
                       int64_t K, const double *grants,
                       const uint8_t *keep_has,
                       const uint64_t *expected_version) {
  std::lock_guard<std::mutex> lock(e->mu);
  int64_t applied = 0;
  for (int64_t i = 0; i < n; ++i) {
    if (!valid_rid(e, rids[i])) continue;
    ResourceStore &r = e->resources[rids[i]];
    if (r.version != expected_version[i]) continue;
    if (!keep_has[i]) {
      const double *g = grants + i * K;
      const int64_t filled =
          std::min<int64_t>(K, static_cast<int64_t>(r.leases.size()));
      for (int64_t j = 0; j < filled; ++j) {
        Lease &l = r.leases[j];
        r.sum_has += g[j] - l.has;
        l.has = g[j];
      }
    }
    ++applied;
  }
  return applied;
}

// ---- Wide-resource (chunked) tracking --------------------------------
//
// A resource wider than the dense bucket cap spans consecutive device
// rows of `W` slots each (slot s lives at row s/W, lane s%W). These
// calls give the wide resident solver slot-granular upload deltas and
// chunk-granular write-back validity, independent of the per-resource
// dirty channel the narrow solver drains.

// Install the tracked set: chunk width W, tracked rids. Clears all
// previous chunk state (slot dirt, chunk versions) engine-wide; the
// caller repacks every tracked chunk right after (a rebuild), so
// versions restart at 0.
void dm_chunk_config(Engine *e, const int32_t *rids, int64_t n,
                     int64_t W) {
  std::lock_guard<std::mutex> lock(e->mu);
  e->chunk_width = W;
  e->slot_dirty_rids.clear();
  for (ResourceStore &r : e->resources) {
    r.chunk_tracked = false;
    r.chunk_version.clear();
    r.slot_dirty.clear();
    r.slot_dirty_list.clear();
  }
  for (int64_t i = 0; i < n; ++i) {
    if (!valid_rid(e, rids[i])) continue;
    ResourceStore &r = e->resources[rids[i]];
    r.chunk_tracked = true;
    const size_t chunks =
        W > 0 ? (r.leases.size() + W - 1) / static_cast<size_t>(W) : 0;
    r.chunk_version.assign(std::max<size_t>(chunks, 1), 0);
  }
}

// Drain one tracked resource's dirty slots: writes up to `cap`
// (slot, level) pairs — level 1 = wants-only, 2 = full — and clears
// them. Returns the count written (call again if == cap).
int64_t dm_drain_slots(Engine *e, int32_t rid, int64_t *slots_out,
                       uint8_t *level_out, int64_t cap) {
  std::lock_guard<std::mutex> lock(e->mu);
  if (!valid_rid(e, rid)) return 0;
  ResourceStore &r = e->resources[rid];
  const int64_t n = std::min<int64_t>(
      cap, static_cast<int64_t>(r.slot_dirty_list.size()));
  for (int64_t i = 0; i < n; ++i) {
    const int64_t slot = r.slot_dirty_list[i];
    slots_out[i] = slot;
    level_out[i] =
        slot < static_cast<int64_t>(r.slot_dirty.size())
            ? r.slot_dirty[slot]
            : uint8_t{2};
    if (slot < static_cast<int64_t>(r.slot_dirty.size()))
      r.slot_dirty[slot] = 0;
  }
  r.slot_dirty_list.erase(r.slot_dirty_list.begin(),
                          r.slot_dirty_list.begin() + n);
  if (r.slot_dirty_list.empty()) {
    auto &v = e->slot_dirty_rids;
    v.erase(std::remove(v.begin(), v.end(), rid), v.end());
  }
  return n;
}

// Tracked rids that currently have dirty slots; returns count (<= cap).
int64_t dm_dirty_slot_rids(Engine *e, int32_t *out, int64_t cap) {
  std::lock_guard<std::mutex> lock(e->mu);
  const int64_t n = std::min<int64_t>(
      cap, static_cast<int64_t>(e->slot_dirty_rids.size()));
  for (int64_t i = 0; i < n; ++i) out[i] = e->slot_dirty_rids[i];
  return n;
}

// Gather n slots' solver-visible state (wants/has/subclients/active);
// slots at/beyond the lease count read as inactive zeros (that IS the
// upload that clears a vacated lane on device).
void dm_pack_slots(Engine *e, int32_t rid, const int64_t *slots, int64_t n,
                   double *wants, double *has, double *sub, uint8_t *act) {
  std::lock_guard<std::mutex> lock(e->mu);
  const bool ok = valid_rid(e, rid);
  const ResourceStore *r = ok ? &e->resources[rid] : nullptr;
  const int64_t size =
      ok ? static_cast<int64_t>(r->leases.size()) : 0;
  for (int64_t i = 0; i < n; ++i) {
    const int64_t s = slots[i];
    if (s < 0 || s >= size) {
      wants[i] = has[i] = sub[i] = 0.0;
      act[i] = 0;
      continue;
    }
    const Lease &l = r->leases[s];
    wants[i] = l.wants;
    has[i] = l.has;
    sub[i] = l.subclients;
    act[i] = 1;
  }
}

// Pack n chunks as rows of the [n, W] slabs: row i holds slots
// [chunks[i]*W, chunks[i]*W + W) of rids[i] (zeros beyond the lease
// count). filled_out[i] = live slots in the chunk; versions_out[i] =
// the chunk's membership version at pack time.
void dm_pack_chunks(Engine *e, const int32_t *rids, const int32_t *chunks,
                    int64_t n, int64_t W, double *wants, double *has,
                    double *sub, uint8_t *act, int32_t *filled_out,
                    uint64_t *versions_out) {
  std::lock_guard<std::mutex> lock(e->mu);
  for (int64_t i = 0; i < n; ++i) {
    double *w = wants + i * W;
    double *h = has + i * W;
    double *s = sub + i * W;
    uint8_t *a = act + i * W;
    std::fill(w, w + W, 0.0);
    std::fill(h, h + W, 0.0);
    std::fill(s, s + W, 0.0);
    std::fill(a, a + W, uint8_t{0});
    filled_out[i] = 0;
    versions_out[i] = 0;
    if (!valid_rid(e, rids[i]) || chunks[i] < 0) continue;
    const ResourceStore &r = e->resources[rids[i]];
    const int64_t base = static_cast<int64_t>(chunks[i]) * W;
    const int64_t size = static_cast<int64_t>(r.leases.size());
    const int64_t filled = std::min<int64_t>(W, size - base);
    for (int64_t j = 0; j < filled; ++j) {
      const Lease &l = r.leases[base + j];
      w[j] = l.wants;
      h[j] = l.has;
      s[j] = l.subclients;
      a[j] = 1;
    }
    if (filled > 0) filled_out[i] = static_cast<int32_t>(filled);
    if (chunks[i] < static_cast<int64_t>(r.chunk_version.size()))
      versions_out[i] = r.chunk_version[chunks[i]];
  }
}

// Read the current membership versions of n chunks. The wide solver
// reads these AFTER draining slot dirt and BEFORE packing: any
// membership change landing after the read bumps the version (so the
// in-flight apply skips) and re-marks its slots (so the next tick
// re-delivers) — expected versions can lag the device state but never
// lead it, which makes a mismatch always the safe direction.
void dm_chunk_versions(Engine *e, const int32_t *rids,
                       const int32_t *chunks, int64_t n,
                       uint64_t *versions_out) {
  std::lock_guard<std::mutex> lock(e->mu);
  for (int64_t i = 0; i < n; ++i) {
    versions_out[i] = 0;
    if (!valid_rid(e, rids[i]) || chunks[i] < 0) continue;
    const ResourceStore &r = e->resources[rids[i]];
    if (chunks[i] < static_cast<int64_t>(r.chunk_version.size()))
      versions_out[i] = r.chunk_version[chunks[i]];
  }
}

// Chunk-granular grant write-back: row i of grants [n, W] applies to
// slots [chunks[i]*W, ...) of rids[i] IF the chunk's membership version
// still equals expected_version[i] (a stale chunk re-delivers after its
// change re-dirties it). Grants only — expiry/refresh stay
// client-driven; keep_has[i] != 0 preserves has (learning replay).
// Returns chunks applied.
int64_t dm_apply_chunks(Engine *e, const int32_t *rids,
                        const int32_t *chunks, int64_t n, int64_t W,
                        const double *grants, const uint8_t *keep_has,
                        const uint64_t *expected_version) {
  std::lock_guard<std::mutex> lock(e->mu);
  int64_t applied = 0;
  for (int64_t i = 0; i < n; ++i) {
    if (!valid_rid(e, rids[i]) || chunks[i] < 0) continue;
    ResourceStore &r = e->resources[rids[i]];
    const uint64_t v =
        chunks[i] < static_cast<int64_t>(r.chunk_version.size())
            ? r.chunk_version[chunks[i]]
            : 0;
    if (v != expected_version[i]) continue;
    if (!keep_has[i]) {
      const double *g = grants + i * W;
      const int64_t base = static_cast<int64_t>(chunks[i]) * W;
      const int64_t filled = std::min<int64_t>(
          W, static_cast<int64_t>(r.leases.size()) - base);
      for (int64_t j = 0; j < filled; ++j) {
        Lease &l = r.leases[base + j];
        r.sum_has += g[j] - l.has;
        l.has = g[j];
      }
    }
    ++applied;
  }
  return applied;
}

// out[0]=sum_has out[1]=sum_wants out[2]=subclient count out[3]=#leases
void dm_sums(Engine *e, int32_t rid, double *out) {
  std::lock_guard<std::mutex> lock(e->mu);
  if (!valid_rid(e, rid)) {
    out[0] = out[1] = out[2] = out[3] = 0.0;
    return;
  }
  const ResourceStore &r = e->resources[rid];
  out[0] = r.sum_has;
  out[1] = r.sum_wants;
  out[2] = static_cast<double>(r.count);
  out[3] = static_cast<double>(r.leases.size());
}

// Fetch one lease: out = {expiry, refresh_interval, has, wants,
// subclients, priority}. Returns 1 if present, else 0 (out untouched).
int32_t dm_get(Engine *e, int32_t rid, int64_t cid, double *out) {
  std::lock_guard<std::mutex> lock(e->mu);
  if (!valid_rid(e, rid)) return 0;
  const ResourceStore &r = e->resources[rid];
  auto it = r.index.find(cid);
  if (it == r.index.end()) return 0;
  const Lease &l = r.leases[it->second];
  out[0] = l.expiry;
  out[1] = l.refresh_interval;
  out[2] = l.has;
  out[3] = l.wants;
  out[4] = l.subclients;
  out[5] = static_cast<double>(l.priority);
  return 1;
}

// Request-path combo read: one locked call returns the client's lease
// AND the resource aggregates — the scalar per-request algorithms need
// both, and paying a ctypes crossing per field read dominated the
// immediate-mode serving path. out = {found, expiry, refresh_interval,
// has, wants, subclients, priority, sum_has, sum_wants, count}; absent
// clients report found=0 with zeroed lease fields (aggregates still
// filled).
void dm_peek(Engine *e, int32_t rid, int64_t cid, double *out) {
  std::lock_guard<std::mutex> lock(e->mu);
  std::fill(out, out + 10, 0.0);
  if (!valid_rid(e, rid)) return;
  const ResourceStore &r = e->resources[rid];
  out[7] = r.sum_has;
  out[8] = r.sum_wants;
  out[9] = static_cast<double>(r.count);
  auto it = r.index.find(cid);
  if (it == r.index.end()) return;
  const Lease &l = r.leases[it->second];
  out[0] = 1.0;
  out[1] = l.expiry;
  out[2] = l.refresh_interval;
  out[3] = l.has;
  out[4] = l.wants;
  out[5] = static_cast<double>(l.subclients);
  out[6] = static_cast<double>(l.priority);
}

// Batch-mode request path in ONE locked call: if the client holds a
// lease, record its new demand (wants/subclients/priority) and stamp a
// fresh expiry while PRESERVING the granted has — a batch server
// serves the last tick's solved grant and only notes demand; the tick
// recomputes (server.py _decide). Writes the served has to *has_out
// and returns 1; returns 0 when the client is unknown (the caller
// falls to the decide path, which admits new clients).
int32_t dm_refresh_grant(Engine *e, int32_t rid, int64_t cid,
                         double expiry, double refresh_interval,
                         double wants, int32_t subclients,
                         int64_t priority, double *has_out) {
  std::lock_guard<std::mutex> lock(e->mu);
  if (!valid_rid(e, rid)) return 0;
  ResourceStore &r = e->resources[rid];
  auto it = r.index.find(cid);
  if (it == r.index.end()) return 0;
  const double has = r.leases[it->second].has;
  upsert(e, rid, cid,
         Lease{expiry, refresh_interval, has, wants, subclients,
               priority});
  *has_out = has;
  return 1;
}

// Whole per-request decide in ONE locked call: expiry sweep, the
// scalar algorithm, and the lease upsert — the immediate-mode serving
// path (reference go/server/doorman/server.go:732-817) without a ctypes
// crossing per primitive store read. The arithmetic REPLICATES
// doorman_tpu/algorithms/scalar.py expression-for-expression (including
// association order), so grants are bit-identical to the Python oracle;
// the parity test asserts exact equality. `kind`: 0 NO_ALGORITHM,
// 1 STATIC, 2 PROPORTIONAL_SHARE, 3 FAIR_SHARE, 4 PROPORTIONAL_TOPUP,
// 6 LEARN (NOT 5 — that is AlgoKind.PRIORITY_BANDS, which must never
// route here; unknown kinds return 0 and the caller stays on the
// Python path). out = {gets, confused(FAIR_SHARE has-mismatch),
// old_has}.
// Returns 1 (always decides; unknown kinds fall back Python-side and
// never reach here).
int32_t dm_decide(Engine *e, int32_t rid, int64_t cid, int32_t kind,
                  double capacity, double now, double lease_length,
                  double refresh_interval, double has, double wants,
                  int32_t subclients, int64_t priority, double *out) {
  std::lock_guard<std::mutex> lock(e->mu);
  out[0] = out[1] = out[2] = 0.0;
  if (!valid_rid(e, rid)) return 0;
  ResourceStore &r = e->resources[rid];
  if (sweep_resource(e, rid, r, now)) mark_dirty(e, rid);

  auto it = r.index.find(cid);
  const bool found = it != r.index.end();
  const Lease old =
      found ? r.leases[it->second]
            : Lease{0.0, 0.0, 0.0, 0.0, 0, 0};
  out[2] = old.has;

  double gets = 0.0;
  switch (kind) {
    case 0:  // NO_ALGORITHM: everyone gets what they want.
      gets = wants;
      break;
    case 1:  // STATIC: per-client configured cap.
      gets = std::min(capacity, wants);
      break;
    case 6:  // LEARN: replay the client's reported grant.
      gets = has;
      break;
    case 2: {  // PROPORTIONAL_SHARE (scalar.py:92-104 order).
      const double all_wants = r.sum_wants - old.wants + wants;
      const double sum_leases = r.sum_has - old.has;
      const double free_cap = std::max(capacity - sum_leases, 0.0);
      if (all_wants < capacity) {
        gets = std::min(wants, free_cap);
      } else {
        gets = std::min(wants * (capacity / all_wants), free_cap);
      }
      break;
    }
    case 4: {  // PROPORTIONAL_TOPUP (scalar.py:116-158 order).
      double count = static_cast<double>(r.count);
      if (!found) count += subclients;
      const double equal_share = capacity / count;
      const double equal_share_client = equal_share * subclients;
      const double unused = capacity - r.sum_has + old.has;
      if (r.sum_wants <= capacity || wants <= equal_share_client) {
        gets = std::min(wants, unused);
        break;
      }
      double extra_capacity = 0.0;
      double extra_need = 0.0;
      for (size_t j = 0; j < r.leases.size(); ++j) {
        double w, s;
        if (r.clients[j] == cid) {
          w = wants;
          s = subclients;
        } else {
          w = r.leases[j].wants;
          s = r.leases[j].subclients;
        }
        const double share = equal_share * s;
        if (w < share) {
          extra_capacity += share - w;
        } else {
          extra_need += w - share;
        }
      }
      // An absent requester contributes nothing to the pools — the
      // Python loop iterates store.items(), substituting the fresh
      // request only for a slot the requester already holds.
      gets = equal_share_client +
             (wants - equal_share_client) * (extra_capacity / extra_need);
      gets = std::min(gets, unused);
      break;
    }
    case 3: {  // FAIR_SHARE (scalar.py:170-226 order).
      if (has != old.has) out[1] = 1.0;  // caller logs "confused"
      const double count =
          static_cast<double>(r.count) - old.subclients + subclients;
      const double available = capacity - r.sum_has + old.has;
      const double equal_share = capacity / count;
      const double deserved = equal_share * subclients;
      if (wants <= deserved) {
        gets = std::min(wants, available);
        break;
      }
      double extra = 0.0;
      double want_extra = subclients;
      for (size_t j = 0; j < r.leases.size(); ++j) {
        if (r.clients[j] == cid) continue;
        const Lease &l = r.leases[j];
        const double their_deserved = l.subclients * equal_share;
        if (l.wants < their_deserved) {
          extra += their_deserved - l.wants;
        } else if (l.wants > their_deserved) {
          want_extra += l.subclients;
        }
      }
      const double deserved_extra = (extra / want_extra) * subclients;
      if (wants < deserved + deserved_extra) {
        gets = std::min(wants, available);
        break;
      }
      double extra_extra = 0.0;
      double want_extra_extra = subclients;
      for (size_t j = 0; j < r.leases.size(); ++j) {
        if (r.clients[j] == cid) continue;
        const Lease &l = r.leases[j];
        const double their_deserved = l.subclients * equal_share;
        if (!(l.wants > their_deserved)) continue;  // round-1 subset
        const double entitled = deserved_extra + deserved;
        if (l.wants < entitled) {
          extra_extra += entitled - l.wants;
        } else if (l.wants > entitled) {
          want_extra_extra += l.subclients;
        }
      }
      const double deserved_extra_extra =
          (extra_extra / want_extra_extra) * subclients;
      gets = std::min(deserved + deserved_extra + deserved_extra_extra,
                      available);
      break;
    }
    default:
      return 0;
  }

  upsert(e, rid, cid,
         Lease{now + lease_length, refresh_interval, gets, wants,
               subclients, priority});
  out[0] = gets;
  return 1;
}

// Dump one resource's leases (store order). Arrays must hold
// dm_sums(...)[3] entries; returns the number written.
int64_t dm_dump(Engine *e, int32_t rid, int64_t *cids, double *expiry,
                double *refresh, double *has, double *wants,
                int32_t *subclients, int64_t *priority, int64_t cap) {
  std::lock_guard<std::mutex> lock(e->mu);
  if (!valid_rid(e, rid)) return 0;
  const ResourceStore &r = e->resources[rid];
  const int64_t n =
      std::min<int64_t>(cap, static_cast<int64_t>(r.leases.size()));
  for (int64_t i = 0; i < n; ++i) {
    const Lease &l = r.leases[i];
    cids[i] = r.clients[i];
    expiry[i] = l.expiry;
    refresh[i] = l.refresh_interval;
    has[i] = l.has;
    wants[i] = l.wants;
    subclients[i] = l.subclients;
    priority[i] = l.priority;
  }
  return n;
}

// Largest per-resource lease count (the dense bucket width the
// resident solver would need).
int64_t dm_max_leases(Engine *e) {
  std::lock_guard<std::mutex> lock(e->mu);
  int64_t m = 0;
  for (const ResourceStore &r : e->resources)
    m = std::max<int64_t>(m, static_cast<int64_t>(r.leases.size()));
  return m;
}

int64_t dm_total_leases(Engine *e) {
  std::lock_guard<std::mutex> lock(e->mu);
  int64_t total = 0;
  for (const ResourceStore &r : e->resources)
    total += static_cast<int64_t>(r.leases.size());
  return total;
}

// Bulk snapshot pack: edges laid out resource-major following `order`
// (engine resource handles, e.g. the batch solver's spec order).
// ridx_out[i] is the POSITION in `order` (the solver's segment id), not
// the engine handle. Returns edges written (<= cap).
int64_t dm_pack(Engine *e, const int32_t *order, int32_t n_order,
                int32_t *ridx_out, int64_t *cid_out, double *wants_out,
                double *has_out, double *sub_out, int64_t *prio_out,
                int64_t cap) {
  std::lock_guard<std::mutex> lock(e->mu);
  int64_t w = 0;
  for (int32_t i = 0; i < n_order; ++i) {
    if (!valid_rid(e, order[i])) continue;
    const ResourceStore &r = e->resources[order[i]];
    const size_t n = r.leases.size();
    for (size_t j = 0; j < n; ++j) {
      if (w >= cap) return w;
      const Lease &l = r.leases[j];
      ridx_out[w] = i;
      cid_out[w] = r.clients[j];
      wants_out[w] = l.wants;
      has_out[w] = l.has;
      sub_out[w] = l.subclients;
      prio_out[w] = l.priority;
      ++w;
    }
  }
  return w;
}

// Bulk grant write-back after a solve: for each edge, if the client
// still holds a lease, set has=gets; everything else — expiry, refresh,
// wants, subclients, priority — keeps its CURRENT store value, so
// demand that changed while the solve was in flight is preserved and
// leases expire on the client's own refresh schedule (same grants-only
// semantics as dm_apply_dense). order[seg] < 0 skips that segment (its
// resource vanished mid-solve); keep_has[seg] != 0 leaves even has
// untouched (learning-mode resources replay the reported grant).
// applied_out[i] is 1 where the edge was written. Returns the number
// applied.
int64_t dm_apply(Engine *e, const int32_t *order, int32_t n_order,
                 const int32_t *ridx, const int64_t *cid,
                 const double *gets, int64_t n_edges,
                 const uint8_t *keep_has, uint8_t *applied_out) {
  std::lock_guard<std::mutex> lock(e->mu);
  int64_t applied = 0;
  for (int64_t i = 0; i < n_edges; ++i) {
    applied_out[i] = 0;
    const int32_t seg = ridx[i];
    if (seg < 0 || seg >= n_order || !valid_rid(e, order[seg])) continue;
    ResourceStore &r = e->resources[order[seg]];
    auto it = r.index.find(cid[i]);
    if (it == r.index.end()) continue;  // released mid-solve
    Lease &l = r.leases[it->second];
    if (!keep_has[seg]) {
      r.sum_has += gets[i] - l.has;
      l.has = gets[i];
    }
    applied_out[i] = 1;
    ++applied;
  }
  return applied;
}

}  // extern "C"
