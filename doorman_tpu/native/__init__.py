"""Native (C++) host-runtime components, bound over ctypes.

The TPU owns the allocation solve; the runtime around it — lease
bookkeeping on every request, the snapshot pack on every tick — is the
host-side hot path. `store.cc` implements that path as a single Engine
holding all of a server's resources; this module builds it on demand
(g++ is in the image; there is no pip/pybind11) and wraps it in
`NativeLeaseStore`, a drop-in for the Python `LeaseStore`.

Everything degrades gracefully: if the toolchain or the build is
unavailable, `native_available()` is False and callers stay on the
Python store.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import tempfile
import threading
import time
from pathlib import Path
from typing import Callable, Iterator, List, Tuple

import numpy as np

from doorman_tpu.core.lease import Lease, ZERO_LEASE
from doorman_tpu.core.store import ClientLeaseStatus, ResourceLeaseStatus

log = logging.getLogger(__name__)

_SRC = Path(__file__).resolve().parent / "store.cc"
_LIB = Path(__file__).resolve().parent / "_store.so"

_lock = threading.Lock()
_lib: "ctypes.CDLL | None" = None
_load_failed = False

_F64P = ctypes.POINTER(ctypes.c_double)
_I64P = ctypes.POINTER(ctypes.c_int64)
_I32P = ctypes.POINTER(ctypes.c_int32)


def _build() -> None:
    # Build into a temp file then rename: atomic under concurrent pytest
    # workers.
    fd, tmp = tempfile.mkstemp(
        suffix=".so", dir=str(_LIB.parent), prefix="_store_build_"
    )
    os.close(fd)
    try:
        subprocess.run(
            ["g++", "-O2", "-std=c++17", "-shared", "-fPIC",
             str(_SRC), "-o", tmp],
            check=True, capture_output=True, timeout=120,
        )
        os.replace(tmp, _LIB)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def _declare(lib: ctypes.CDLL) -> None:
    lib.dm_engine_new.restype = ctypes.c_void_p
    lib.dm_engine_free.argtypes = [ctypes.c_void_p]
    lib.dm_resource.restype = ctypes.c_int32
    lib.dm_resource.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.dm_client.restype = ctypes.c_int64
    lib.dm_client.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.dm_assign.restype = ctypes.c_int32
    lib.dm_assign.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, ctypes.c_int64, ctypes.c_double,
        ctypes.c_double, ctypes.c_double, ctypes.c_double, ctypes.c_int32,
        ctypes.c_int64,
    ]
    lib.dm_bulk_assign.restype = ctypes.c_int64
    lib.dm_bulk_assign.argtypes = [
        ctypes.c_void_p, _I32P, _I64P, _F64P, _F64P, _F64P, _F64P, _I32P,
        _I64P, ctypes.c_int64,
    ]
    lib.dm_regrant.restype = ctypes.c_int32
    lib.dm_regrant.argtypes = [ctypes.c_void_p, ctypes.c_int32,
                               ctypes.c_int64, ctypes.c_double]
    lib.dm_release.restype = ctypes.c_int32
    lib.dm_release.argtypes = [ctypes.c_void_p, ctypes.c_int32,
                               ctypes.c_int64]
    lib.dm_clean.restype = ctypes.c_int64
    lib.dm_clean.argtypes = [ctypes.c_void_p, ctypes.c_int32,
                             ctypes.c_double]
    lib.dm_sums.argtypes = [ctypes.c_void_p, ctypes.c_int32, _F64P]
    lib.dm_get.restype = ctypes.c_int32
    lib.dm_get.argtypes = [ctypes.c_void_p, ctypes.c_int32, ctypes.c_int64,
                           _F64P]
    lib.dm_dump.restype = ctypes.c_int64
    lib.dm_dump.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, _I64P, _F64P, _F64P, _F64P, _F64P,
        _I32P, _I64P, ctypes.c_int64,
    ]
    lib.dm_total_leases.restype = ctypes.c_int64
    lib.dm_total_leases.argtypes = [ctypes.c_void_p]
    lib.dm_max_leases.restype = ctypes.c_int64
    lib.dm_max_leases.argtypes = [ctypes.c_void_p]
    lib.dm_pack.restype = ctypes.c_int64
    lib.dm_pack.argtypes = [
        ctypes.c_void_p, _I32P, ctypes.c_int32, _I32P, _I64P, _F64P, _F64P,
        _F64P, _I64P, ctypes.c_int64,
    ]
    lib.dm_apply.restype = ctypes.c_int64
    lib.dm_apply.argtypes = [
        ctypes.c_void_p, _I32P, ctypes.c_int32, _I32P, _I64P, _F64P,
        ctypes.c_int64, ctypes.POINTER(ctypes.c_uint8),
        ctypes.POINTER(ctypes.c_uint8),
    ]
    u8p = ctypes.POINTER(ctypes.c_uint8)
    u64p = ctypes.POINTER(ctypes.c_uint64)
    lib.dm_clean_all.restype = ctypes.c_int64
    lib.dm_clean_all.argtypes = [ctypes.c_void_p, ctypes.c_double]
    lib.dm_drain_dirty2.restype = ctypes.c_int64
    lib.dm_drain_dirty2.argtypes = [ctypes.c_void_p, _I32P, u8p,
                                    ctypes.c_int64]
    lib.dm_pack_rows.argtypes = [
        ctypes.c_void_p, _I32P, ctypes.c_int64, ctypes.c_int64,
        _F64P, _F64P, _F64P, u8p, _I32P, u64p,
    ]
    lib.dm_apply_dense.restype = ctypes.c_int64
    lib.dm_apply_dense.argtypes = [
        ctypes.c_void_p, _I32P, ctypes.c_int64, ctypes.c_int64,
        _F64P, u8p, u64p,
    ]
    lib.dm_band_aggregates.restype = ctypes.c_int64
    lib.dm_band_aggregates.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, _I64P, _F64P, _I64P,
        ctypes.c_int64,
    ]
    lib.dm_bulk_refresh.restype = ctypes.c_int64
    lib.dm_bulk_refresh.argtypes = [
        ctypes.c_void_p, _I32P, _I64P, _F64P, _F64P, _F64P,
        ctypes.c_int64,
    ]
    lib.dm_chunk_config.argtypes = [
        ctypes.c_void_p, _I32P, ctypes.c_int64, ctypes.c_int64,
    ]
    lib.dm_drain_slots.restype = ctypes.c_int64
    lib.dm_drain_slots.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, _I64P, u8p, ctypes.c_int64,
    ]
    lib.dm_dirty_slot_rids.restype = ctypes.c_int64
    lib.dm_dirty_slot_rids.argtypes = [ctypes.c_void_p, _I32P,
                                       ctypes.c_int64]
    lib.dm_pack_slots.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, _I64P, ctypes.c_int64,
        _F64P, _F64P, _F64P, u8p,
    ]
    lib.dm_pack_chunks.argtypes = [
        ctypes.c_void_p, _I32P, _I32P, ctypes.c_int64, ctypes.c_int64,
        _F64P, _F64P, _F64P, u8p, _I32P, u64p,
    ]
    lib.dm_apply_chunks.restype = ctypes.c_int64
    lib.dm_apply_chunks.argtypes = [
        ctypes.c_void_p, _I32P, _I32P, ctypes.c_int64, ctypes.c_int64,
        _F64P, u8p, u64p,
    ]
    lib.dm_chunk_versions.argtypes = [
        ctypes.c_void_p, _I32P, _I32P, ctypes.c_int64, u64p,
    ]
    lib.dm_peek.argtypes = [ctypes.c_void_p, ctypes.c_int32,
                            ctypes.c_int64, _F64P]
    lib.dm_decide.restype = ctypes.c_int32
    lib.dm_decide.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, ctypes.c_int64, ctypes.c_int32,
        ctypes.c_double, ctypes.c_double, ctypes.c_double, ctypes.c_double,
        ctypes.c_double, ctypes.c_double, ctypes.c_int32, ctypes.c_int64,
        _F64P,
    ]
    lib.dm_refresh_grant.restype = ctypes.c_int32
    lib.dm_refresh_grant.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, ctypes.c_int64, ctypes.c_double,
        ctypes.c_double, ctypes.c_double, ctypes.c_int32, ctypes.c_int64,
        _F64P,
    ]


def _load() -> "ctypes.CDLL | None":
    global _lib, _load_failed
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        try:
            if (not _LIB.exists()
                    or _LIB.stat().st_mtime < _SRC.stat().st_mtime):
                _build()
            try:
                lib = ctypes.CDLL(str(_LIB))
            except OSError:
                # A stale or foreign-platform .so; rebuild once and retry.
                _build()
                lib = ctypes.CDLL(str(_LIB))
            _declare(lib)
            _lib = lib
        except Exception:
            log.exception("native store unavailable; using Python store")
            _load_failed = True
        return _lib


def native_available() -> bool:
    return _load() is not None


class StoreEngine:
    """One engine per server: every resource's leases in native memory.

    `store(resource_id)` hands out `NativeLeaseStore` views; `pack` dumps
    the whole engine as resource-major edge arrays for the batch solver.
    """

    def __init__(self, clock: Callable[[], float] = time.time):
        lib = _load()
        if lib is None:
            raise RuntimeError(
                "native store engine unavailable (g++ build failed?); "
                "check native_available() before constructing"
            )
        self._lib = lib
        self._ptr = ctypes.c_void_p(lib.dm_engine_new())
        self._clock = clock
        self._client_names: List[str] = []
        self._client_handles: dict[str, int] = {}

    def __del__(self):
        ptr, self._ptr = getattr(self, "_ptr", None), None
        if ptr and getattr(self, "_lib", None) is not None:
            self._lib.dm_engine_free(ptr)

    def client_handle(self, client_id: str) -> int:
        h = self._client_handles.get(client_id)
        if h is None:
            h = self._lib.dm_client(self._ptr, client_id.encode())
            self._client_handles[client_id] = h
            if h != len(self._client_names):
                # Cross-language invariant: the C side hands out handles
                # densely in registration order, which is what lets
                # client_name() index a plain list. Must survive python -O.
                raise RuntimeError(
                    f"native client handle {h} out of sync with name table "
                    f"size {len(self._client_names)}"
                )
            self._client_names.append(client_id)
        return h

    def client_name(self, handle: int) -> str:
        return self._client_names[handle]

    def store(self, resource_id: str) -> "NativeLeaseStore":
        rid = self._lib.dm_resource(self._ptr, resource_id.encode())
        return NativeLeaseStore(self, resource_id, rid)

    @property
    def total_leases(self) -> int:
        return self._lib.dm_total_leases(self._ptr)

    @property
    def max_leases(self) -> int:
        """Largest per-resource lease count (one O(R) C call)."""
        return self._lib.dm_max_leases(self._ptr)

    def pack(self, order: List["NativeLeaseStore"]) -> Tuple[
        np.ndarray, np.ndarray, np.ndarray, np.ndarray, np.ndarray,
        np.ndarray,
    ]:
        """Resource-major edge dump following `order`: returns
        (ridx, cid, wants, has, subclients, priority) with ridx the
        position of the edge's resource in `order` — the solver's
        segment id."""
        cap = self._lib.dm_total_leases(self._ptr)
        ridx = np.empty(cap, np.int32)
        cid = np.empty(cap, np.int64)
        wants = np.empty(cap, np.float64)
        has = np.empty(cap, np.float64)
        sub = np.empty(cap, np.float64)
        prio = np.empty(cap, np.int64)
        handles = np.asarray([s._rid for s in order], np.int32)
        n = self._lib.dm_pack(
            self._ptr,
            handles.ctypes.data_as(_I32P), len(order),
            ridx.ctypes.data_as(_I32P), cid.ctypes.data_as(_I64P),
            wants.ctypes.data_as(_F64P), has.ctypes.data_as(_F64P),
            sub.ctypes.data_as(_F64P), prio.ctypes.data_as(_I64P), cap,
        )
        return ridx[:n], cid[:n], wants[:n], has[:n], sub[:n], prio[:n]

    def bulk_assign(
        self,
        rids: np.ndarray,  # [n] engine resource handles per lease
        cids: np.ndarray,  # [n] client handles
        expiry: np.ndarray,  # [n] absolute expiry stamps
        refresh: np.ndarray,  # [n]
        has: np.ndarray,  # [n]
        wants: np.ndarray,  # [n]
        subclients: np.ndarray,  # [n]
        priority: "np.ndarray | None" = None,  # [n]
    ) -> int:
        """Bulk lease upsert in one C call (snapshot load / bench
        population); returns the number assigned."""
        n = len(rids)
        rids = np.ascontiguousarray(rids, np.int32)
        cids = np.ascontiguousarray(cids, np.int64)
        expiry = np.ascontiguousarray(expiry, np.float64)
        refresh = np.ascontiguousarray(refresh, np.float64)
        has = np.ascontiguousarray(has, np.float64)
        wants = np.ascontiguousarray(wants, np.float64)
        subclients = np.ascontiguousarray(subclients, np.int32)
        if priority is None:
            priority = np.zeros(n, np.int64)
        priority = np.ascontiguousarray(priority, np.int64)
        return int(
            self._lib.dm_bulk_assign(
                self._ptr,
                rids.ctypes.data_as(_I32P), cids.ctypes.data_as(_I64P),
                expiry.ctypes.data_as(_F64P), refresh.ctypes.data_as(_F64P),
                has.ctypes.data_as(_F64P), wants.ctypes.data_as(_F64P),
                subclients.ctypes.data_as(_I32P),
                priority.ctypes.data_as(_I64P), n,
            )
        )

    def bulk_refresh(
        self,
        rids: np.ndarray,  # [n] engine resource handles
        cids: np.ndarray,  # [n] client handles
        expiry: np.ndarray,  # [n]
        refresh: np.ndarray,  # [n]
        wants: np.ndarray,  # [n]
    ) -> int:
        """Bulk demand refresh preserving each lease's current
        has/subclients/priority (a client refresh's store effect);
        returns the number refreshed."""
        rids = np.ascontiguousarray(rids, np.int32)
        cids = np.ascontiguousarray(cids, np.int64)
        expiry = np.ascontiguousarray(expiry, np.float64)
        refresh = np.ascontiguousarray(refresh, np.float64)
        wants = np.ascontiguousarray(wants, np.float64)
        return int(
            self._lib.dm_bulk_refresh(
                self._ptr, rids.ctypes.data_as(_I32P),
                cids.ctypes.data_as(_I64P),
                expiry.ctypes.data_as(_F64P),
                refresh.ctypes.data_as(_F64P),
                wants.ctypes.data_as(_F64P), len(rids),
            )
        )

    def clean_all(self, now: "float | None" = None) -> int:
        """Engine-wide expiry sweep in one C call; returns removals."""
        if now is None:
            now = self._clock()
        return int(self._lib.dm_clean_all(self._ptr, now))

    def drain_dirty2(self) -> Tuple[np.ndarray, np.ndarray]:
        """Resources whose solver-visible inputs changed since the last
        drain (engine rids, int32), plus a parallel uint8 array flagging
        rows that changed beyond wants (membership / has / subclients /
        priority) — those need a full re-upload; unflagged rows changed
        only in wants and ship just the wants lane. Clears both flags."""
        u8p = ctypes.POINTER(ctypes.c_uint8)
        rid_chunks, full_chunks = [], []
        while True:
            buf = np.empty(4096, np.int32)
            full = np.empty(4096, np.uint8)
            n = int(
                self._lib.dm_drain_dirty2(
                    self._ptr, buf.ctypes.data_as(_I32P),
                    full.ctypes.data_as(u8p), len(buf)
                )
            )
            rid_chunks.append(buf[:n])
            full_chunks.append(full[:n])
            if n < len(buf):
                break
        if len(rid_chunks) > 1:
            return np.concatenate(rid_chunks), np.concatenate(full_chunks)
        return rid_chunks[0], full_chunks[0]

    def pack_rows(self, rids: np.ndarray, K: int):
        """Dense [n, K] row pack of the given resources: returns
        (wants, has, subclients, active, counts, versions). counts may
        exceed K — the caller detects bucket overflow."""
        rids = np.ascontiguousarray(rids, np.int32)
        n = len(rids)
        wants = np.empty((n, K), np.float64)
        has = np.empty((n, K), np.float64)
        sub = np.empty((n, K), np.float64)
        act = np.empty((n, K), np.uint8)
        counts = np.empty(n, np.int32)
        versions = np.empty(n, np.uint64)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        u64p = ctypes.POINTER(ctypes.c_uint64)
        self._lib.dm_pack_rows(
            self._ptr, rids.ctypes.data_as(_I32P), n, K,
            wants.ctypes.data_as(_F64P), has.ctypes.data_as(_F64P),
            sub.ctypes.data_as(_F64P), act.ctypes.data_as(u8p),
            counts.ctypes.data_as(_I32P), versions.ctypes.data_as(u64p),
        )
        return wants, has, sub, act, counts, versions

    def apply_dense(
        self,
        rids: np.ndarray,  # [n] engine resource handles
        grants: np.ndarray,  # [n, K] in upload-time slot order
        keep_has: np.ndarray,  # [n] uint8
        expected_versions: np.ndarray,  # [n] uint64
    ) -> int:
        """Dense grant write-back (grants ONLY — expiry/refresh are
        client-driven, see dm_apply_dense); rows whose membership epoch
        moved since upload are skipped (they re-solve next tick).
        Returns the number of rows applied."""
        rids = np.ascontiguousarray(rids, np.int32)
        grants = np.ascontiguousarray(grants, np.float64)
        keep_has = np.ascontiguousarray(keep_has, np.uint8)
        expected_versions = np.ascontiguousarray(
            expected_versions, np.uint64
        )
        u8p = ctypes.POINTER(ctypes.c_uint8)
        u64p = ctypes.POINTER(ctypes.c_uint64)
        return int(
            self._lib.dm_apply_dense(
                self._ptr, rids.ctypes.data_as(_I32P), len(rids),
                grants.shape[1], grants.ctypes.data_as(_F64P),
                keep_has.ctypes.data_as(u8p),
                expected_versions.ctypes.data_as(u64p),
            )
        )

    # -- wide-resource (chunked) tracking -----------------------------

    def chunk_config(self, rids: np.ndarray, W: int) -> None:
        """Install the chunk-tracked resource set (width W slots per
        device row). Clears all prior chunk state; the caller repacks
        every tracked chunk right after (rebuild)."""
        rids = np.ascontiguousarray(rids, np.int32)
        self._lib.dm_chunk_config(
            self._ptr, rids.ctypes.data_as(_I32P), len(rids), W
        )

    def dirty_slot_rids(self) -> np.ndarray:
        """Tracked rids that currently have dirty slots. The C call is a
        non-consuming COPY (unlike drain_slots), so a full buffer means
        retry bigger, not page."""
        cap = 1024
        while True:
            buf = np.empty(cap, np.int32)
            n = int(self._lib.dm_dirty_slot_rids(
                self._ptr, buf.ctypes.data_as(_I32P), cap
            ))
            if n < cap:
                return buf[:n].copy()
            cap *= 2

    def drain_slots(self, rid: int) -> Tuple[np.ndarray, np.ndarray]:
        """One tracked resource's dirty slots since the last drain:
        (slots int64, level uint8 — 1 wants-only, 2 full). Clears them."""
        u8p = ctypes.POINTER(ctypes.c_uint8)
        slot_chunks, lvl_chunks = [], []
        while True:
            slots = np.empty(65536, np.int64)
            lvl = np.empty(65536, np.uint8)
            n = int(self._lib.dm_drain_slots(
                self._ptr, rid, slots.ctypes.data_as(_I64P),
                lvl.ctypes.data_as(u8p), len(slots)
            ))
            slot_chunks.append(slots[:n])
            lvl_chunks.append(lvl[:n])
            if n < len(slots):
                break
        if len(slot_chunks) > 1:
            return np.concatenate(slot_chunks), np.concatenate(lvl_chunks)
        return slot_chunks[0], lvl_chunks[0]

    def pack_slots(self, rid: int, slots: np.ndarray):
        """Gather the given slots' (wants, has, subclients, active);
        slots beyond the lease count read as inactive zeros."""
        slots = np.ascontiguousarray(slots, np.int64)
        n = len(slots)
        wants = np.empty(n, np.float64)
        has = np.empty(n, np.float64)
        sub = np.empty(n, np.float64)
        act = np.empty(n, np.uint8)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        self._lib.dm_pack_slots(
            self._ptr, rid, slots.ctypes.data_as(_I64P), n,
            wants.ctypes.data_as(_F64P), has.ctypes.data_as(_F64P),
            sub.ctypes.data_as(_F64P), act.ctypes.data_as(u8p),
        )
        return wants, has, sub, act

    def pack_chunks(self, rids: np.ndarray, chunks: np.ndarray, W: int):
        """Pack n chunks as [n, W] rows: returns (wants, has, sub,
        active, filled, versions) with versions the per-chunk membership
        epochs at pack time."""
        rids = np.ascontiguousarray(rids, np.int32)
        chunks = np.ascontiguousarray(chunks, np.int32)
        n = len(rids)
        wants = np.empty((n, W), np.float64)
        has = np.empty((n, W), np.float64)
        sub = np.empty((n, W), np.float64)
        act = np.empty((n, W), np.uint8)
        filled = np.empty(n, np.int32)
        versions = np.empty(n, np.uint64)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        u64p = ctypes.POINTER(ctypes.c_uint64)
        self._lib.dm_pack_chunks(
            self._ptr, rids.ctypes.data_as(_I32P),
            chunks.ctypes.data_as(_I32P), n, W,
            wants.ctypes.data_as(_F64P), has.ctypes.data_as(_F64P),
            sub.ctypes.data_as(_F64P), act.ctypes.data_as(u8p),
            filled.ctypes.data_as(_I32P), versions.ctypes.data_as(u64p),
        )
        return wants, has, sub, act, filled, versions

    def chunk_versions(
        self, rids: np.ndarray, chunks: np.ndarray
    ) -> np.ndarray:
        """Current membership versions of the given chunks. Read AFTER
        a slot drain and BEFORE the pack (see dm_chunk_versions for why
        that ordering keeps apply mismatches in the safe direction)."""
        rids = np.ascontiguousarray(rids, np.int32)
        chunks = np.ascontiguousarray(chunks, np.int32)
        out = np.empty(len(rids), np.uint64)
        u64p = ctypes.POINTER(ctypes.c_uint64)
        self._lib.dm_chunk_versions(
            self._ptr, rids.ctypes.data_as(_I32P),
            chunks.ctypes.data_as(_I32P), len(rids),
            out.ctypes.data_as(u64p),
        )
        return out

    def apply_chunks(
        self,
        rids: np.ndarray,  # [n]
        chunks: np.ndarray,  # [n]
        grants: np.ndarray,  # [n, W] in upload-time slot order
        keep_has: np.ndarray,  # [n] uint8
        expected_versions: np.ndarray,  # [n] uint64
    ) -> int:
        """Chunk-granular grant write-back (grants only; see
        dm_apply_chunks); chunks whose membership version moved since
        upload are skipped. Returns chunks applied."""
        rids = np.ascontiguousarray(rids, np.int32)
        chunks = np.ascontiguousarray(chunks, np.int32)
        grants = np.ascontiguousarray(grants, np.float64)
        keep_has = np.ascontiguousarray(keep_has, np.uint8)
        expected_versions = np.ascontiguousarray(
            expected_versions, np.uint64
        )
        u8p = ctypes.POINTER(ctypes.c_uint8)
        u64p = ctypes.POINTER(ctypes.c_uint64)
        return int(
            self._lib.dm_apply_chunks(
                self._ptr, rids.ctypes.data_as(_I32P),
                chunks.ctypes.data_as(_I32P), len(rids),
                grants.shape[1], grants.ctypes.data_as(_F64P),
                keep_has.ctypes.data_as(u8p),
                expected_versions.ctypes.data_as(u64p),
            )
        )

    def apply(
        self,
        order_rids: np.ndarray,  # [n_seg] engine rids; -1 skips a segment
        ridx: np.ndarray,  # [E] segment per edge
        cid: np.ndarray,  # [E]
        gets: np.ndarray,  # [E]
        keep_has: "np.ndarray | None" = None,  # [n_seg] bool
    ) -> np.ndarray:
        """Bulk grant write-back (grants ONLY — expiry/refresh are
        client-driven, see dm_apply); returns a bool mask of edges
        applied (False: client released or resource gone mid-solve).
        Segments flagged in keep_has leave has untouched (learning)."""
        order_rids = np.ascontiguousarray(order_rids, np.int32)
        ridx = np.ascontiguousarray(ridx, np.int32)
        cid = np.ascontiguousarray(cid, np.int64)
        gets = np.ascontiguousarray(gets, np.float64)
        if keep_has is None:
            keep_has = np.zeros(len(order_rids), np.uint8)
        keep_has = np.ascontiguousarray(keep_has, np.uint8)
        applied = np.zeros(len(ridx), np.uint8)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        self._lib.dm_apply(
            self._ptr,
            order_rids.ctypes.data_as(_I32P), len(order_rids),
            ridx.ctypes.data_as(_I32P), cid.ctypes.data_as(_I64P),
            gets.ctypes.data_as(_F64P), len(ridx),
            keep_has.ctypes.data_as(u8p),
            applied.ctypes.data_as(u8p),
        )
        return applied.astype(bool)


class NativeLeaseStore:
    """Drop-in for core.store.LeaseStore, backed by a StoreEngine.

    Same interface and semantics (cites store.py; ultimately reference
    store.go:68-213); construct via StoreEngine.store().
    """

    def __init__(self, engine: StoreEngine, resource_id: str, rid: int):
        self.id = resource_id
        self._engine = engine
        self._lib = engine._lib
        self._ptr = engine._ptr
        self._rid = rid
        self._clock = engine._clock
        # Request-path scratch with the ctypes pointers prebuilt ONCE:
        # numpy's data_as() + ctypes.cast() cost ~5us per call — more
        # than the C call itself. ONLY the request paths (decide_fast /
        # peek / refresh_grant) may use shared scratch: they run
        # exclusively on the event loop (RPC handlers and the
        # single-threaded sim). Every other accessor allocates per
        # call, because the tick executor thread reads stores
        # concurrently with handlers (len/sums in the solvers' rebuild
        # checks, get in grant-map rebuilds) and a shared buffer would
        # tear.
        self._peek_buf = np.empty(10, np.float64)
        self._peek_ptr = self._peek_buf.ctypes.data_as(_F64P)

    def _sums(self) -> np.ndarray:
        out = np.empty(4, np.float64)
        self._lib.dm_sums(self._ptr, self._rid, out.ctypes.data_as(_F64P))
        return out

    def __len__(self) -> int:
        return int(self._sums()[3])

    @property
    def count(self) -> int:
        return int(self._sums()[2])

    @property
    def sum_has(self) -> float:
        return float(self._sums()[0])

    @property
    def sum_wants(self) -> float:
        return float(self._sums()[1])

    def get(self, client: str) -> Lease:
        # Per-call scratch: get() is also reached from the tick
        # executor (grant-map rebuilds), concurrent with handlers.
        out = np.empty(6, np.float64)
        ok = self._lib.dm_get(
            self._ptr, self._rid, self._engine.client_handle(client),
            out.ctypes.data_as(_F64P),
        )
        if not ok:
            return ZERO_LEASE
        e, r, h, w, s, p = out
        return Lease(expiry=e, refresh_interval=r, has=h, wants=w,
                     subclients=int(s), priority=int(p))

    def peek(self, client: str):
        """(found, lease, sum_has, sum_wants, count) in ONE locked C
        call — the scalar algorithms' whole read set (see
        algorithms.scalar._peek); absent clients report (False,
        ZERO_LEASE, ...) with the aggregates still filled."""
        self._lib.dm_peek(
            self._ptr, self._rid, self._engine.client_handle(client),
            self._peek_ptr,
        )
        out = self._peek_buf
        if out[0] == 0.0:
            return False, ZERO_LEASE, out[7], out[8], int(out[9])
        lease = Lease(
            expiry=out[1], refresh_interval=out[2], has=out[3],
            wants=out[4], subclients=int(out[5]), priority=int(out[6]),
        )
        return True, lease, out[7], out[8], int(out[9])

    # dm_decide's LEARN code; 0-4 are AlgoKind lane values (5 is
    # PRIORITY_BANDS, which never routes to C).
    DECIDE_LEARN = 6
    _DECIDE_KINDS = frozenset((0, 1, 2, 3, 4, 6))

    def decide_fast(
        self,
        kind: int,
        capacity: float,
        lease_length: float,
        refresh_interval: float,
        has: float,
        wants: float,
        subclients: int,
        priority: int,
        client: str,
    ):
        """The whole immediate-mode decide (sweep + algorithm + upsert)
        in one locked C call; grants are bit-identical to the scalar
        Python oracle (see dm_decide). Returns (Lease, confused,
        old_has), or None for kinds the C side does not carry (the
        caller then runs the Python algorithm)."""
        if kind not in self._DECIDE_KINDS:
            return None
        now = self._clock()
        ok = self._lib.dm_decide(
            self._ptr, self._rid, self._engine.client_handle(client),
            kind, capacity, now, lease_length, refresh_interval,
            has, wants, subclients, priority, self._peek_ptr,
        )
        if not ok:
            return None
        out = self._peek_buf
        lease = Lease(
            expiry=now + lease_length, refresh_interval=refresh_interval,
            has=float(out[0]), wants=wants, subclients=subclients,
            priority=priority,
        )
        return lease, out[1] != 0.0, float(out[2])

    def refresh_grant(
        self,
        client: str,
        lease_length: float,
        refresh_interval: float,
        wants: float,
        subclients: int,
        priority: int,
    ) -> "Lease | None":
        """Batch-mode request path in one locked C call: record new
        demand + fresh expiry, PRESERVE the granted has (the tick
        recomputes; see dm_refresh_grant). Returns the refreshed lease,
        or None when the client holds no lease (the caller then runs
        the decide path, which admits new clients)."""
        expiry = self._clock() + lease_length
        ok = self._lib.dm_refresh_grant(
            self._ptr, self._rid, self._engine.client_handle(client),
            expiry, refresh_interval, wants, subclients, priority,
            self._peek_ptr,  # event-loop-only scratch, like decide_fast
        )
        if not ok:
            return None
        return Lease(
            expiry=expiry, refresh_interval=refresh_interval,
            has=float(self._peek_buf[0]), wants=wants,
            subclients=subclients, priority=priority,
        )

    def has_client(self, client: str) -> bool:
        out = np.empty(6, np.float64)
        return bool(self._lib.dm_get(
            self._ptr, self._rid, self._engine.client_handle(client),
            out.ctypes.data_as(_F64P),
        ))

    def subclients(self, client: str) -> int:
        return self.get(client).subclients

    def assign(
        self,
        client: str,
        lease_length: float,
        refresh_interval: float,
        has: float,
        wants: float,
        subclients: int,
        priority: int = 0,
    ) -> Lease:
        expiry = self._clock() + lease_length
        self._lib.dm_assign(
            self._ptr, self._rid, self._engine.client_handle(client),
            expiry, refresh_interval, has, wants, subclients, priority,
        )
        return Lease(expiry=expiry, refresh_interval=refresh_interval,
                     has=has, wants=wants, subclients=subclients,
                     priority=priority)

    def bulk_assign(
        self,
        clients,
        lease_length: float,
        refresh_interval: float,
        has,
        wants,
        subclients=None,
        priority=None,
    ) -> None:
        """Same contract as core.store.LeaseStore.bulk_assign — an
        assign() per row in input order (dm_bulk_assign runs the same
        per-row upsert, so the running-aggregate accumulation order is
        identical) — in one C call after interning the client names."""
        handles = np.fromiter(
            (self._engine.client_handle(c) for c in clients),
            np.int64, count=len(clients),
        )
        self.bulk_assign_handles(
            handles, lease_length, refresh_interval, has, wants,
            subclients, priority,
        )

    def bulk_assign_handles(
        self,
        cid_handles,
        lease_length: float,
        refresh_interval: float,
        has,
        wants,
        subclients=None,
        priority=None,
    ) -> None:
        """bulk_assign for callers that already hold engine client
        handles (the vector population caches them per server), so a
        steady-state grouped commit is one C call with zero per-row
        Python work."""
        n = len(cid_handles)
        self._engine.bulk_assign(
            np.full(n, self._rid, np.int32),
            np.ascontiguousarray(cid_handles, np.int64),
            np.full(n, self._clock() + lease_length, np.float64),
            np.full(n, refresh_interval, np.float64),
            has,
            wants,
            np.ones(n, np.int32) if subclients is None else subclients,
            priority,
        )

    def regrant(self, client: str, has: float) -> None:
        """Update only the granted capacity of an existing lease (see
        core.store.LeaseStore.regrant); expiry/refresh stay put and the
        row is NOT dirtied — a delivery write-back is the solver's own
        output, so it must not trigger a re-upload next tick."""
        self._lib.dm_regrant(
            self._ptr, self._rid, self._engine.client_handle(client), has
        )

    def release(self, client: str) -> None:
        self._lib.dm_release(
            self._ptr, self._rid, self._engine.client_handle(client)
        )

    def clean(self) -> int:
        return self._lib.dm_clean(self._ptr, self._rid, self._clock())

    def _dump(self):
        n = len(self)
        cids = np.empty(n, np.int64)
        expiry = np.empty(n, np.float64)
        refresh = np.empty(n, np.float64)
        has = np.empty(n, np.float64)
        wants = np.empty(n, np.float64)
        sub = np.empty(n, np.int32)
        prio = np.empty(n, np.int64)
        n = self._lib.dm_dump(
            self._ptr, self._rid, cids.ctypes.data_as(_I64P),
            expiry.ctypes.data_as(_F64P), refresh.ctypes.data_as(_F64P),
            has.ctypes.data_as(_F64P), wants.ctypes.data_as(_F64P),
            sub.ctypes.data_as(_I32P), prio.ctypes.data_as(_I64P), n,
        )
        return (cids[:n], expiry[:n], refresh[:n], has[:n], wants[:n],
                sub[:n], prio[:n])

    def restore(self, client: str, lease: Lease) -> None:
        """Insert a lease verbatim (absolute expiry preserved) — the
        persistence restore path; see core.store.LeaseStore.restore.
        Bulk restores go through StoreEngine.bulk_assign instead."""
        self._lib.dm_assign(
            self._ptr, self._rid, self._engine.client_handle(client),
            lease.expiry, lease.refresh_interval, lease.has, lease.wants,
            lease.subclients, lease.priority,
        )

    def dump_rows(self) -> "list[tuple[str, float, float, float, float, int, int]]":
        """Drain API for snapshotting (see core.store.LeaseStore
        .dump_rows): one bulk C call, then name resolution through the
        engine's interning table."""
        cids, expiry, refresh, has, wants, sub, prio = self._dump()
        name = self._engine.client_name
        return [
            (name(int(cids[i])), float(expiry[i]), float(refresh[i]),
             float(has[i]), float(wants[i]), int(sub[i]), int(prio[i]))
            for i in range(len(cids))
        ]

    def items(self) -> Iterator[Tuple[str, Lease]]:
        cids, expiry, refresh, has, wants, sub, prio = self._dump()
        name = self._engine.client_name
        for i in range(len(cids)):
            yield name(int(cids[i])), Lease(
                expiry=float(expiry[i]),
                refresh_interval=float(refresh[i]),
                has=float(has[i]),
                wants=float(wants[i]),
                subclients=int(sub[i]),
                priority=int(prio[i]),
            )

    def map(self, fn: Callable[[str, Lease], None]) -> None:
        for client, lease in self.items():
            fn(client, lease)

    def band_aggregates(self) -> "list[tuple[int, float, int]]":
        """(priority, wants-sum, subclient-count) per distinct priority,
        ascending — one C call, no per-lease Python objects (the
        intermediate server's upstream pack at 1M leases must not walk
        the store on the event loop)."""
        cap = max(len(self), 1)
        prio = np.empty(cap, np.int64)
        wants = np.empty(cap, np.float64)
        num = np.empty(cap, np.int64)
        n = self._lib.dm_band_aggregates(
            self._ptr, self._rid, prio.ctypes.data_as(_I64P),
            wants.ctypes.data_as(_F64P), num.ctypes.data_as(_I64P), cap,
        )
        return [
            (int(prio[i]), float(wants[i]), int(num[i])) for i in range(n)
        ]

    def lease_status(self) -> ResourceLeaseStatus:
        sums = self._sums()
        return ResourceLeaseStatus(
            id=self.id,
            sum_has=float(sums[0]),
            sum_wants=float(sums[1]),
            leases=[
                ClientLeaseStatus(client_id=c, lease=l)
                for c, l in self.items()
            ],
        )
