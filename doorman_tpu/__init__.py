"""doorman-tpu: a TPU-native framework for global distributed client-side
rate limiting.

Clients of a shared resource cooperatively obtain time-bounded capacity
leases from master-elected servers. Where the reference system
(/root/reference, Go) runs its apportionment algorithms per request —
O(clients) to O(clients^2) per call — this framework recasts each refresh
tick as ONE batched allocation solve in JAX/XLA: the master's
(client x resource) wants table is snapshotted into device arrays and all
resources are solved at once via vmapped proportional-share and
water-filling fair-share kernels, sharded over a device mesh for scale.

Package layout:
    proto/        wire schema (proto3) + hand-wired gRPC service
    algorithms/   scalar oracle implementations (parity reference)
    solver/       batched JAX kernels + tick-level batch solver
    parallel/     mesh + shard_map sharded solves (client axis, 2-level tree)
    core/         lease store, resource registry, snapshots
    server/       the capacity server (4 RPCs), config, election
    persist/      durable lease-state snapshots + journal; warm takeover
    client/       master-aware connection + refresh-loop client
    ratelimiter/  QPS + adaptive rate limiters
    metrics/      prometheus + /debug/status + /debug/resources
    sim/          discrete-event simulation harness (scenarios 1-7)
    cli/          doorman_server / doorman_client / doorman_shell
    utils/        backoff, flagenv
"""

__version__ = "0.1.0"
