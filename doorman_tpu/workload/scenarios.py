"""The named scenario library: one factory per scenario, an SLO-gated
verdict per run.

Each factory takes ``(scale, seed, ticks)`` and returns a WorkloadSpec
— `scale` multiplies the client population AND the configured capacity
together, so satisfaction targets are scale-invariant and the same
scenario smoke-tests in CI at scale 0.2 and soaks locally at scale 50.
The factory's docstring first line is the one-liner `--list-scenarios`
prints (the same convention sim.scenarios uses).

``flash_crowd_predictive`` is the head-to-head: it runs the SAME spec
twice — once with the seasonal forecaster feeding the AIMD controller,
once purely reactive — and emits a standing pair verdict requiring the
predictive run's stressed top-band satisfaction to be at least the
reactive run's. The flash crowd repeats on the forecaster's period, so
from the second cycle on the forecast leads the spike by one tick and
the controller multiplies down BEFORE the crowd lands instead of one
window after.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

from doorman_tpu.obs import slo as slo_mod
from doorman_tpu.workload.harness import WorkloadRunner
from doorman_tpu.workload.spec import GeneratorSpec, WorkloadSpec

__all__ = ["SCENARIOS", "run_scenario", "scenario_lines"]

G = GeneratorSpec.make


def _pop(scale: float, n: int) -> int:
    return max(1, int(round(n * scale)))


def diurnal(scale: float = 1.0, seed: int = 0,
            ticks: Optional[int] = None) -> WorkloadSpec:
    """Day/night arrival wave over a mixed-band population."""
    ticks = ticks or 48
    cap = 400.0 * scale
    return WorkloadSpec.make(
        "diurnal", ticks, seed=seed, capacity=cap,
        algorithm="PRIORITY_BANDS",
        base_clients=[(2, 20.0)] * _pop(scale, 3),
        generators=[
            G(
                "diurnal",
                # One "day": quiet, a morning ramp to the peak, an
                # evening decay. Periodic, so any tick count works.
                curve="0:1,12:6,24:10,36:4,48:1",
                period=48.0, jitter=0.2,
                bands=[[0, 2.0], [1, 1.0], [2, 1.0]],
                wants=8.0, lifetime_ticks=8,
                max_population=_pop(scale, 120),
            ),
        ],
        gates={
            "top_band_satisfaction": 0.95,
            "satisfaction": 0.5,
            "peak_population": _pop(scale, 3) + 3,
            "get_capacity_p99_ms": 250.0,
        },
    )


def flash_crowd(scale: float = 1.0, seed: int = 0,
                ticks: Optional[int] = None) -> WorkloadSpec:
    """Sudden low-band crowd against AIMD admission; top band rides."""
    ticks = ticks or 28
    crowd = list(range(8, 14))
    return WorkloadSpec.make(
        "flash_crowd", ticks, seed=seed, capacity=100.0 * scale,
        algorithm="PRIORITY_BANDS",
        admission={"max_rps": max(4.0, 16.0 * scale), "min_level": 0.05},
        base_clients=[(1, 10.0)] * _pop(scale, 6),
        generators=[
            G(
                "flash_crowd", at=8, duration=6,
                clients=_pop(scale, 24), band=0, wants=10.0,
            ),
        ],
        stress_ticks=crowd,
        gates={
            "top_band_satisfaction": 0.9,
            "stress_satisfaction": 0.9,
            "top_band_goodput": 0.95,
            "refresh_ok_ratio": 0.5,
        },
    )


def rolling_deploy(scale: float = 1.0, seed: int = 0,
                   ticks: Optional[int] = None) -> WorkloadSpec:
    """Serial server deploys: abdicate, drain, rejoin, reconverge."""
    ticks = ticks or 30
    return WorkloadSpec.make(
        "rolling_deploy", ticks, seed=seed, servers=2,
        capacity=200.0 * scale,
        base_clients=[(0, 10.0), (0, 20.0), (1, 30.0)]
        * _pop(scale, 1),
        generators=[
            G("rolling_deploy", at=6, down_ticks=3, gap_ticks=5),
        ],
        baseline_tick=4, heal_tick=17,
        gates={
            "reconverge_ticks": 6.0,
            "master_changes": 3.0,
            "refresh_ok_ratio": 0.7,
            "top_band_satisfaction": 0.8,
        },
    )


def multi_region(scale: float = 1.0, seed: int = 0,
                 ticks: Optional[int] = None) -> WorkloadSpec:
    """Clients spread across regions; WAN RTT rides the latency SLO."""
    ticks = ticks or 24
    return WorkloadSpec.make(
        "multi_region", ticks, seed=seed, capacity=300.0 * scale,
        base_clients=[(0, 10.0)] * _pop(scale, 8),
        generators=[
            G(
                "multi_region",
                regions=[["local", 2.0, 2.0], ["near", 40.0, 2.0],
                         ["far", 150.0, 1.0]],
            ),
            G(
                "diurnal", curve="0:2,12:4,24:2", period=24.0,
                jitter=0.1, bands=[[0, 1.0]], wants=5.0,
                lifetime_ticks=6, max_population=_pop(scale, 40),
                prefix="m",
            ),
        ],
        gates={
            "satisfaction": 0.9,
            "refresh_virtual_p99_ms": 170.0,
            "get_capacity_p99_ms": 250.0,
        },
    )


def elastic_preempt(scale: float = 1.0, seed: int = 0,
                    ticks: Optional[int] = None) -> WorkloadSpec:
    """Elastic jobs ride out preemption by a rigid crowd, then finish.

    The fractional-job model of arxiv 1106.4985: work accrues with
    whatever is granted; sustained starvation preempts and requeues."""
    ticks = ticks or 40
    jobs = _pop(scale, 6)
    return WorkloadSpec.make(
        "elastic_preempt", ticks, seed=seed,
        capacity=100.0 * scale, algorithm="PRIORITY_BANDS",
        base_clients=[(1, 15.0)] * _pop(scale, 2),
        generators=[
            G(
                "elastic", jobs=jobs, band=0, min_wants=4.0,
                max_wants=15.0, total_work=160.0,
                patience=2, requeue_ticks=3, start_tick=1,
            ),
            # The rigid interference: a higher-band crowd that grabs
            # most of the capacity mid-run, starving the elastic band.
            G(
                "flash_crowd", at=10, duration=8,
                clients=_pop(scale, 5), band=1, wants=18.0,
                prefix="rigid",
            ),
        ],
        gates={
            "completions": float(jobs),
            "preemptions": 1.0,
            "top_band_satisfaction": 0.85,
        },
    )


def flash_crowd_federated(scale: float = 1.0, seed: int = 0,
                          ticks: Optional[int] = None) -> WorkloadSpec:
    """Flash crowd against one shard of a federated straddling root."""
    ticks = ticks or 26
    return WorkloadSpec.make(
        "flash_crowd_federated", ticks, seed=seed, servers=2,
        capacity=200.0 * scale,
        federated={
            "straddle": ["r0"],
            "client_shards": [0, 0, 1, 1],
        },
        base_clients=[(0, 20.0), (1, 10.0), (0, 20.0), (1, 10.0)],
        generators=[
            G(
                "flash_crowd", at=8, duration=6,
                clients=_pop(scale, 10), band=0, wants=15.0,
            ),
        ],
        gates={
            "fed_capacity_violations": 0.0,
            "top_band_satisfaction": 0.9,
        },
    )


def diurnal_streaming(scale: float = 1.0, seed: int = 0,
                      ticks: Optional[int] = None) -> WorkloadSpec:
    """Diurnal churn with WatchCapacity stream clients riding along."""
    ticks = ticks or 30
    return WorkloadSpec.make(
        "diurnal_streaming", ticks, seed=seed, capacity=300.0 * scale,
        stream_clients=[(1, 20.0)] * _pop(scale, 3),
        base_clients=[(1, 10.0)] * _pop(scale, 2),
        generators=[
            G(
                "diurnal", curve="0:2,10:6,20:2", period=20.0,
                jitter=0.15, bands=[[0, 1.0]], wants=6.0,
                lifetime_ticks=5, max_population=_pop(scale, 50),
            ),
        ],
        gates={
            "stream_pushes": float(_pop(scale, 3)),
            "satisfaction": 0.9,
        },
    )


def diurnal_streaming_pooled(scale: float = 1.0, seed: int = 0,
                             ticks: Optional[int] = None
                             ) -> WorkloadSpec:
    """Diurnal streaming churn served through the frontend pool."""
    # The diurnal_streaming shape, but the WatchCapacity leg rides the
    # serving-plane pool: 2 listener workers over shared-memory push
    # rings, streams spread across 4 shards (stable client hash), the
    # tick-edge pump standing in for the workers' poll loops. The
    # frontend gates require the pool to have visibly carried the
    # stream traffic AND still be holding every stream at run end —
    # a silent fall-back to the in-process path fails the scenario.
    ticks = ticks or 30
    streams = _pop(scale, 4)
    return WorkloadSpec.make(
        "diurnal_streaming_pooled", ticks, seed=seed,
        capacity=300.0 * scale,
        stream_clients=[(1, 20.0)] * streams,
        base_clients=[(1, 10.0)] * _pop(scale, 2),
        frontend_workers=2, stream_shards=4,
        generators=[
            G(
                "diurnal", curve="0:2,10:6,20:2", period=20.0,
                jitter=0.15, bands=[[0, 1.0]], wants=6.0,
                lifetime_ticks=5, max_population=_pop(scale, 50),
            ),
        ],
        gates={
            "stream_pushes": float(streams),
            "satisfaction": 0.9,
            "frontend_frames": float(streams),
            "frontend_held": float(streams),
        },
    )


def flash_crowd_predictive(scale: float = 1.0, seed: int = 0,
                           ticks: Optional[int] = None) -> WorkloadSpec:
    """Seasonal forecaster primes AIMD before each repeating crowd."""
    period = 16
    ticks = ticks or (8 + 3 * period + 4)
    crowd_ticks = [
        t
        for cycle in (1, 2)  # cycles after the forecaster has seen one
        for t in range(8 + cycle * period, 8 + cycle * period + 4)
    ]
    return WorkloadSpec.make(
        "flash_crowd_predictive", ticks, seed=seed,
        capacity=100.0 * scale,
        # Tight budget + deep MD: one predicted-overload window is
        # enough to extinguish the bottom band (level 0.4 with two
        # bands -> band-0 admit probability 0).
        admission={"max_rps": max(4.0, 12.0 * scale), "min_level": 0.05,
                   "md_factor": 0.4},
        base_clients=[(1, 10.0)] * _pop(scale, 6),
        generators=[
            G(
                "flash_crowd", at=8, duration=4,
                clients=_pop(scale, 24), band=0, wants=10.0,
                period=period, repeats=3,
            ),
        ],
        # Slow level / fast season (both dyadic): the level must NOT
        # chase the spike, or the seasonal term never accumulates the
        # amplitude the pre-spike forecast needs.
        predictive={"period": period, "alpha": 0.25, "beta": 0.5},
        stress_ticks=crowd_ticks,
        gates={
            "top_band_satisfaction": 0.9,
            "stress_satisfaction": 0.85,
            "top_band_goodput": 0.95,
        },
    )


def _million_population(pop: int) -> list:
    """The compact (count, band, wants) base rows for a million-client
    scenario: 60/30/10 across three bands, exact total."""
    b0 = (pop * 6) // 10
    b1 = (pop * 3) // 10
    return [[b0, 0, 1.0], [b1, 1, 2.0], [pop - b0 - b1, 2, 4.0]]


# Million-client scenarios: refresh each resident row every
# MILLION_SPREAD ticks (due set per tick = population / spread), with
# leases sized to outlive a full wheel lap so nothing expires between
# refreshes.
MILLION_SPREAD = 50


def diurnal_million(scale: float = 1.0, seed: int = 0,
                    ticks: Optional[int] = None) -> WorkloadSpec:
    """Million-client diurnal wave on the array-backed vector engine."""
    ticks = ticks or 24
    pop = max(1, int(round(1_000_000 * scale)))
    return WorkloadSpec.make(
        "diurnal_million", ticks, seed=seed, capacity=float(pop),
        lease_length=4.0 * MILLION_SPREAD,
        population_engine="vector", refresh_spread=MILLION_SPREAD,
        native_store=True,
        base_population=_million_population(pop),
        generators=[
            # Modest churn rides on top of the parked million: the
            # arrival wave exercises bulk arrivals/departures without
            # dominating the resident population.
            G(
                "diurnal", curve="0:2,6:8,12:14,18:6,24:2",
                period=24.0, jitter=0.2,
                bands=[[0, 1.0], [1, 1.0]], wants=5.0,
                lifetime_ticks=6, max_population=_pop(scale, 200),
            ),
        ],
        gates={
            "peak_population": float(pop),
            "refresh_ok_ratio": 0.95,
        },
    )


def flash_crowd_million(scale: float = 1.0, seed: int = 0,
                        ticks: Optional[int] = None) -> WorkloadSpec:
    """Flash crowd over a parked million-client base; AIMD admission."""
    ticks = ticks or 20
    pop = max(1, int(round(1_000_000 * scale)))
    b1 = pop // 2
    # AIMD budget sized to the steady due rate (population / spread):
    # the crowd's extra arrivals push the window over it, so band 0
    # sheds while the top band rides the goodput floor.
    steady_rps = max(4.0, pop / MILLION_SPREAD)
    return WorkloadSpec.make(
        "flash_crowd_million", ticks, seed=seed, capacity=float(pop),
        algorithm="PRIORITY_BANDS",
        lease_length=4.0 * MILLION_SPREAD,
        population_engine="vector", refresh_spread=MILLION_SPREAD,
        native_store=True,
        admission={"max_rps": steady_rps, "min_level": 0.05},
        base_population=[[pop - b1, 0, 1.0], [b1, 1, 2.0]],
        generators=[
            G(
                "flash_crowd", at=6, duration=6,
                clients=_pop(scale, 500), band=0, wants=10.0,
            ),
        ],
        gates={
            "peak_population": float(pop),
            "top_band_goodput": 0.9,
        },
    )


def reshard_diurnal(scale: float = 1.0, seed: int = 0,
                    ticks: Optional[int] = None) -> WorkloadSpec:
    """Diurnal wave over an elastic fleet: the autoscale generator
    grows the active shard set 2→4 when the morning ramp starves
    satisfaction, and shrinks back 4→2 once the evening decay restores
    headroom — with the straddle capacity-sum and top-band leases
    pinned through both routing-epoch changes."""
    ticks = ticks or 48
    cap = 260.0 * scale
    # Straddling resources must decompose into compact per-shard
    # summaries, so the fleet runs the proportional default (the
    # reconciler rejects PRIORITY_BANDS straddles by design).
    return WorkloadSpec.make(
        "reshard_diurnal", ticks, seed=seed, servers=4,
        capacity=cap,
        federated={
            "fleet": True,
            "active": 2,
            "straddle": ["r0"],
            "client_shards": [0, 1],
        },
        base_clients=[(2, 30.0 * scale), (2, 30.0 * scale)],
        generators=[
            G(
                "diurnal",
                # Sharp day: quiet, a steep morning ramp that
                # overloads the pool, a fast evening decay so the
                # shrink leg fires well before the run ends.
                curve="0:1,10:10,22:12,30:2,48:1",
                period=48.0, jitter=0.2,
                bands=[[0, 2.0], [1, 1.0]],
                wants=8.0 * scale, lifetime_ticks=6,
                max_population=_pop(scale, 100),
            ),
            G(
                "autoscale", target=0.85, min_shards=2, max_shards=4,
                scale_step=2, hysteresis=3, cooldown=6,
                shrink_margin=0.05,
            ),
        ],
        gates={
            # Both legs of the 2→4→2 arc visibly happened...
            "epoch_changes": 2.0,
            # ...without ever over-admitting across the fleet...
            "fed_capacity_violations": 0.0,
            # ...while the resident leases ride through both
            # routing-epoch changes and refreshes keep landing.
            "top_band_satisfaction": 0.8,
            "refresh_ok_ratio": 0.9,
            "get_capacity_p99_ms": 250.0,
        },
    )


SCENARIOS: Dict[str, Callable[..., WorkloadSpec]] = {
    fn.__name__: fn
    for fn in (
        diurnal, flash_crowd, rolling_deploy, multi_region,
        elastic_preempt, flash_crowd_federated, diurnal_streaming,
        diurnal_streaming_pooled, flash_crowd_predictive,
        diurnal_million, flash_crowd_million, reshard_diurnal,
    )
}


def scenario_lines() -> list:
    """[(name, one-line doc), ...] — what --list-scenarios prints
    (the sim registry's convention, via its shared helper)."""
    from doorman_tpu.sim.scenarios import registry_lines

    return registry_lines(SCENARIOS)


async def _run(spec: WorkloadSpec, forecaster=None):
    runner = WorkloadRunner(spec, forecaster=forecaster)
    return await runner.run(), runner


def _warm_forecaster(spec: WorkloadSpec, history):
    """A forecaster primed from a durable history, or None when the
    spec is not predictive / the history holds nothing to replay. The
    model is built exactly as the harness builds its cold one, then
    `warm_start` replays the recorded per-tick offered stream through
    `observe` — so the resulting state is bit-identical to having
    watched that stream live (the pin in
    tests/test_workload_population.py)."""
    from doorman_tpu.workload.forecast import SeasonalForecaster

    predictive = spec.predictive_config()
    if not predictive:
        return None
    bands = [int(b) for b in predictive.get("bands", [0, 1])]
    fc = SeasonalForecaster(
        series=len(bands),
        period=int(predictive["period"]),
        alpha=float(predictive.get("alpha", 0.5)),
        beta=float(predictive.get("beta", 0.25)),
        engine=str(predictive.get("engine", "auto")),
    )
    fed = fc.warm_start(
        history, field="offered", interval=float(spec.tick_interval)
    )
    return fc if fed else None


async def run_scenario_async(
    name: str, *, scale: float = 1.0, seed: int = 0,
    ticks: Optional[int] = None, history_dir: Optional[str] = None,
) -> dict:
    """Run one named scenario and return its verdict dict.

    ``flash_crowd_predictive`` runs twice — forecaster on, then the
    identical spec with the forecaster stripped — and the returned
    verdict is the predictive run's, extended with the reactive run's
    summary and the standing predictive-over-reactive pair verdict.
    """
    factory = SCENARIOS.get(name)
    if factory is None:
        raise ValueError(
            f"unknown scenario {name!r} (known: {sorted(SCENARIOS)})"
        )
    spec = factory(scale=scale, seed=seed, ticks=ticks)
    history = forecaster = None
    if history_dir:
        from doorman_tpu.obs.history import HistoryStore

        # Loading the store replays any prior runs' segments; a
        # predictive spec warm-starts its forecaster from them.
        history = HistoryStore(
            history_dir, component=f"workload:{spec.name}"
        )
        forecaster = _warm_forecaster(spec, history)
    warm_ticks = forecaster.ticks_observed if forecaster else 0
    verdict, runner = await _run(spec, forecaster=forecaster)
    if history is not None:
        verdict["forecaster_warm_start"] = warm_ticks
        # Re-home this run's flight records as durable segments, so the
        # NEXT invocation starts where this one's traffic left off.
        try:
            for rec in runner.flightrec.snapshot():
                history.append(rec)
        finally:
            history.close()
    if spec.predictive_config():
        reactive_spec = spec.with_(
            predictive={}
        ).with_(name=f"{spec.name}_reactive")
        reactive, _ = await _run(reactive_spec)
        key = "top_band_satisfaction_stress"
        pair = slo_mod.predictive_goodput_verdict(
            float(verdict["summary"].get(key, 0.0)),
            float(reactive["summary"].get(key, 0.0)),
            name=f"workload:{spec.name}:predictive_over_reactive",
        )
        pair["delta_vs_prev"] = slo_mod.TrajectoryComparator(
        ).slo_delta(pair)
        verdict["slo"]["verdicts"].append(pair)
        verdict["slo"]["ok"] = verdict["slo"]["ok"] and (
            pair["status"] != "fail"
        )
        verdict["ok"] = verdict["slo"]["ok"]
        verdict["reactive"] = {
            "summary": reactive["summary"],
            "log_sha256": reactive["log_sha256"],
        }
    return verdict


def run_scenario(name: str, *, scale: float = 1.0, seed: int = 0,
                 ticks: Optional[int] = None,
                 history_dir: Optional[str] = None) -> dict:
    import asyncio

    return asyncio.run(
        run_scenario_async(
            name, scale=scale, seed=seed, ticks=ticks,
            history_dir=history_dir,
        )
    )
