"""Device-batched seasonal demand forecaster for predictive admission.

A Holt–Winters additive-seasonal model over per-band offered-rate
history (the flight recorder's per-tick series): one EWMA level per
series plus a seasonal correction per slot of a fixed period. Each
``observe(x)`` folds in one tick's rates and returns the forecast for
the NEXT tick, clamped to the min/max envelope of everything seen so
far — a forecast is a claim about recurring traffic, not an
extrapolation license:

    level'        = level + alpha * ((x - season[slot]) - level)
    season[slot]' = season[slot] + beta * ((x - level') - season[slot])
    forecast      = clip(level' + season[next_slot], hist_min, hist_max)

The update is elementwise over the batch of series (bands), so the
device path is one fused jitted step over float32 arrays — the
"device-batched Learn mode" of the tentpole. Per the PR-15 oracle
discipline, the numpy host path is the ORACLE and the device path is
pinned bit-identical to it (tests/test_forecast.py). Bit parity across
compilers follows the repo's exactly-representable convention: the
gains ``alpha``/``beta`` are constrained to powers of two, so every
multiply in the delta-form update scales by a power of two and is
EXACT in float32 — an fma-fusing backend rounds each fused
multiply-add exactly once, the same place numpy's separate ops round,
and no expression can diverge. (The general convex form
``a*x + (1-a)*y`` has two inexact products and IS fma-sensitive; the
delta form with dyadic gains is why this model replays bit-for-bit.)

Two invariants hold by construction (hypothesis-tested):

  * the forecast never leaves the historical [min, max] envelope
    (the final clip);
  * constant traffic is a fixpoint: after the first observation of a
    constant series the forecast equals the constant exactly (level
    initializes to x, every seasonal correction stays 0).
"""

from __future__ import annotations

import math
from typing import Optional, Sequence, Tuple

import numpy as np

__all__ = ["SeasonalForecaster", "host_step", "device_available"]

# State tuple: (level[B], season[B, P], hist_min[B], hist_max[B],
# seen[B] as float32 0/1). Everything float32: the device path computes
# in f32 and the oracle must match it bit for bit.
State = Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, np.ndarray]


def _dyadic(gain: float) -> bool:
    """True for 0 or a power of two in (0, 1] — the gains whose f32
    products are exact (see module docstring)."""
    if gain == 0.0:
        return True
    if not 0.0 < gain <= 1.0:
        return False
    return math.frexp(gain)[0] == 0.5


def init_state(series: int, period: int) -> State:
    if series < 1:
        raise ValueError(f"series must be >= 1, got {series}")
    if period < 1:
        raise ValueError(f"period must be >= 1, got {period}")
    return (
        np.zeros(series, np.float32),
        np.zeros((series, period), np.float32),
        np.zeros(series, np.float32),
        np.zeros(series, np.float32),
        np.zeros(series, np.float32),
    )


def host_step(
    state: State, x: np.ndarray, slot: int, nxt: int,
    alpha: float, beta: float,
) -> Tuple[State, np.ndarray]:
    """One numpy oracle step: fold in x (float32[B]) at seasonal slot
    `slot`, forecast for slot `nxt`. The device step mirrors these
    expressions operation for operation."""
    level, season, hist_min, hist_max, seen = state
    a = np.float32(alpha)
    b = np.float32(beta)
    s = season[:, slot]
    level2 = np.where(seen > 0, level + a * ((x - s) - level), x)
    season_slot = np.where(
        seen > 0, s + b * ((x - level2) - s), s
    )
    hist_min2 = np.where(seen > 0, np.minimum(hist_min, x), x)
    hist_max2 = np.where(seen > 0, np.maximum(hist_max, x), x)
    season2 = season.copy()
    season2[:, slot] = season_slot
    forecast = np.clip(level2 + season2[:, nxt], hist_min2, hist_max2)
    seen2 = np.ones_like(seen)
    return (
        (level2, season2, hist_min2, hist_max2, seen2),
        forecast.astype(np.float32),
    )


_DEVICE_STEP = None
_DEVICE_OK: Optional[bool] = None


def device_available() -> bool:
    """True when jax imports and can build the jitted step."""
    return _get_device_step() is not None


def _get_device_step():
    global _DEVICE_STEP, _DEVICE_OK
    if _DEVICE_OK is not None:
        return _DEVICE_STEP
    try:
        import jax
        import jax.numpy as jnp

        @jax.jit
        def step(level, season, hist_min, hist_max, seen, x, slot, nxt,
                 alpha, beta):
            # The oracle's expressions, same order, f32 throughout.
            a = alpha.astype(jnp.float32)
            b = beta.astype(jnp.float32)
            s = season[:, slot]
            level2 = jnp.where(
                seen > 0, level + a * ((x - s) - level), x
            )
            season_slot = jnp.where(
                seen > 0, s + b * ((x - level2) - s), s
            )
            hist_min2 = jnp.where(seen > 0, jnp.minimum(hist_min, x), x)
            hist_max2 = jnp.where(seen > 0, jnp.maximum(hist_max, x), x)
            season2 = season.at[:, slot].set(season_slot)
            forecast = jnp.clip(
                level2 + season2[:, nxt], hist_min2, hist_max2
            )
            seen2 = jnp.ones_like(seen)
            return (
                level2, season2, hist_min2, hist_max2, seen2, forecast
            )

        _DEVICE_STEP = step
        _DEVICE_OK = True
    except Exception:  # jax missing or backend init failed
        _DEVICE_STEP = None
        _DEVICE_OK = False
    return _DEVICE_STEP


class SeasonalForecaster:
    """Batched Holt–Winters forecaster over `series` parallel rate
    series with seasonal period `period` (in ticks).

    alpha/beta must be 0 or a power of two in (0, 1] (the bit-parity
    constraint in the module docstring); beta=0 disables the seasonal
    leg and leaves a plain EWMA.

    engine: "auto" (device when jax is importable, else host),
    "host" (force the numpy oracle), "device" (force jax; raises if
    unavailable)."""

    def __init__(
        self,
        series: int,
        period: int,
        *,
        alpha: float = 0.5,
        beta: float = 0.25,
        engine: str = "auto",
    ):
        if alpha == 0.0 or not _dyadic(alpha):
            raise ValueError(
                f"alpha must be a power of two in (0, 1], got {alpha}"
            )
        if not _dyadic(beta):
            raise ValueError(
                f"beta must be 0 or a power of two in (0, 1], "
                f"got {beta}"
            )
        if engine not in ("auto", "host", "device"):
            raise ValueError(f"unknown engine {engine!r}")
        self.series = int(series)
        self.period = int(period)
        self.alpha = float(alpha)
        self.beta = float(beta)
        self._state = init_state(self.series, self.period)
        self._t = 0
        if engine == "auto":
            engine = "device" if _get_device_step() else "host"
        elif engine == "device" and _get_device_step() is None:
            raise RuntimeError("jax unavailable: no device forecaster")
        self.engine = engine

    @property
    def ticks_observed(self) -> int:
        return self._t

    def warm_start(
        self,
        history,
        *,
        field: str = "offered",
        tier: int = 0,
        interval: float = 1.0,
    ) -> int:
        """Prime the model from a flight-record history before serving
        live traffic: replay the per-tick offered-rate stream through
        ``observe`` so a restarted process starts with the previous
        run's level/seasonal state instead of a cold model.

        ``history`` is an ``obs.history.HistoryStore`` (or any iterable
        of record dicts / scalars, oldest first). Each record's
        ``field`` value is divided by ``interval`` (counts -> rates,
        same arithmetic as the live harness) and broadcast across the
        batch. Because this IS ``observe``, the resulting state is
        bit-identical to having watched the same stream live — the
        restart-spanning twin of the oracle discipline. Returns the
        number of ticks folded in."""
        if hasattr(history, "records"):
            records = history.records(tier=tier)
        else:
            records = history
        fed = 0
        for rec in records:
            v = rec.get(field) if isinstance(rec, dict) else rec
            if v is None:
                continue
            x = np.full(
                self.series, np.float32(float(v) / interval), np.float32
            )
            self.observe(x)
            fed += 1
        return fed

    def observe(self, x: Sequence[float]) -> np.ndarray:
        """Fold in one tick's per-series rates; return float32[B]
        forecast for the next tick."""
        arr = np.asarray(x, np.float32)
        if arr.shape != (self.series,):
            raise ValueError(
                f"expected {self.series} rates, got shape {arr.shape}"
            )
        slot = self._t % self.period
        nxt = (self._t + 1) % self.period
        if self.engine == "device":
            step = _get_device_step()
            out = step(
                *self._state, arr,
                np.int32(slot), np.int32(nxt),
                np.float32(self.alpha), np.float32(self.beta),
            )
            self._state = tuple(np.asarray(v) for v in out[:5])
            forecast = np.asarray(out[5])
        else:
            self._state, forecast = host_step(
                self._state, arr, slot, nxt, self.alpha, self.beta
            )
        self._t += 1
        return forecast

    def status(self) -> dict:
        level, _, hist_min, hist_max, seen = self._state
        return {
            "engine": self.engine,
            "period": self.period,
            "ticks_observed": self._t,
            "level": [round(float(v), 3) for v in level],
            "hist_min": [round(float(v), 3) for v in hist_min],
            "hist_max": [round(float(v), 3) for v in hist_max],
            "seen": bool(seen.any()),
        }
