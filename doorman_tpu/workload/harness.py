"""WorkloadRunner: drive a WorkloadSpec against the real stack on the
virtual clock and return an SLO-gated verdict.

The topology is the chaos runner's, reused piece for piece (this is
the point: scenarios measure the REAL server, not a model of it): real
CapacityServer instances behind ChaosGrpcProxy loopback hops, the
stepped TTL-lock election, real Client instances refreshing leases —
every periodic loop driven explicitly in a fixed order per virtual
tick, so the same spec + seed replays the same event log
byte-for-byte. What the workload harness adds over chaos is the LOAD
side: a dynamic client population moved by the spec's generators
(arrivals, departures, deploys, elastic preemption), per-band
satisfaction accounting, and the SLO gate layer that turns a run into
a machine-readable pass/fail verdict.

Determinism contract (the byte-stable event-log acceptance):

  * the event log records only virtual-time facts — tick indices,
    client counts, rounded satisfaction/level/forecast values, master
    sets — never wall-clock durations;
  * wall-clock latencies (perf_counter around each refresh) feed ONLY
    the SLO sample streams, whose verdicts sit outside the log digest;
  * all randomness comes from the spec's seeded RNGs (FaultState.rng
    for decisions that reach the server — admission shed draws — and a
    separate measurement RNG for the virtual RTT jitter, so the
    measurement model cannot perturb admission's replay);
  * clients are stepped in insertion order, generators in spec order.

Predictive admission: with ``spec.predictive`` set, a
`forecast.SeasonalForecaster` observes the per-band offered rates each
tick and feeds the summed next-tick forecast to every server's AIMD
controller (`set_forecast`) — the controller then multiplies down at
the window boundary ENTERING a predicted spike instead of the one
after it. The flash_crowd_predictive scenario races this against the
identical reactive run.
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import random
import time
from typing import Dict, List, Optional

import numpy as np

from doorman_tpu.chaos.clock import ChaosClock
from doorman_tpu.chaos.injectors import ChaosGrpcProxy, FaultState
from doorman_tpu.chaos.invariants import InvariantChecker
from doorman_tpu.chaos.runner import SteppedElection, _cancel_background
from doorman_tpu.client.client import Client
from doorman_tpu.obs import slo as slo_mod
from doorman_tpu.obs import trace as trace_mod
from doorman_tpu.obs.flightrec import FlightRecorder
from doorman_tpu.server.config import parse_yaml_config
from doorman_tpu.server.election import InMemoryKV, shard_lock_key
from doorman_tpu.server.server import CapacityServer
from doorman_tpu.workload import generators as gen_mod
from doorman_tpu.workload.forecast import SeasonalForecaster
from doorman_tpu.workload.spec import WorkloadSpec

LOCK = "/workload/master"

__all__ = ["WorkloadRunner", "run_spec"]


class WorkloadRunner:
    def __init__(self, spec: WorkloadSpec,
                 forecaster: Optional[SeasonalForecaster] = None):
        self.spec = spec
        # A caller-supplied (typically history-warm-started, see
        # forecast.warm_start) forecaster to use instead of building a
        # cold one from the predictive config; its series count must
        # match the config's bands. Validated here, before run() has
        # started any server a failure would leak.
        self._preset_forecaster = forecaster
        if forecaster is not None:
            predictive = spec.predictive_config() or {}
            bands = predictive.get("bands", [0, 1])
            if forecaster.series != len(bands):
                raise ValueError(
                    f"preset forecaster has {forecaster.series} "
                    f"series, predictive config has {len(bands)} bands"
                )
        self.clock = ChaosClock()
        self.tick_interval = float(spec.tick_interval)
        # Fault-free switchboard: the workload harness injects load,
        # not faults — FaultState is here for its seeded RNG (the only
        # randomness that reaches server-side decisions) and the proxy
        # plumbing it shares with chaos.
        self.state = FaultState(spec.seed)
        self.rng = self.state.rng
        # Measurement-side RNG (virtual RTT jitter): separate stream so
        # the latency model cannot perturb admission's replay.
        self.meas_rng = random.Random(spec.seed ^ 0x5EED)
        self.servers: Dict[str, CapacityServer] = {}
        self.proxies: Dict[str, ChaosGrpcProxy] = {}
        self.elections: Dict[str, SteppedElection] = {}
        self._locks: Dict[str, str] = {}
        self.kv: Optional[InMemoryKV] = None
        self.federation = None
        self.clients: Dict[str, Client] = {}
        self.stream_clients: List[Client] = []
        # Array-backed population engine (spec.population_engine ==
        # "vector"): macro clients live as numpy rows, refreshed in
        # batched grouped passes instead of per-client RPCs. None keeps
        # the per-client reference path.
        self._vector = None
        if spec.population_engine == "vector":
            from doorman_tpu.workload.population import VectorPopulation

            self._vector = VectorPopulation(self)
        elif spec.population_engine != "clients":
            raise ValueError(
                f"unknown population_engine "
                f"{spec.population_engine!r} "
                "(known: 'clients', 'vector')"
            )
        # Serving-plane pools (spec.frontend_workers > 0): one inline
        # frontend pool per server, pumped at the tick edge where a
        # real worker's poll loop would have woken.
        self.frontends: Dict[str, object] = {}
        self._frontend_frames = 0
        self._frontend_final: Dict[str, dict] = {}
        self.client_meta: Dict[str, dict] = {}
        self._client_shard: Dict[str, Optional[int]] = {}
        self.generators = gen_mod.build(spec)
        self.log: List[list] = []
        self.counters: Dict[str, int] = {}
        self.samples: Dict[str, List[float]] = {
            "get_capacity_wall_ms": [],
            "refresh_virtual_ms": [],
        }
        self._tick = 0
        self._offered_by_band: Dict[int, int] = {}
        self._down: Dict[str, int] = {}  # server name -> down until tick
        self._attach = ""
        self._admission_last: Dict[str, tuple] = {}
        self._last_band_row: Optional[list] = None
        self._last_forecast: Optional[float] = None
        self._fed_last_shares: Dict[str, list] = {}
        self._base_ids: List[str] = []
        self._baseline: Optional[Dict[str, float]] = None
        self._converged_at: Optional[int] = None
        self._last_masters: tuple = ()
        self._master_changes = 0
        self._refresh_attempts = 0
        self._refresh_ok = 0
        self._stream_pushes = 0
        self._fed_violations = 0
        self._peak_population = 0
        self._sat_rows: List[Dict[int, float]] = []
        self._sat_ticks: List[int] = []
        self.forecaster: Optional[SeasonalForecaster] = None
        self._forecast_bands: List[int] = []
        self.flightrec = FlightRecorder(
            capacity=spec.ticks + 8,
            component=f"workload:{spec.name}",
            clock=self.clock,
        )
        self.flight_dump: Optional[dict] = None

    # -- the mutator surface generators drive ---------------------------

    def client_ids(self) -> List[str]:
        if self._vector is not None:
            return self._vector.client_ids()
        return list(self.clients)

    def _population_count(self) -> int:
        if self._vector is not None:
            return self._vector.population()
        return len(self.clients)

    def note(self, tick: int, kind: str, *fields) -> None:
        """One deterministic event-log entry + a trace instant (the
        trace ring sits outside the log digest)."""
        self.log.append([tick, kind, *fields])
        trace_mod.default_tracer().instant(
            f"workload.{kind}", cat="workload", args={"tick": tick}
        )

    def bump(self, counter: str, by: int = 1) -> None:
        self.counters[counter] = self.counters.get(counter, 0) + by

    async def arrive(
        self, cid: str, band: int, wants: float,
        shard: Optional[int] = None,
    ) -> Optional[Client]:
        if self._vector is not None:
            self._vector.arrive(cid, int(band), float(wants), shard=shard)
            self._client_shard[cid] = shard
            self.client_meta.setdefault(cid, {})["band"] = int(band)
            for g in self.generators:
                g.on_arrive(cid, self)
            rtt_ms = self.client_meta.get(cid, {}).get("rtt_ms")
            if rtt_ms is not None:
                self._vector.set_rtt(cid, rtt_ms)
            return None
        if cid in self.clients:
            raise ValueError(f"client id {cid!r} already present")
        addr = self._attach
        if shard is not None:
            addr = self.proxies[f"s{int(shard)}"].address
        client = Client(
            addr, cid, minimum_refresh_interval=0.0, max_retries=0,
            clock=self.clock,
        )
        await client.resource(
            self.spec.resource, float(wants), priority=int(band)
        )
        self.clients[cid] = client
        self._client_shard[cid] = shard
        self.client_meta.setdefault(cid, {})["band"] = int(band)
        for g in self.generators:
            g.on_arrive(cid, self)
        return client

    async def depart(self, cid: str) -> None:
        if self._vector is not None:
            self.client_meta.pop(cid, None)
            await self._vector.depart(cid)
            return
        client = self.clients.pop(cid, None)
        if client is None:
            return
        self.client_meta.pop(cid, None)
        try:
            await client.close()
        except Exception:
            pass

    def grant_of(self, cid: str) -> float:
        if self._vector is not None:
            return self._vector.grant_of(cid)
        client = self.clients.get(cid)
        if client is None:
            return 0.0
        return sum(
            res.current_capacity() for res in client.resources.values()
        )

    def fleet_reshard(self, n_shards: int) -> None:
        """Publish a new routing epoch serving `n_shards` of the
        provisioned pool (fleet specs only). The generator owns the
        policy (fixed schedule or autoscaler); the harness just applies
        it, counts it, and logs the change deterministically."""
        if self.federation is None or not hasattr(
            self.federation, "reshard"
        ):
            raise ValueError(
                "fleet_reshard needs a fleet federated spec "
                '({"fleet": True, ...})'
            )
        change = self.federation.reshard(int(n_shards))
        self.bump("epoch_changes")
        self.note(
            self._tick, "fleet_epoch", change.epoch,
            change.n_from, change.n_to,
        )

    async def deploy(self, server_index: int, down_ticks: int) -> None:
        """Take one server down for a graceful rolling-deploy window:
        abdicate mastership, release its lock, and stay out of the
        campaign until the window ends (SteppedElection step's
        campaign=False leg)."""
        name = f"s{int(server_index)}"
        if name not in self.servers:
            return
        self._down[name] = self._tick + int(down_ticks)
        election = self.elections[name]
        if election.is_master:
            await election.abdicate()
            self.kv.expire(self._locks[name])
        self.note(self._tick, "deploy", name, int(down_ticks))

    # -- setup / teardown ----------------------------------------------

    def _config_yaml(self) -> str:
        s = self.spec
        safe_line = (
            f"  safe_capacity: {s.safe_capacity}\n"
            if s.safe_capacity is not None else ""
        )
        variant_part = (
            ", parameters: [{name: variant, value: "
            f"{s.algorithm_variant}" "}]"
            if s.algorithm_variant else ""
        )
        return (
            "resources:\n"
            f"- identifier_glob: \"*\"\n"
            f"  capacity: {s.capacity}\n"
            + safe_line
            + "  algorithm: {"
            + f"kind: {s.algorithm}, "
            + f"lease_length: {s.lease_length}, "
            + f"refresh_interval: {s.refresh_interval}, "
            + f"learning_mode_duration: {s.learning_mode_duration}"
            + variant_part
            + "}\n"
        )

    async def _setup(self) -> None:
        spec = self.spec
        self.kv = InMemoryKV(clock=self.clock)
        config = parse_yaml_config(self._config_yaml())
        fed = spec.federated_config()
        admission_kwargs = spec.admission_kwargs()
        for i in range(int(spec.servers)):
            name = f"s{i}"
            proxy = ChaosGrpcProxy(self.state, link=f"link:{name}")
            await proxy.start()
            lock = shard_lock_key(LOCK, i) if fed else LOCK
            self._locks[name] = lock
            election = SteppedElection(
                self.kv, lock, ttl=float(spec.election_ttl),
                clock=self.clock,
            )
            admission = None
            if admission_kwargs:
                from doorman_tpu.admission import Admission

                a = dict(admission_kwargs)
                admission = Admission(
                    coalesce_window=float(a.pop("coalesce_window", 0.0)),
                    clock=self.clock,
                    rng=self.rng,
                    **a,
                )
            server = CapacityServer(
                proxy.address, election,
                mode="immediate",
                tick_interval=self.tick_interval,
                minimum_refresh_interval=0.0,
                clock=self.clock,
                admission=admission,
                native_store=bool(spec.native_store),
                stream_push=bool(spec.stream_clients),
                stream_shards=int(spec.stream_shards),
                shard=i if fed else None,
            )
            await server.start(0, host="127.0.0.1")
            await _cancel_background(server)
            proxy.backend = server
            await server.load_config(config)
            if spec.frontend_workers and spec.stream_clients:
                self.frontends[name] = server.attach_frontend(
                    int(spec.frontend_workers),
                    ring_bytes=int(spec.frontend_ring),
                )
            self.servers[name] = server
            self.proxies[name] = proxy
            self.elections[name] = election

        if fed and fed.get("fleet"):
            # Fleet runtime: all spec.servers are PROVISIONED shards,
            # the first `active` serve; generators move the boundary
            # live through harness.fleet_reshard (routing epochs).
            from doorman_tpu.fleet import FleetController

            self.federation = FleetController(
                {
                    i: self.servers[f"s{i}"]
                    for i in range(int(spec.servers))
                },
                straddle=tuple(fed.get("straddle", (spec.resource,))),
                overrides=fed.get("overrides"),
                active=fed.get("active"),
                addrs={
                    i: self.proxies[f"s{i}"].address
                    for i in range(int(spec.servers))
                },
                share_ttl=float(fed.get("share_ttl", 2.0)),
                clock=self.clock,
            )
        elif fed:
            from doorman_tpu.federation import FederatedRoots, ShardRouter

            router = ShardRouter(
                int(spec.servers),
                straddle=tuple(fed.get("straddle", (spec.resource,))),
                overrides=fed.get("overrides"),
            )
            self.federation = FederatedRoots(
                router,
                {
                    i: self.servers[f"s{i}"]
                    for i in range(router.n_shards)
                },
                share_ttl=float(fed.get("share_ttl", 2.0)),
                clock=self.clock,
            )

        self._attach = self.proxies["s0"].address
        client_shards = (fed or {}).get("client_shards") or []
        for i, (band, wants) in enumerate(spec.base_clients):
            shard = (
                int(client_shards[i])
                if i < len(client_shards) and client_shards[i] is not None
                else None
            )
            cid = f"c{i}"
            await self.arrive(cid, int(band), float(wants), shard=shard)
            self._base_ids.append(cid)
        # Compact base_population rows continue the c-numbering. The
        # vector engine appends each block as one array extension (its
        # deadline wheel staggers the initial lease establishment); the
        # per-client engine expands to real clients one by one.
        serial = len(spec.base_clients)
        for count, band, wants in spec.base_population:
            ids = [f"c{serial + k}" for k in range(int(count))]
            serial += int(count)
            if self._vector is not None:
                self._vector.bulk_arrive(ids, int(band), float(wants))
                for cid in ids:
                    self.client_meta.setdefault(cid, {})["band"] = int(
                        band
                    )
                    for g in self.generators:
                        g.on_arrive(cid, self)
                    rtt_ms = self.client_meta[cid].get("rtt_ms")
                    if rtt_ms is not None:
                        self._vector.set_rtt(cid, rtt_ms)
            else:
                for cid in ids:
                    await self.arrive(cid, int(band), float(wants))
            self._base_ids.extend(ids)
        for i, (band, wants) in enumerate(spec.stream_clients):
            client = Client(
                self._attach, f"w{i}", minimum_refresh_interval=0.0,
                max_retries=0, clock=self.clock, stream=True,
                retry_rng=random.Random(spec.seed * 1000 + i),
            )
            await client.resource(
                spec.resource, float(wants), priority=int(band)
            )
            self.stream_clients.append(client)

        predictive = spec.predictive_config()
        if predictive:
            if not admission_kwargs or "max_rps" not in admission_kwargs:
                raise ValueError(
                    "predictive admission needs an admission config "
                    "with max_rps (the budget the forecast scales "
                    "against)"
                )
            self._forecast_bands = [
                int(b) for b in predictive.get("bands", [0, 1])
            ]
            if self._preset_forecaster is not None:
                if self._preset_forecaster.series != len(
                    self._forecast_bands
                ):
                    raise ValueError(
                        f"preset forecaster has "
                        f"{self._preset_forecaster.series} series, "
                        f"predictive config has "
                        f"{len(self._forecast_bands)} bands"
                    )
                self.forecaster = self._preset_forecaster
            else:
                self.forecaster = SeasonalForecaster(
                    series=len(self._forecast_bands),
                    period=int(predictive["period"]),
                    alpha=float(predictive.get("alpha", 0.5)),
                    beta=float(predictive.get("beta", 0.25)),
                    engine=str(predictive.get("engine", "auto")),
                )
        for g in self.generators:
            await g.setup(self)

    async def _teardown(self) -> None:
        # Snapshot the pools' final shape BEFORE stopping anything:
        # WorkerCore.status() reads its ring's control words, and
        # server.stop() releases the ring buffers.
        self._frontend_final = {
            name: pool.status()
            for name, pool in sorted(self.frontends.items())
        }
        for client in list(self.clients.values()) + self.stream_clients:
            try:
                await client.close()
            except Exception:
                pass
        for proxy in self.proxies.values():
            await proxy.stop()
        for server in self.servers.values():
            try:
                await server.stop()
            except Exception:
                pass

    # -- per-tick beats -------------------------------------------------

    async def _step_elections(self, tick: int) -> None:
        for name, election in self.elections.items():
            down = self._down.get(name, 0) > tick
            await election.step(campaign=not down)
        masters = tuple(sorted(
            n for n, srv in self.servers.items() if srv.is_master
        ))
        if masters != self._last_masters:
            self._master_changes += 1
            self._last_masters = masters
            self.note(tick, "master", list(masters))

    async def _refresh_clients(self, tick: int) -> None:
        if self._vector is not None:
            self._vector.step_refresh(tick)
            return
        offered: Dict[int, int] = {}
        for cid, client in list(self.clients.items()):
            band = max(
                (res.priority for res in client.resources.values()),
                default=0,
            )
            offered[band] = offered.get(band, 0) + 1
            self._refresh_attempts += 1
            t0 = time.perf_counter()
            ok = await client.refresh_once()
            wall_ms = (time.perf_counter() - t0) * 1000.0
            self.samples["get_capacity_wall_ms"].append(wall_ms)
            meta = self.client_meta.get(cid, {})
            rtt_ms = meta.get("rtt_ms")
            if rtt_ms is not None:
                # Virtual refresh latency: one modeled WAN round trip
                # with +/-10% seeded jitter on top of a 1 ms service
                # floor. Measurement-only (SLO samples, not the log).
                self.samples["refresh_virtual_ms"].append(
                    1.0 + rtt_ms * (
                        0.9 + 0.2 * self.meas_rng.random()
                    )
                )
            if ok:
                self._refresh_ok += 1
        self._offered_by_band = offered

    async def _drive_streams(self, tick: int) -> None:
        if not self.stream_clients:
            return
        for server in self.servers.values():
            server.push_streams()
        for name, pool in self.frontends.items():
            stats = pool.pump_all()
            self._frontend_frames += stats["frames"]
            if stats["lapped"] or stats["corrupt"] or stats["stalled"]:
                self.log.append([
                    tick, "frontend_pump", name, stats["frames"],
                    stats["lapped"], stats["corrupt"], stats["stalled"],
                ])
        for client in self.stream_clients:
            out = await client.stream_step(drain_timeout=0.05)
            self._stream_pushes += out["pushes"]
            if out["events"] or out["pushes"]:
                self.log.append([
                    tick, "stream", client.id,
                    ",".join(out["events"]) or "push",
                    out["pushes"],
                ])

    def _drive_federation(self, tick: int) -> None:
        if self.federation is None:
            return
        installed = self.federation.reconcile_once()
        for rid, shares in sorted(installed.items()):
            rounded = [
                [shard, round(value, 6)]
                for shard, value in sorted(shares.items())
            ]
            if self._fed_last_shares.get(rid) != rounded:
                self._fed_last_shares[rid] = rounded
                self.log.append([tick, "straddle", rid, rounded])

    def _check_federation(self, tick: int,
                          checker: InvariantChecker) -> None:
        if self.federation is None:
            return
        violations = checker.check_federation(
            tick, self.servers, self.federation.straddle_capacities()
        )
        for v in violations:
            self._fed_violations += 1
            self.log.append([tick] + v.as_log())

    def _measure_bands(self, tick: int) -> Dict[int, float]:
        if self._vector is not None:
            wants_by, gets_by = self._vector.measure_bands()
        else:
            wants_by = {}
            gets_by = {}
            for client in self.clients.values():
                for res in client.resources.values():
                    band = int(res.priority)
                    wants_by[band] = wants_by.get(band, 0.0) + float(
                        res.wants
                    )
                    gets_by[band] = gets_by.get(band, 0.0) + min(
                        res.current_capacity(), float(res.wants)
                    )
        sat = {
            band: (gets_by[band] / wants_by[band])
            for band in wants_by if wants_by[band] > 0
        }
        row = [
            [band, round(wants_by[band], 6), round(gets_by[band], 6)]
            for band in sorted(wants_by)
        ]
        if row != self._last_band_row:
            self._last_band_row = row
            self.log.append([tick, "band", row])
        if sat:
            self._sat_rows.append(sat)
            self._sat_ticks.append(tick)
        return sat

    def _log_admission(self, tick: int) -> None:
        for name, server in self.servers.items():
            adm = getattr(server, "_admission", None)
            if adm is None:
                continue
            admitted = shed = 0
            for (method, _band), counts in adm.tallies.items():
                if method == "GetCapacity":
                    admitted += counts["admitted"]
                    shed += counts["shed"]
            last = self._admission_last.get(name, (0, 0))
            if (admitted, shed) != last:
                self._admission_last[name] = (admitted, shed)
                self.log.append([
                    tick, "admission", name,
                    admitted - last[0], shed - last[1],
                    round(adm.controller.level, 6),
                ])

    def _feed_forecast(self, tick: int) -> None:
        if self.forecaster is None:
            return
        rates = np.asarray(
            [
                self._offered_by_band.get(b, 0) / self.tick_interval
                for b in self._forecast_bands
            ],
            np.float32,
        )
        forecast = self.forecaster.observe(rates)
        total = float(np.sum(forecast))
        for server in self.servers.values():
            adm = getattr(server, "_admission", None)
            if adm is not None:
                adm.controller.set_forecast(total)
        rounded = round(total, 3)
        if rounded != self._last_forecast:
            self._last_forecast = rounded
            self.log.append([tick, "forecast", rounded])

    def _flight_record(self, tick: int,
                       sat: Dict[int, float]) -> None:
        rec: dict = {
            "t": self.clock(),
            "tick": tick,
            "masters": list(self._last_masters),
            "satisfaction": {
                str(b): round(v, 6) for b, v in sorted(sat.items())
            },
        }
        rec["population"] = self._population_count()
        rec["offered"] = sum(self._offered_by_band.values())
        if self.federation is not None and hasattr(
            self.federation, "epoch"
        ):
            # The fleet's routing state on the black box: an operator
            # lines a grant wiggle up with the epoch that caused it.
            rec["fleet_epoch"] = self.federation.epoch
            rec["fleet_active"] = self.federation.active
        if self.frontends:
            rec["frontend_held"] = sum(
                pool.held() for pool in self.frontends.values()
            )
        for name, server in sorted(self.servers.items()):
            adm = getattr(server, "_admission", None)
            if adm is not None:
                rec["admission_level"] = round(
                    adm.controller.level, 6
                )
                break
        if self._last_forecast is not None:
            rec["forecast_rps"] = self._last_forecast
        self.flightrec.record(**rec)

    # -- reconvergence --------------------------------------------------

    def _snapshot(self) -> Dict[str, float]:
        if self._vector is not None:
            return self._vector.snapshot(self._base_ids)
        out = {}
        for cid in self._base_ids:
            client = self.clients.get(cid)
            if client is None:
                continue
            for rid, res in client.resources.items():
                out[f"{cid}/{rid}"] = res.current_capacity()
        return out

    @staticmethod
    def _matches(a: Dict[str, float], b: Dict[str, float]) -> bool:
        return a.keys() == b.keys() and all(
            abs(a[k] - b[k]) <= 1e-9 for k in a
        )

    def _track_reconvergence(self, tick: int) -> None:
        spec = self.spec
        if spec.baseline_tick is None or spec.heal_tick is None:
            return
        if tick == spec.baseline_tick:
            self._baseline = self._snapshot()
        if (
            self._baseline is not None
            and self._converged_at is None
            and tick >= spec.heal_tick
            and self._matches(self._snapshot(), self._baseline)
        ):
            self._converged_at = tick
            self.note(tick, "converged", tick - spec.heal_tick)

    # -- the drive ------------------------------------------------------

    async def run(self) -> dict:
        spec = self.spec
        await self._setup()
        checker = InvariantChecker(
            self.clock, lease_length=float(spec.lease_length)
        )
        try:
            with trace_mod.default_tracer().span(
                "workload.scenario", cat="workload",
                args={"scenario": spec.name, "seed": spec.seed},
            ):
                for tick in range(spec.ticks):
                    self._tick = tick
                    self.state.begin_tick(tick)
                    for g in self.generators:
                        await g.step(tick, self)
                    self._peak_population = max(
                        self._peak_population, self._population_count()
                    )
                    await self._step_elections(tick)
                    self._drive_federation(tick)
                    await self._refresh_clients(tick)
                    await self._drive_streams(tick)
                    for g in self.generators:
                        await g.after_refresh(tick, self)
                    sat = self._measure_bands(tick)
                    self._log_admission(tick)
                    self._check_federation(tick, checker)
                    self._track_reconvergence(tick)
                    self._feed_forecast(tick)
                    self._flight_record(tick, sat)
                    self.clock.advance(self.tick_interval)
        finally:
            await self._teardown()
        return self._verdict()

    # -- verdict --------------------------------------------------------

    def _scalars(self) -> Dict[str, float]:
        spec = self.spec
        top_series: List[float] = []
        all_series: List[float] = []
        stress_series: List[float] = []
        stress = set(int(t) for t in spec.stress_ticks)
        for tick, sat in zip(self._sat_ticks, self._sat_rows):
            top = max(sat)
            top_series.append(sat[top])
            all_series.extend(sat.values())
            if tick in stress:
                stress_series.append(sat[top])
        scalars: Dict[str, float] = {
            "peak_population": float(self._peak_population),
            "master_changes": float(self._master_changes),
            "stream_pushes": float(self._stream_pushes),
            "fed_capacity_violations": float(self._fed_violations),
            "completions": float(self.counters.get("completions", 0)),
            "preemptions": float(self.counters.get("preemptions", 0)),
            "epoch_changes": float(
                self.counters.get("epoch_changes", 0)
            ),
        }
        if self.frontends or self._frontend_final:
            scalars["frontend_frames"] = float(self._frontend_frames)
            scalars["frontend_held"] = float(sum(
                st.get("held", 0)
                for st in self._frontend_final.values()
            ))
        if self._refresh_attempts:
            scalars["refresh_ok_ratio"] = (
                self._refresh_ok / self._refresh_attempts
            )
        if top_series:
            scalars["top_band_satisfaction"] = sum(top_series) / len(
                top_series
            )
        if all_series:
            scalars["satisfaction_overall"] = sum(all_series) / len(
                all_series
            )
        if stress_series:
            scalars["top_band_satisfaction_stress"] = sum(
                stress_series
            ) / len(stress_series)
        if self._converged_at is not None and spec.heal_tick is not None:
            scalars["reconverge_ticks"] = float(
                self._converged_at - spec.heal_tick
            )
        return scalars

    def _band_tallies(self) -> Dict[int, Dict[str, int]]:
        tallies: Dict[int, Dict[str, int]] = {}
        for server in self.servers.values():
            adm = getattr(server, "_admission", None)
            if adm is None:
                continue
            for (method, band), counts in adm.tallies.items():
                if method != "GetCapacity":
                    continue
                entry = tallies.setdefault(
                    int(band),
                    {"admitted": 0, "shed": 0, "fast_fail": 0},
                )
                for key in entry:
                    entry[key] += counts.get(key, 0)
        return tallies

    def _verdict(self) -> dict:
        spec = self.spec
        scalars = self._scalars()
        specs = slo_mod.workload_slos(
            spec.gate_targets(), name_prefix=f"workload:{spec.name}"
        )
        verdicts = slo_mod.SloEngine(specs).evaluate(
            slo_mod.SloInputs(
                scalars=scalars,
                samples=self.samples,
                band_tallies=self._band_tallies(),
            )
        )
        for v in verdicts:
            if (
                v["slo"].endswith(":reconverge_ticks")
                and v["status"] == "no_data"
                and spec.heal_tick is not None
            ):
                # Never reconverged is a hard fail, not missing data.
                v["status"] = "fail"
                v["detail"] = {"note": "no reconvergence within the run"}
        comparator = slo_mod.TrajectoryComparator()
        for v in verdicts:
            v["delta_vs_prev"] = comparator.slo_delta(v)
        ok = all(v["status"] != "fail" for v in verdicts)
        if not ok and self.flight_dump is None:
            failed = next(
                v["slo"] for v in verdicts if v["status"] == "fail"
            )
            self.flight_dump = self.flightrec.dump(f"slo:{failed}")
        log_bytes = json.dumps(
            self.log, sort_keys=True, separators=(",", ":")
        ).encode()
        summary = {
            key: (round(value, 6) if isinstance(value, float) else value)
            for key, value in sorted(scalars.items())
        }
        if self.forecaster is not None:
            summary["forecaster"] = self.forecaster.status()
        return {
            "scenario": spec.name,
            "seed": spec.seed,
            "ok": ok,
            "ticks": spec.ticks,
            "tick_interval": self.tick_interval,
            "summary": summary,
            "frontend": self._frontend_final or None,
            "slo": {"ok": ok, "verdicts": verdicts},
            "flightrec_dump": self.flight_dump,
            "event_log": self.log,
            "log_sha256": hashlib.sha256(log_bytes).hexdigest(),
        }


def run_spec(spec: WorkloadSpec) -> dict:
    """Synchronous convenience: drive one spec, return the verdict."""
    return asyncio.run(WorkloadRunner(spec).run())
