"""Composable load-shape generators for the workload harness.

Each generator is a small state machine stepped once per virtual tick
BEFORE the tick's refreshes (arrivals, departures, deploys), with an
optional `after_refresh` hook AFTER them (work accrual, preemption
detection). Generators move load exclusively through the harness's
mutators (`arrive`/`depart`/`set_wants`/`deploy`/`note`), and draw all
randomness from the harness's seeded RNG — so a scenario's event log
replays byte-for-byte.

Registry kinds:

  * ``diurnal``      — arrivals paced by a piecewise-linear rate curve
                       (loadtest.ratecurve), weighted band mix, seeded
                       lifetimes;
  * ``flash_crowd``  — a burst population arriving at once (optionally
                       repeating with a period, the predictive
                       scenario's seasonal signal) and leaving together;
  * ``rolling_deploy`` — takes each server down in sequence (graceful
                       abdication, re-campaign after `down_ticks`);
  * ``multi_region`` — assigns every client a region with a seeded RTT
                       that rides the virtual refresh-latency samples;
  * ``elastic``      — fractional/elastic jobs (arxiv 1106.4985): work
                       accrues with whatever capacity is granted,
                       sustained starvation below `min_wants` preempts
                       (depart + requeue), jobs complete at
                       `total_work`;
  * ``trace``        — replays a recorded arrival log (inline
                       ``events`` rows or a ``loadtest.storm --record``
                       JSONL file): real traffic shapes re-run against
                       the virtual-clock harness, deterministically;
  * ``reshard``      — a fixed routing-epoch schedule for fleet specs
                       ([[tick, shards], ...] applied through
                       harness.fleet_reshard);
  * ``autoscale``    — the SLO-driven elastic shard count: per-tick
                       satisfaction verdicts feed fleet.Autoscaler
                       (hysteresis + cool-down) and its decisions
                       become live reshards.
"""

from __future__ import annotations

import json
import random
from typing import Dict, List, Optional

from doorman_tpu.loadtest.ratecurve import ArrivalSampler, RateCurve

__all__ = ["Generator", "GENERATORS", "build"]


class Generator:
    """Base: a no-op shape. Subclasses override setup/step hooks."""

    kind = "base"

    def __init__(self, params: dict):
        self.params = dict(params)

    async def setup(self, harness) -> None:
        pass

    async def step(self, tick: int, harness) -> None:
        pass

    async def after_refresh(self, tick: int, harness) -> None:
        pass

    def on_arrive(self, cid: str, harness) -> None:
        pass


class DiurnalArrivals(Generator):
    kind = "diurnal"

    def __init__(self, params: dict):
        super().__init__(params)
        p = self.params
        self.curve = RateCurve.parse(p["curve"])
        self.period = p.get("period")
        self.jitter = float(p.get("jitter", 0.0))
        # [[band, weight], ...] — the arrival band mix.
        self.bands = [
            (int(b), float(w)) for b, w in p.get("bands", [[0, 1.0]])
        ]
        self.wants = float(p.get("wants", 10.0))
        self.lifetime_ticks = int(p.get("lifetime_ticks", 10))
        self.max_population = int(p.get("max_population", 10_000))
        self.prefix = str(p.get("prefix", "d"))
        self._sampler: Optional[ArrivalSampler] = None
        self._serial = 0
        self._departures: Dict[int, List[str]] = {}
        self._alive = 0

    async def setup(self, harness) -> None:
        self._sampler = ArrivalSampler(
            self.curve, jitter=self.jitter, rng=harness.rng,
            period=self.period,
        )

    def _pick_band(self, rng: random.Random) -> int:
        total = sum(w for _, w in self.bands)
        roll = rng.random() * total
        acc = 0.0
        for band, weight in self.bands:
            acc += weight
            if roll < acc:
                return band
        return self.bands[-1][0]

    async def step(self, tick: int, harness) -> None:
        for cid in self._departures.pop(tick, []):
            await harness.depart(cid)
            self._alive -= 1
        t0 = tick * harness.tick_interval
        t1 = t0 + harness.tick_interval
        n = self._sampler.take(t0, t1)
        arrived = 0
        for _ in range(n):
            if self._alive >= self.max_population:
                break
            band = self._pick_band(harness.rng)
            cid = f"{self.prefix}{self._serial}"
            self._serial += 1
            await harness.arrive(cid, band, self.wants)
            life = max(
                1,
                int(self.lifetime_ticks
                    * (0.5 + harness.rng.random())),
            )
            self._departures.setdefault(tick + life, []).append(cid)
            self._alive += 1
            arrived += 1
        if arrived:
            harness.note(tick, "diurnal_arrive", arrived, self._alive)


class FlashCrowd(Generator):
    kind = "flash_crowd"

    def __init__(self, params: dict):
        super().__init__(params)
        p = self.params
        self.at = int(p["at"])
        self.duration = int(p.get("duration", 4))
        self.clients = int(p.get("clients", 20))
        self.band = int(p.get("band", 0))
        self.wants = float(p.get("wants", 10.0))
        self.period = p.get("period")
        self.repeats = int(p.get("repeats", 1))
        self.prefix = str(p.get("prefix", "fc"))
        self._crowd: List[str] = []
        self._cycle = 0

    def start_ticks(self) -> List[int]:
        if self.period is None:
            return [self.at]
        return [
            self.at + k * int(self.period) for k in range(self.repeats)
        ]

    async def step(self, tick: int, harness) -> None:
        if tick in self.start_ticks() and not self._crowd:
            for i in range(self.clients):
                cid = f"{self.prefix}{self._cycle}_{i}"
                await harness.arrive(cid, self.band, self.wants)
                self._crowd.append(cid)
            harness.note(tick, "crowd_start", self._cycle, self.clients)
            self._end = tick + self.duration
            self._cycle += 1
        elif self._crowd and tick >= self._end:
            crowd, self._crowd = self._crowd, []
            for cid in crowd:
                await harness.depart(cid)
            harness.note(tick, "crowd_end", self._cycle - 1, len(crowd))


class RollingDeploy(Generator):
    kind = "rolling_deploy"

    def __init__(self, params: dict):
        super().__init__(params)
        p = self.params
        self.at = int(p.get("at", 5))
        self.down_ticks = int(p.get("down_ticks", 3))
        self.gap_ticks = int(p.get("gap_ticks", 4))

    async def step(self, tick: int, harness) -> None:
        stride = self.down_ticks + self.gap_ticks
        for i in range(harness.spec.servers):
            if tick == self.at + i * stride:
                await harness.deploy(i, self.down_ticks)


class MultiRegionRtt(Generator):
    kind = "multi_region"

    def __init__(self, params: dict):
        super().__init__(params)
        # [[name, rtt_ms, weight], ...]
        self.regions = [
            (str(n), float(rtt), float(w))
            for n, rtt, w in self.params.get(
                "regions",
                [["local", 2.0, 1.0], ["near", 40.0, 1.0],
                 ["far", 150.0, 1.0]],
            )
        ]

    def _assign(self, cid: str, harness) -> None:
        total = sum(w for _, _, w in self.regions)
        roll = harness.rng.random() * total
        acc = 0.0
        for name, rtt_ms, weight in self.regions:
            acc += weight
            if roll < acc:
                break
        harness.client_meta.setdefault(cid, {}).update(
            region=name, rtt_ms=rtt_ms
        )

    async def setup(self, harness) -> None:
        for cid in harness.client_ids():
            self._assign(cid, harness)

    def on_arrive(self, cid: str, harness) -> None:
        self._assign(cid, harness)


class ElasticJobs(Generator):
    """Fractional/elastic jobs: each job wants up to `max_wants` but
    makes progress with ANY grant (work += grant * tick_interval). A
    grant below `min_wants` for `patience` consecutive ticks preempts
    the job — it releases its lease and requeues `requeue_ticks` later
    with its accrued work intact. A job completes (departs for good)
    at `total_work`."""

    kind = "elastic"

    def __init__(self, params: dict):
        super().__init__(params)
        p = self.params
        self.jobs = int(p.get("jobs", 8))
        self.band = int(p.get("band", 0))
        self.min_wants = float(p.get("min_wants", 5.0))
        self.max_wants = float(p.get("max_wants", 20.0))
        self.total_work = float(p.get("total_work", 200.0))
        self.patience = int(p.get("patience", 2))
        self.requeue_ticks = int(p.get("requeue_ticks", 3))
        self.start_tick = int(p.get("start_tick", 0))
        self.prefix = str(p.get("prefix", "e"))
        # cid -> {"work", "starve"}; requeues: tick -> [cid]
        self._state: Dict[str, Dict[str, float]] = {}
        self._running: List[str] = []
        self._requeue: Dict[int, List[str]] = {}

    async def step(self, tick: int, harness) -> None:
        if tick == self.start_tick:
            for i in range(self.jobs):
                cid = f"{self.prefix}{i}"
                self._state[cid] = {"work": 0.0, "starve": 0}
                await harness.arrive(cid, self.band, self.max_wants)
                self._running.append(cid)
            harness.note(tick, "elastic_start", self.jobs)
        for cid in self._requeue.pop(tick, []):
            await harness.arrive(cid, self.band, self.max_wants)
            self._state[cid]["starve"] = 0
            self._running.append(cid)
            harness.note(tick, "elastic_requeue", cid)

    async def after_refresh(self, tick: int, harness) -> None:
        for cid in list(self._running):
            st = self._state[cid]
            grant = harness.grant_of(cid)
            st["work"] += grant * harness.tick_interval
            if st["work"] >= self.total_work:
                self._running.remove(cid)
                await harness.depart(cid)
                harness.bump("completions")
                harness.note(
                    tick, "elastic_complete", cid,
                    round(st["work"], 6),
                )
                continue
            if grant < self.min_wants:
                st["starve"] += 1
                if st["starve"] >= self.patience:
                    self._running.remove(cid)
                    await harness.depart(cid)
                    harness.bump("preemptions")
                    self._requeue.setdefault(
                        tick + self.requeue_ticks, []
                    ).append(cid)
                    harness.note(
                        tick, "elastic_preempt", cid,
                        round(st["work"], 6),
                    )
            else:
                st["starve"] = 0


class TraceReplay(Generator):
    """Replay a recorded arrival trace against the harness.

    Events come inline (``events: [[tick, band, wants], ...]``) or
    from a JSONL ``path`` — one object per line with ``tick`` plus
    optional ``band``/``wants``, the format ``loadtest.storm --record``
    writes — so a storm captured against a real deployment re-runs as
    a deterministic scenario. Each event arrives one client at its
    tick; ``lifetime_ticks > 0`` departs it that many ticks later
    (0: it stays for the run). Draws no randomness: the trace IS the
    schedule."""

    kind = "trace"

    def __init__(self, params: dict):
        super().__init__(params)
        p = self.params
        self.events = p.get("events")
        self.path = str(p.get("path", ""))
        if self.events is None and not self.path:
            raise ValueError("trace generator needs events or path")
        self.lifetime_ticks = int(p.get("lifetime_ticks", 0))
        self.prefix = str(p.get("prefix", "tr"))
        self._by_tick: Dict[int, List[tuple]] = {}
        self._serial = 0
        self._departures: Dict[int, List[str]] = {}

    async def setup(self, harness) -> None:
        events = self.events
        if events is None:
            events = []
            with open(self.path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    rec = json.loads(line)
                    if isinstance(rec, dict):
                        events.append([
                            rec["tick"], rec.get("band", 0),
                            rec.get("wants", 10.0),
                        ])
                    else:
                        events.append(rec)
        self._by_tick = {}
        for t, band, wants in events:
            self._by_tick.setdefault(int(t), []).append(
                (int(band), float(wants))
            )

    async def step(self, tick: int, harness) -> None:
        for cid in self._departures.pop(tick, []):
            await harness.depart(cid)
        arrivals = self._by_tick.get(tick, [])
        for band, wants in arrivals:
            cid = f"{self.prefix}{self._serial}"
            self._serial += 1
            await harness.arrive(cid, band, wants)
            if self.lifetime_ticks > 0:
                self._departures.setdefault(
                    tick + self.lifetime_ticks, []
                ).append(cid)
        if arrivals:
            harness.note(tick, "trace_arrive", len(arrivals))


class ReshardSchedule(Generator):
    """A fixed routing-epoch schedule for fleet specs: ``schedule``
    rows of [tick, shards] applied in order through
    harness.fleet_reshard. Draws no randomness — the schedule IS the
    policy (the autoscale generator is the closed-loop variant)."""

    kind = "reshard"

    def __init__(self, params: dict):
        super().__init__(params)
        self.schedule = {
            int(t): int(m)
            for t, m in self.params.get("schedule", [])
        }
        if not self.schedule:
            raise ValueError("reshard generator needs a schedule")

    async def step(self, tick: int, harness) -> None:
        target = self.schedule.get(tick)
        if target is not None:
            harness.fleet_reshard(target)


class AutoscaleFleet(Generator):
    """SLO-driven elastic shard count. After each tick's refreshes the
    generator renders the tick's satisfaction as a min-kind verdict
    against ``target`` (observed < target fails; margin = observed -
    target) and feeds it to a fleet.Autoscaler — sustained failure
    grows the active set by ``scale_step``, sustained pass with at
    least ``shrink_margin`` headroom shrinks it, hysteresis and
    cool-down guard against flapping. Decisions apply immediately via
    harness.fleet_reshard, so the NEXT beat re-splits the straddle
    shares over the new active set. Deterministic: satisfaction is
    plan arithmetic and the autoscaler draws no randomness."""

    kind = "autoscale"

    def __init__(self, params: dict):
        super().__init__(params)
        p = self.params
        from doorman_tpu.fleet import Autoscaler

        self.target = float(p.get("target", 0.9))
        self.scaler = Autoscaler(
            min_shards=int(p["min_shards"]),
            max_shards=int(p["max_shards"]),
            step=int(p.get("scale_step", 1)),
            hysteresis=int(p.get("hysteresis", 3)),
            cooldown=int(p.get("cooldown", 6)),
            shrink_margin=float(p.get("shrink_margin", 0.0)),
        )

    async def after_refresh(self, tick: int, harness) -> None:
        # _measure_bands runs after the generators' after_refresh, so
        # measure this tick's satisfaction directly (same arithmetic).
        if harness._vector is not None:
            wants_by, gets_by = harness._vector.measure_bands()
        else:
            wants_by = {}
            gets_by = {}
            for client in harness.clients.values():
                for res in client.resources.values():
                    band = int(res.priority)
                    wants_by[band] = wants_by.get(band, 0.0) + float(
                        res.wants
                    )
                    gets_by[band] = gets_by.get(band, 0.0) + min(
                        res.current_capacity(), float(res.wants)
                    )
        total_wants = sum(wants_by.values())
        if total_wants <= 0:
            return
        observed = sum(gets_by.values()) / total_wants
        verdict = {
            "slo": "autoscale:satisfaction",
            "status": "pass" if observed >= self.target else "fail",
            "margin": observed - self.target,
        }
        decided = self.scaler.observe(
            tick, [verdict], harness.federation.active
        )
        if decided is not None:
            harness.note(
                tick, "autoscale",
                self.scaler.decisions[-1]["reason"],
                harness.federation.active, decided,
            )
            harness.fleet_reshard(decided)


GENERATORS = {
    cls.kind: cls
    for cls in (
        DiurnalArrivals, FlashCrowd, RollingDeploy, MultiRegionRtt,
        ElasticJobs, TraceReplay, ReshardSchedule, AutoscaleFleet,
    )
}


def build(spec) -> List[Generator]:
    out = []
    for g in spec.generators:
        cls = GENERATORS.get(g.kind)
        if cls is None:
            raise ValueError(
                f"unknown generator kind {g.kind!r} "
                f"(known: {sorted(GENERATORS)})"
            )
        out.append(cls(g.as_params()))
    return out
