"""Array-backed macro-client population for the workload harness.

The per-client engine steps one real `Client` per macro client per
tick (`harness._refresh_clients`): a million-client tick is a million
awaited RPCs, which ROADMAP.md names as the driver ceiling now that
the serving plane can hold the streams. This module replaces the
coroutines with a table: every per-client fact the refresh loop reads
— band, wants, client-side lease (has / expiry / fallback / safe
capacity), server-side lease mirror, region RTT, shard pin — lives in
one numpy column, and a tick refreshes the due set with a handful of
batched calls into the server's bulk decide seam
(`CapacityServer.decide_bulk` -> `coalesce.decide_grouped_arrays`,
falling back to the sequential `decide_grouped`).

Parity contract (the vector-vs-clients `log_sha256` pin in
tests/test_workload_population.py): with ``refresh_spread == 1`` this
engine is byte-identical to the per-client path, because every
observable effect is replayed in the same order —

  * rows are append-only and stepped in row (= insertion) order, the
    order `dict` iteration gives the per-client loop; a departed id
    that re-arrives gets a NEW row, exactly like a dict pop+reinsert;
  * admission draws come from the same shared controller RNG in due
    order (`Admission.check_get_capacity_many`; per-row
    `check_get_capacity_band` when federation makes several
    controllers share the stream), before any decide — decides draw no
    randomness, so batching the draws preserves the sequence;
  * store mutations replay through `decide_bulk`, whose array pass is
    grant-exact with the sequential path (see
    coalesce.decide_grouped_arrays' exactness argument) and whose
    fallback IS the sequential path;
  * client-side lease semantics mirror client.py exactly: expiry is
    the response's ``int()``-truncated ``expiry_time`` (np.floor for
    positive floats), a FAILED refresh keeps leases and only an
    expired one (strict ``expiry < now``) falls back to the last
    server-sent safe capacity (or 0.0), and a successful refresh
    clears the fallback;
  * the RTT jitter draws (`meas_rng`) happen per due rtt-carrying row
    in row order — the same subsequence the per-client loop draws.

Routing replays the connection layer's redirect chase without the
RPCs, including its stickiness: each row carries the server its
virtual `Connection` is parked on (`conn`, -1 = no channel yet, which
dials the shard seed like `Connection.addr`), and a refresh follows
``current_master`` address pointers from there — parking on every hop
exactly as `Connection._connect` does, failing with the row parked in
place when a pointer is empty (`MasterUnknown`) or the 5-hop sleepless
budget runs out. The distinction matters at a mastership flip: a row
parked on the old master fails that tick if the old master's pointer
is still empty, even though the new master already holds the lock —
the same one-tick blindness the per-client path exhibits (harness
clients run with ``max_retries=0``, so one chase per refresh).
Departures replay `Client.close()`: one ReleaseCapacity against the
current master (never shed, `note_pass_through` + store release), or
nothing when there is no master — leases then self-expire.

Scale discipline (the `workload_population_scaling` bench row): a tick
must cost O(due set), never O(population). Due selection is a
deadline wheel (`refresh_spread` buckets of row indices, compacted as
rows die); the expired-lease precondition is a lazy scalar lower
bound over the mirrored server expiries (recomputed only when the
clock passes it); native client handles are interned once per row per
engine generation and passed as arrays, so the fast path never
materializes a million id strings.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from doorman_tpu.proto import doorman_pb2 as pb

__all__ = ["VectorPopulation"]

_NAN = float("nan")
_INF = float("inf")


class VectorPopulation:
    """The array population behind ``population_engine: "vector"``.

    Owns no servers and no sockets: the runner passes itself in, and
    the engine drives `runner.servers` in-process through the same
    handler-adjacent seams the loopback clients reach by RPC.
    """

    def __init__(self, runner):
        self.runner = runner
        spec = runner.spec
        self.rid = str(spec.resource)
        self.spread = max(1, int(spec.refresh_spread))
        self.fed = spec.federated_config() is not None
        self._n = 0
        self._cap = 0
        self._active_count = 0
        self._ids: List[str] = []
        self._row: Dict[str, int] = {}
        self._alloc(1024)
        # Deadline wheel: per-phase chunks of row indices (arrays from
        # bulk arrivals, one-element arrays from singles), compacted to
        # the live rows each time the bucket comes due.
        self._buckets: List[List[np.ndarray]] = [
            [] for _ in range(self.spread)
        ]
        # Rows awaiting their FIRST refresh ahead of their wheel slot
        # (spread > 1 only; at spread 1 every row is due every tick).
        self._pending_first: List[int] = []
        # Server-side mirror binding, one entry per shard group (the
        # non-federated topology is one group): which server+store the
        # srv_* mirrors describe. A mastership flip wipes the server's
        # resources, so a changed binding invalidates the mirrors.
        self._bound: Dict[int, tuple] = {}
        self._live: Dict[int, int] = {}
        self._srv_min: Dict[int, float] = {}
        # Native client-handle cache: (engine, row-aligned int64 array,
        # -1 = not interned against this engine generation).
        self._hcache: Optional[Tuple[object, np.ndarray]] = None
        # Proxy address -> server index, built on first chase (the
        # proxies do not exist yet when the runner constructs us).
        self._addr2idx: Optional[Dict[str, int]] = None
        # Introspection for tests and the scaling bench.
        self.step_walls: List[float] = []
        self.fast_rows_total = 0
        self.seq_rows_total = 0
        self.seq_ticks = 0

    # -- storage ---------------------------------------------------------

    def _alloc(self, cap: int) -> None:
        self.band = np.zeros(cap, np.int32)
        self.wants = np.zeros(cap, np.float64)
        self.rtt = np.full(cap, _NAN, np.float64)
        self.active = np.zeros(cap, bool)
        self.shard = np.zeros(cap, np.int32)
        # Client-side lease state (client.py's ClientResource).
        self.cli_has = np.zeros(cap, np.float64)
        self.cli_expiry = np.zeros(cap, np.float64)  # int()-cast values
        self.cli_lease = np.zeros(cap, bool)
        self.fallback = np.zeros(cap, np.float64)
        self.safe = np.zeros(cap, np.float64)
        self.has_safe = np.zeros(cap, bool)
        # Server-side lease mirror (exact floats out of decide_bulk).
        self.srv_has = np.zeros(cap, np.float64)
        self.srv_wants = np.zeros(cap, np.float64)
        self.srv_expiry = np.zeros(cap, np.float64)
        self.srv_live = np.zeros(cap, bool)
        # The server index this row's virtual Connection is parked on
        # (-1: no channel; the next chase dials the shard seed).
        self.conn = np.full(cap, -1, np.int32)
        self._cap = cap

    _COLUMNS = (
        "band", "wants", "rtt", "active", "shard", "cli_has",
        "cli_expiry", "cli_lease", "fallback", "safe", "has_safe",
        "srv_has", "srv_wants", "srv_expiry", "srv_live", "conn",
    )

    def _ensure(self, extra: int) -> None:
        need = self._n + extra
        if need <= self._cap:
            return
        cap = self._cap
        while cap < need:
            cap *= 2
        old = {name: getattr(self, name) for name in self._COLUMNS}
        self._alloc(cap)
        for name, arr in old.items():
            getattr(self, name)[: self._n] = arr[: self._n]
        if self._hcache is not None:
            engine, handles = self._hcache
            grown = np.full(cap, -1, np.int64)
            grown[: self._n] = handles[: self._n]
            self._hcache = (engine, grown)

    # -- the mutator surface (harness.arrive/depart/grant_of) ------------

    def arrive(
        self, cid: str, band: int, wants: float,
        shard: Optional[int] = None,
    ) -> None:
        if cid in self._row:
            raise ValueError(f"client id {cid!r} already present")
        self._ensure(1)
        i = self._n
        self.band[i] = int(band)
        self.wants[i] = float(wants)
        self.rtt[i] = _NAN
        self.active[i] = True
        self.shard[i] = 0 if shard is None else int(shard)
        self.cli_lease[i] = False
        self.cli_has[i] = 0.0
        self.fallback[i] = 0.0
        self.has_safe[i] = False
        self.srv_live[i] = False
        self.conn[i] = -1
        self._ids.append(cid)
        self._row[cid] = i
        self._n = i + 1
        self._active_count += 1
        self._buckets[i % self.spread].append(
            np.array([i], np.int64)
        )
        if self.spread > 1:
            self._pending_first.append(i)

    def bulk_arrive(
        self, ids: List[str], band: int, wants: float,
        shard: Optional[int] = None, first_refresh: str = "wheel",
    ) -> None:
        """Append a block of identical-shape rows in one shot (the
        base_population expansion). ``first_refresh="wheel"`` lets the
        deadline wheel stage lease establishment over one revolution —
        the parked-million setup; ``"now"`` queues every row for the
        next tick like `arrive` does."""
        n = len(ids)
        if not n:
            return
        self._ensure(n)
        start, end = self._n, self._n + n
        rows = np.arange(start, end, dtype=np.int64)
        self.band[start:end] = int(band)
        self.wants[start:end] = float(wants)
        self.rtt[start:end] = _NAN
        self.active[start:end] = True
        self.shard[start:end] = 0 if shard is None else int(shard)
        self.cli_lease[start:end] = False
        self.cli_has[start:end] = 0.0
        self.fallback[start:end] = 0.0
        self.has_safe[start:end] = False
        self.srv_live[start:end] = False
        self.conn[start:end] = -1
        self._ids.extend(ids)
        self._row.update(zip(ids, range(start, end)))
        self._n = end
        self._active_count += n
        for p in range(self.spread):
            first = start + ((p - start) % self.spread)
            if first < end:
                self._buckets[p].append(
                    np.arange(first, end, self.spread, dtype=np.int64)
                )
        if self.spread > 1 and first_refresh == "now":
            self._pending_first.extend(rows.tolist())

    def set_rtt(self, cid: str, rtt_ms: float) -> None:
        self.rtt[self._row[cid]] = float(rtt_ms)

    async def depart(self, cid: str) -> None:
        """Replay `Client.close()`'s release leg: one ReleaseCapacity
        against the current master (the redirect chase's terminus), or
        nothing when there is none — the lease then self-expires."""
        i = self._row.pop(cid, None)
        if i is None:
            return
        self.active[i] = False
        self._active_count -= 1
        key = int(self.shard[i]) if self.fed else 0
        land, parked = self._chase(int(self.conn[i]), int(self.shard[i]))
        self.conn[i] = parked
        if land < 0:
            return  # close() swallows the error; leases self-expire
        server = self.runner.servers.get(f"s{land}")
        if server is None:
            return
        req = pb.ReleaseCapacityRequest(
            client_id=cid, resource_id=[self.rid]
        )
        out = pb.ReleaseCapacityResponse()
        await server._release_capacity(
            req, None, out, server._clock(), False
        )
        # The release only touched the store our mirrors describe if
        # the binding is still current (a stale binding is reset on the
        # next refresh pass either way).
        store = self._store_of(server)
        if (
            self.srv_live[i]
            and self._bound.get(key) == self._token(server, store)
        ):
            self.srv_live[i] = False
            self._live[key] = self._live.get(key, 0) - 1

    def grant_of(self, cid: str) -> float:
        i = self._row.get(cid)
        if i is None:
            return 0.0
        if self.cli_lease[i]:
            return float(self.cli_has[i])
        return float(self.fallback[i])

    def client_ids(self) -> List[str]:
        return [
            self._ids[i] for i in range(self._n) if self.active[i]
        ]

    def population(self) -> int:
        return self._active_count

    # -- routing / server-mirror bookkeeping -----------------------------

    def _addr_index(self) -> Dict[str, int]:
        """Proxy address -> server index (addresses are stable for the
        life of a run; server OBJECTS behind them may be redeployed, so
        lookups resolve `runner.servers[f"s{i}"]` live)."""
        if self._addr2idx is None:
            self._addr2idx = {
                proxy.address: int(name[1:])
                for name, proxy in self.runner.proxies.items()
            }
        return self._addr2idx

    def _chase(self, conn: int, seed: int) -> Tuple[int, int]:
        """Replay one `Connection.execute` mastership chase (the
        harness clients run with ``max_retries=0``: exactly one chase
        per refresh, no backoff re-dial). Returns ``(landing, parked)``
        server indices — landing is -1 when the chase fails
        (`MasterUnknown` / hop budget), with the connection parked
        wherever `_connect` last left it; a dead dial closes the
        channel (parked -1) like the transport-error path does."""
        servers = self.runner.servers
        addr2idx = self._addr_index()
        if conn < 0:
            conn = seed
        hops = 0
        while True:
            server = servers.get(f"s{conn}")
            if server is None:
                return -1, -1
            if server.is_master:
                return conn, conn
            ptr = server.current_master
            if not ptr:
                return -1, conn
            hops += 1
            if hops > 5:
                return -1, conn
            nxt = addr2idx.get(ptr)
            if nxt is None:
                return -1, -1
            conn = nxt

    def _route_rows(self, rows: np.ndarray) -> np.ndarray:
        """Chase every row's connection (grouped by identical parked
        state, so the cost is O(distinct states), not O(rows)), park
        the connections where the chases leave them, and return each
        row's landing server index (-1: the refresh fails this tick)."""
        conn = self.conn[rows]
        seeds = self.shard[rows]
        landed = np.full(rows.size, -1, np.int32)
        parked = conn.copy()
        pairs = np.unique(
            np.stack((conn.astype(np.int64), seeds.astype(np.int64))),
            axis=1,
        )
        for c, s in pairs.T.tolist():
            land, park = self._chase(int(c), int(s))
            m = (conn == c) & (seeds == s)
            landed[m] = land
            parked[m] = park
        self.conn[rows] = parked
        return landed

    def _store_of(self, server):
        res = server.resources.get(self.rid)
        return None if res is None else res.store

    @staticmethod
    def _token(server, store) -> tuple:
        # Strong references on purpose: an id()-based token could
        # collide when a wiped store's address is reused by its
        # replacement. Neither class defines __eq__, so the tuple
        # comparison is identity.
        return (server, store)

    def _group_mask(self, key: int) -> np.ndarray:
        mask = self.srv_live[: self._n]
        if self.fed:
            mask = mask & (self.shard[: self._n] == key)
        return mask

    def _sync_binding(self, key: int, server) -> None:
        """Reset the srv_* mirrors when they describe a previous store
        generation — a mastership flip wipes the server's resources, so
        every lease the mirrors remember is gone."""
        token = self._token(server, self._store_of(server))
        if self._bound.get(key) == token:
            return
        if self._live.get(key, 0):
            mask = self._group_mask(key)
            self.srv_live[: self._n][mask] = False
        self._live[key] = 0
        self._srv_min[key] = _INF
        self._bound[key] = token

    def _recompute_min(self, key: int) -> None:
        mask = self._group_mask(key)
        if mask.any():
            self._srv_min[key] = float(
                self.srv_expiry[: self._n][mask].min()
            )
        else:
            self._srv_min[key] = _INF

    def _sweep_expired(self, key: int, now: float) -> None:
        """After a sequential decide ran with expired mirrors: the
        store's clean() removed every lease with ``now > expiry`` —
        drop the same rows from the mirror."""
        mask = self._group_mask(key) & (
            now > self.srv_expiry[: self._n]
        )
        dead = int(np.count_nonzero(mask))
        if dead:
            self.srv_live[: self._n][mask] = False
            self._live[key] = self._live.get(key, 0) - dead
        self._recompute_min(key)

    def _handles_for(self, engine, rows: np.ndarray) -> np.ndarray:
        if self._hcache is None or self._hcache[0] is not engine:
            self._hcache = (engine, np.full(self._cap, -1, np.int64))
        handles = self._hcache[1]
        missing = rows[handles[rows] < 0]
        if missing.size:
            intern = engine.client_handle
            ids = self._ids
            for i in missing.tolist():
                handles[i] = intern(ids[i])
        return handles[rows]

    # -- the per-tick refresh pass ---------------------------------------

    def _due_rows(self, tick: int) -> np.ndarray:
        phase = tick % self.spread
        chunks = self._buckets[phase]
        if chunks:
            cat = chunks[0] if len(chunks) == 1 else np.concatenate(chunks)
            due = cat[self.active[cat]]
            # Compact: dead rows never come back, so the bucket keeps
            # only its live rows (O(live) forever, not O(ever-lived)).
            self._buckets[phase] = [due] if due.size else []
        else:
            due = np.empty(0, np.int64)
        if self._pending_first:
            pending = np.asarray(self._pending_first, np.int64)
            self._pending_first = []
            pending = pending[self.active[pending]]
            if pending.size:
                due = np.unique(np.concatenate((due, pending)))
        return due

    def step_refresh(self, tick: int) -> None:
        """One tick's refresh pass over the due set — the vector twin
        of `harness._refresh_clients`. Synchronous by design: every
        decide runs inline on the loop, the same discipline as the
        coalescer's window-0 submit."""
        r = self.runner
        t0 = time.perf_counter()
        due = self._due_rows(tick)
        if due.size == 0:
            r._offered_by_band = {}
            self.step_walls.append(time.perf_counter() - t0)
            return
        bands_due = self.band[due]
        offered: Dict[int, int] = {}
        for b, c in zip(*np.unique(bands_due, return_counts=True)):
            offered[int(b)] = int(c)
        r._refresh_attempts += int(due.size)
        now = r.clock()
        ok = np.zeros(due.size, bool)

        # Route first: one chase per distinct parked-connection state
        # gives every due row its landing server (-1: that row's chase
        # failed — no admission draw, no decide, lease-retention path).
        landed = self._route_rows(due)

        if self.fed:
            shards = self.shard[due]
            groups = [
                (int(s), np.flatnonzero(shards == s))
                for s in np.unique(shards)
            ]
        else:
            groups = [(0, np.arange(due.size, dtype=np.int64))]

        # Admission draws consume the SHARED seeded RNG: when several
        # controllers (federated shards) interleave on one due stream,
        # the draws must happen per row in due order — grouping first
        # would reorder the stream against the per-client path. A
        # single controller's subsequence stays contiguous, so the
        # batched `check_get_capacity_many` replays it exactly.
        fed_admission = self.fed and any(
            getattr(s, "_admission", None) is not None
            for s in r.servers.values()
        )
        admitted_by_pos: Optional[np.ndarray] = None
        if fed_admission:
            admitted_by_pos = np.zeros(due.size, bool)
            servers = r.servers
            for pos in range(due.size):
                land = int(landed[pos])
                if land < 0:
                    continue
                server = servers.get(f"s{land}")
                if server is None:
                    continue
                adm = getattr(server, "_admission", None)
                admitted_by_pos[pos] = (
                    True if adm is None
                    else adm.check_get_capacity_band(
                        int(bands_due[pos])
                    )
                )

        for key, gpos in groups:
            gl = gpos[landed[gpos] >= 0]
            if not gl.size:
                continue  # masterless / every chase failed this tick
            # Every successful chase in a group lands on the same
            # server: an election lock has one holder at a time, and
            # federated master pointers never cross shards.
            server = r.servers.get(f"s{int(landed[gl[0]])}")
            if server is None:
                continue
            if admitted_by_pos is not None:
                admitted = admitted_by_pos[gl]
            else:
                adm = getattr(server, "_admission", None)
                admitted = (
                    np.ones(gl.size, bool) if adm is None
                    else np.asarray(
                        adm.check_get_capacity_many(bands_due[gl]),
                        bool,
                    )
                )
            gpos_ok = gl[admitted]
            if not gpos_ok.size:
                continue
            sel = due[gpos_ok]

            self._sync_binding(key, server)
            live = self._live.get(key, 0)
            fast_ok = True
            if live > 0 and now > self._srv_min.get(key, _INF):
                # The lower bound tripped: find the true minimum; if
                # the clock really passed it, a sequential decide must
                # sweep the expired leases this tick.
                self._recompute_min(key)
                if now > self._srv_min[key]:
                    fast_ok = False

            w = self.wants[sel]
            prio = self.band[sel].astype(np.int64)
            has = np.where(self.cli_lease[sel], self.cli_has[sel], 0.0)
            srv_live_sel = self.srv_live[sel]
            old_h = np.where(srv_live_sel, self.srv_has[sel], 0.0)
            old_w = np.where(srv_live_sel, self.srv_wants[sel], 0.0)
            new = ~srv_live_sel
            engine = getattr(server, "_store_engine", None)
            cids = handles = None
            if engine is not None:
                handles = self._handles_for(engine, sel)
            else:
                cids = [self._ids[i] for i in sel.tolist()]
            grants, expiry, _refresh, safe, fast_rows = server.decide_bulk(
                self.rid, cids, has, w, prio,
                old_has=old_h, old_wants=old_w, new_mask=new,
                cid_handles=handles,
                # -1 forces the count precondition to fail, which
                # routes the whole batch down the sequential path (the
                # one that sweeps expired leases).
                expected_count=(live if fast_ok else -1),
            )

            # Client side, exactly as client.py applies a response:
            # truncated expiry, stored safe capacity, cleared fallback.
            self.cli_has[sel] = grants
            self.cli_expiry[sel] = np.floor(expiry)
            self.cli_lease[sel] = True
            self.fallback[sel] = 0.0
            self.safe[sel] = safe
            self.has_safe[sel] = True
            # Server mirror: exact floats for the next tick's deltas.
            self.srv_has[sel] = grants
            self.srv_wants[sel] = w
            self.srv_expiry[sel] = expiry
            self.srv_live[sel] = True
            self._live[key] = live + int(np.count_nonzero(new))
            if not fast_ok:
                self._sweep_expired(key, now)
            self._srv_min[key] = min(
                self._srv_min.get(key, _INF), float(expiry.min())
            )
            self._bound[key] = self._token(
                server, self._store_of(server)
            )
            ok[gpos_ok] = True
            r._refresh_ok += int(sel.size)
            self.fast_rows_total += int(fast_rows)
            self.seq_rows_total += int(sel.size) - int(fast_rows)
            if fast_rows < sel.size:
                self.seq_ticks += 1

        failed = due[~ok]
        if failed.size:
            # A failed refresh keeps leases; only an expired one
            # (strict, against the int-cast client expiry) falls back
            # to the last server-sent safe capacity, else 0.0.
            exp = failed[
                self.cli_lease[failed] & (self.cli_expiry[failed] < now)
            ]
            if exp.size:
                self.fallback[exp] = np.where(
                    self.has_safe[exp], self.safe[exp], 0.0
                )
                self.cli_lease[exp] = False

        # Measurement streams (outside the log digest): the bulk wall
        # amortized per due client, and the modeled-WAN virtual latency
        # with its seeded jitter drawn per rtt-carrying row in order.
        wall_ms = (time.perf_counter() - t0) * 1000.0
        r.samples["get_capacity_wall_ms"].extend(
            [wall_ms / due.size] * int(due.size)
        )
        rtt_due = self.rtt[due]
        with_rtt = np.flatnonzero(~np.isnan(rtt_due))
        if with_rtt.size:
            meas = r.meas_rng
            out = r.samples["refresh_virtual_ms"]
            for pos in with_rtt.tolist():
                out.append(
                    1.0 + rtt_due[pos] * (0.9 + 0.2 * meas.random())
                )
        r._offered_by_band = offered
        self.step_walls.append(time.perf_counter() - t0)

    # -- measurement -----------------------------------------------------

    def measure_bands(self) -> Tuple[Dict[int, float], Dict[int, float]]:
        """Per-band (wants, gets) sums over the live population.
        np.bincount accumulates its input strictly in order, so each
        band's float additions replay in row (= insertion) order —
        the same accumulation sequence as the per-client loop."""
        act = np.flatnonzero(self.active[: self._n])
        if not act.size:
            return {}, {}
        bands = self.band[act]
        w = self.wants[act]
        cur = np.where(
            self.cli_lease[act], self.cli_has[act], self.fallback[act]
        )
        g = np.minimum(cur, w)
        minlength = int(bands.max()) + 1
        wants_sum = np.bincount(bands, weights=w, minlength=minlength)
        gets_sum = np.bincount(bands, weights=g, minlength=minlength)
        wants_by: Dict[int, float] = {}
        gets_by: Dict[int, float] = {}
        for b in np.unique(bands).tolist():
            wants_by[int(b)] = float(wants_sum[b])
            gets_by[int(b)] = float(gets_sum[b])
        return wants_by, gets_by

    def snapshot(self, base_ids: List[str]) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for cid in base_ids:
            i = self._row.get(cid)
            if i is None:
                continue
            out[f"{cid}/{self.rid}"] = (
                float(self.cli_has[i]) if self.cli_lease[i]
                else float(self.fallback[i])
            )
        return out
