"""Trace-driven workload harness: closed-loop scenarios over the real
server on the virtual ChaosClock, gated by machine-readable SLO
verdicts.

The package composes the substrate the repo already has — ChaosClock,
the chaos runner's stepped loopback topology, the SLO engine, the
flight recorder, the rate-curve driver — into *named scenarios* that
measure user-visible outcomes (per-band satisfaction, goodput under
shedding, reconvergence after disturbances) instead of tick wall-time:

  * `spec`       — the declarative WorkloadSpec (population, band mix,
                   generators, gates);
  * `generators` — composable load shapes: diurnal curves, flash
                   crowds, rolling deploys, multi-region RTTs, elastic
                   jobs with preemption;
  * `forecast`   — the device-batched seasonal demand forecaster (numpy
                   host oracle, bit-identity pinned) behind the
                   predictive-admission scenario;
  * `harness`    — WorkloadRunner: drives the topology tick by tick and
                   returns a verdict with a byte-stable event log;
  * `scenarios`  — the named scenario library and its registry.

Run one: ``python -m doorman_tpu.cmd.workload --scenario flash_crowd``.
See doc/workload.md.
"""

from __future__ import annotations

__all__ = ["run_scenario", "SCENARIOS", "WorkloadSpec", "WorkloadRunner"]


def __getattr__(name):
    # Lazy re-exports: importing the package must not pull grpc/jax.
    if name in ("run_scenario", "SCENARIOS"):
        from doorman_tpu.workload import scenarios

        return getattr(scenarios, name)
    if name == "WorkloadSpec":
        from doorman_tpu.workload.spec import WorkloadSpec

        return WorkloadSpec
    if name == "WorkloadRunner":
        from doorman_tpu.workload.harness import WorkloadRunner

        return WorkloadRunner
    raise AttributeError(name)
