"""The declarative workload spec: everything a scenario run needs,
as plain data.

A WorkloadSpec is the workload harness's counterpart of a chaos
FaultPlan: topology (servers, algorithm, capacity, admission,
federation), the base client population (per-band wants), the
composable generators that move load during the run (GeneratorSpec
rows, built by `generators.build`), and the SLO gates the verdict is
judged against. Specs are frozen and JSON-round-trippable
(`as_dict`/`from_dict`) so a scenario is reproducible from its
serialized form alone — same spec + same seed, same event log bytes.
"""

from __future__ import annotations

from dataclasses import asdict, dataclass, field, replace
from typing import Any, Dict, Mapping, Optional, Tuple

__all__ = ["GeneratorSpec", "WorkloadSpec"]


def _freeze(value):
    """Dicts/lists -> tuples of sorted pairs / tuples, recursively, so
    frozen specs hash and compare structurally."""
    if isinstance(value, Mapping):
        return tuple(
            (k, _freeze(v)) for k, v in sorted(value.items())
        )
    if isinstance(value, (list, tuple)):
        return tuple(_freeze(v) for v in value)
    return value


def _thaw(value):
    if isinstance(value, tuple):
        if value and all(
            isinstance(v, tuple) and len(v) == 2
            and isinstance(v[0], str) for v in value
        ):
            return {k: _thaw(v) for k, v in value}
        return [_thaw(v) for v in value]
    return value


@dataclass(frozen=True)
class GeneratorSpec:
    """One load shape: a `generators` registry kind plus its params."""

    kind: str
    params: tuple = ()  # frozen mapping (see _freeze)

    @classmethod
    def make(cls, kind: str, **params) -> "GeneratorSpec":
        return cls(kind=kind, params=_freeze(params))

    def as_params(self) -> Dict[str, Any]:
        out = _thaw(self.params)
        return out if isinstance(out, dict) else {}


@dataclass(frozen=True)
class WorkloadSpec:
    name: str
    ticks: int
    seed: int = 0
    tick_interval: float = 1.0
    # -- topology -------------------------------------------------------
    servers: int = 1
    election_ttl: float = 3.0
    capacity: float = 100.0
    safe_capacity: Optional[float] = None
    algorithm: str = "PROPORTIONAL_SHARE"
    algorithm_variant: Optional[str] = None
    lease_length: float = 60.0
    refresh_interval: float = 1.0
    learning_mode_duration: float = 0.0
    resource: str = "r0"
    # Admission controller kwargs (None: no admission front-end).
    admission: tuple = ()
    # Federated topology: {"straddle": [rid...], "client_shards": [...]}
    # (each server becomes a shard with its own election lock).
    federated: tuple = ()
    # -- population -----------------------------------------------------
    # Base clients as (band, wants) pairs, attached before tick 0.
    base_clients: tuple = ()
    # Compact base population as (count, band, wants) rows — the
    # million-client form of base_clients (a spec listing 1e6 pairs
    # would dwarf the run it describes). Expanded after base_clients,
    # in row order.
    base_population: tuple = ()
    # Population engine: "clients" steps one real Client per macro
    # client per tick (the reference harness path); "vector" holds the
    # population as arrays (workload.population) and refreshes it in
    # batched grouped passes against the same servers. At small scale
    # the two produce byte-identical event logs (the parity pin in
    # tests/test_workload_population.py).
    population_engine: str = "clients"
    # Vector engine only: refresh each client every N ticks (deadline
    # wheel staggered by row). 1 refreshes everyone every tick — the
    # per-client path's cadence and the parity default. Million-client
    # scenarios raise it so a tick's due set is population/N.
    refresh_spread: int = 1
    # Back every server's resources with the native C++ store engine
    # (falls back to the Python store when the build is unavailable).
    native_store: bool = False
    # Streaming clients as (band, wants) pairs (WatchCapacity leg).
    stream_clients: tuple = ()
    # Serving-plane pool (doorman_tpu/frontend/): N listener workers
    # fanning WatchCapacity pushes through per-worker shared-memory
    # rings; 0 keeps the single-process in-server streaming path.
    frontend_workers: int = 0
    # Per-worker ring capacity in bytes (only read when workers > 0).
    frontend_ring: int = 1 << 20
    # Stream-shard count (stable client hash -> shard -> worker); >1 is
    # what spreads streams across the pool's workers.
    stream_shards: int = 1
    # -- load shapes ----------------------------------------------------
    generators: Tuple[GeneratorSpec, ...] = ()
    # -- predictive admission -------------------------------------------
    # {"period": P, "alpha": a, "beta": b, "engine": "auto"}; None keeps
    # the controller purely reactive.
    predictive: tuple = ()
    # -- measurement ----------------------------------------------------
    # Reconvergence: snapshot base clients at baseline_tick, expect the
    # snapshot to match again by heal_tick + the gate's budget. None
    # disables the reconvergence leg.
    baseline_tick: Optional[int] = None
    heal_tick: Optional[int] = None
    # Ticks whose top-band satisfaction feeds the "stress" scalar
    # (e.g. the crowd windows of later flash-crowd cycles).
    stress_ticks: tuple = ()
    # SLO gates: {gate_name: target}; see harness._build_specs for the
    # known gate names.
    gates: tuple = ()

    # -- accessors (thawed views of the frozen fields) ------------------

    def admission_kwargs(self) -> Dict[str, Any]:
        out = _thaw(self.admission)
        return out if isinstance(out, dict) else {}

    def federated_config(self) -> Optional[Dict[str, Any]]:
        out = _thaw(self.federated)
        return out if isinstance(out, dict) and out else None

    def predictive_config(self) -> Optional[Dict[str, Any]]:
        out = _thaw(self.predictive)
        return out if isinstance(out, dict) and out else None

    def gate_targets(self) -> Dict[str, float]:
        out = _thaw(self.gates)
        return out if isinstance(out, dict) else {}

    def with_(self, **changes) -> "WorkloadSpec":
        """replace() with the spec's freezing applied to dict-valued
        fields, so scenario factories can stay readable."""
        for key in (
            "admission", "federated", "predictive", "gates",
        ):
            if key in changes and isinstance(changes[key], Mapping):
                changes[key] = _freeze(changes[key])
        if "generators" in changes:
            changes["generators"] = tuple(changes["generators"])
        for key in (
            "base_clients", "base_population", "stream_clients",
            "stress_ticks",
        ):
            if key in changes:
                changes[key] = _freeze(changes[key])
        return replace(self, **changes)

    @classmethod
    def make(cls, name: str, ticks: int, **kw) -> "WorkloadSpec":
        return cls(name=name, ticks=int(ticks)).with_(**kw)

    def as_dict(self) -> Dict[str, Any]:
        out = asdict(self)
        for key in (
            "admission", "federated", "predictive", "gates",
        ):
            out[key] = _thaw(out[key]) or {}
        for key in (
            "base_clients", "base_population", "stream_clients",
            "stress_ticks",
        ):
            out[key] = _thaw(out[key]) or []
        out["generators"] = [
            {"kind": g.kind, "params": _thaw(g.params) or {}}
            for g in self.generators
        ]
        return out

    @classmethod
    def from_dict(cls, data: Mapping[str, Any]) -> "WorkloadSpec":
        data = dict(data)
        gens = tuple(
            GeneratorSpec.make(g["kind"], **(g.get("params") or {}))
            for g in data.pop("generators", [])
        )
        name = data.pop("name")
        ticks = data.pop("ticks")
        return cls.make(name, ticks, **data).with_(generators=gens)
