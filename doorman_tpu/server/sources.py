"""Config sources: blocking "next config version" abstraction.

Capability parity with reference go/configuration/configuration.go: a
Source is an async callable returning the next version of the raw config
bytes — LocalFile re-reads on SIGHUP (with an initial self-signal so the
first call returns immediately, configuration.go:31-53), Etcd gets then
watches a key (configuration.go:56-105), and parse_source dispatches on a
"file:" / "etcd:" prefix (configuration.go:109-121).

The etcd source speaks the v3 HTTP/JSON gateway through the shared
client in server/etcd.py — the same API generation the election lock
uses — in an executor thread, and raises a clear error at construction
if the endpoint list is empty.
"""

from __future__ import annotations

import asyncio
import logging
import signal
import weakref
from typing import Awaitable, Callable, List, Optional

from doorman_tpu.server.etcd import EtcdGateway
from doorman_tpu.utils.backoff import MIN_BACKOFF, MAX_BACKOFF, backoff

log = logging.getLogger(__name__)

# A Source, awaited repeatedly, blocks until a new config version exists
# and returns its bytes (configuration.go:21-29).
Source = Callable[[], Awaitable[bytes]]


# All live file sources share one SIGHUP handler that wakes every one of
# them — a per-source add_signal_handler would silently clobber the
# previous source's handler. Each event is woken via its OWN loop
# (call_soon_threadsafe): the handler runs on the main thread's loop, but
# a source may live on a loop in another thread, and Event.set() is not
# thread-safe. WeakKeyDictionary so abandoned sources get collected.
_sighup_events: "weakref.WeakKeyDictionary[asyncio.Event, asyncio.AbstractEventLoop]" = (
    weakref.WeakKeyDictionary()
)


def _on_sighup() -> None:
    for event, loop in list(_sighup_events.items()):
        try:
            loop.call_soon_threadsafe(event.set)
        except RuntimeError:
            pass  # that source's loop already closed; wake the rest


def local_file(path: str,
               loop: Optional[asyncio.AbstractEventLoop] = None) -> Source:
    """Re-reads `path` every time SIGHUP arrives; the first call reads
    immediately (the reference self-sends SIGHUP at setup,
    configuration.go:36)."""
    event = asyncio.Event()
    event.set()  # initial read
    loop = loop or asyncio.get_event_loop()
    _sighup_events[event] = loop
    try:
        loop.add_signal_handler(signal.SIGHUP, _on_sighup)
    except (NotImplementedError, RuntimeError, ValueError):
        # Non-unix platform, or the loop runs off the main thread
        # (add_signal_handler raises ValueError there).
        log.warning("SIGHUP reload unavailable; config loads once")

    async def source() -> bytes:
        await event.wait()
        event.clear()
        return await asyncio.get_event_loop().run_in_executor(
            None, lambda: open(path, "rb").read()
        )

    return source


def etcd(key: str, endpoints: List[str]) -> Source:
    """Gets `key`, then blocks on a watch for each subsequent version,
    retrying with backoff on errors (configuration.go:56-105)."""
    gateway = EtcdGateway(endpoints)
    state = {"last": None, "retries": 0}

    async def source() -> bytes:
        loop = asyncio.get_event_loop()
        while True:
            watch_ok = True
            if state["last"] is not None:
                watch_ok = await loop.run_in_executor(
                    None, gateway.wait_for_change, key
                )
            try:
                value = await loop.run_in_executor(None, gateway.get, key)
            except Exception:
                log.exception("etcd get %r failed", key)
                value = None
            if value is not None and value != state["last"]:
                state["last"] = value
                state["retries"] = 0
                return value
            # Missing key, broken watch, or unchanged value: sleep instead
            # of busy-reloading the same config. Only actual errors (no
            # value, or a watch that could not be established) escalate
            # the backoff — a healthy idle key keeps the minimum sleep, so
            # a real change is still picked up within one watch cycle.
            await asyncio.sleep(
                backoff(MIN_BACKOFF, MAX_BACKOFF, state["retries"])
            )
            if value is None or not watch_ok:
                state["retries"] += 1

    return source


def parse_source(text: str, etcd_endpoints: Optional[List[str]] = None,
                 loop: Optional[asyncio.AbstractEventLoop] = None) -> Source:
    """Dispatch on "file:<path>" or "etcd:<key>" (configuration.go:109)."""
    kind, sep, path = text.partition(":")
    if not sep:
        raise ValueError(f"config source needs a 'file:'/'etcd:' prefix: "
                         f"{text!r}")
    if kind == "file":
        return local_file(path, loop=loop)
    if kind == "etcd":
        return etcd(path, etcd_endpoints or [])
    raise ValueError(f"unknown config source kind {kind!r} in {text!r}")
