"""Tick-edge lease push: the sharded WatchCapacity subscription registry.

One `StreamRegistry` per server owns every open WatchCapacity stream.
The subscriber space is partitioned across N `StreamShard`s keyed by
the federation router's stable blake2b hash of the client id
(federation/router.stable_shard — the same cross-process contract that
routes resources to root shards), each shard owning its subscriptions,
outbound queues, band counts, silent-refresh deadline wheel, and seq
counter. At every tick edge the server hands the registry the work the
device matcher extracted — exactly the (subscription, changed row)
pairs (server/match.py intersects the engine's device-extracted
changed-rid set with a device-resident incidence structure) — plus the
subscriptions due their silent refresh beat, and each shard runs ONE
grouped per-resource decide pass over its slice (the same grouped
machinery the admission coalescer uses, admission/coalesce.py
decide_grouped), so per-tick fanout cost scales with
changed rows x affected subscribers, never with total subscribers.

A push carries exactly the bytes a poll at the same instant would have
carried; change detection compares the decide RESULT against the last
pushed lease, so parity with poll-every-tick holds even when the delta
filter over-approximates (it may only ever over-approximate — a missed
resource is caught at the subscription's next refresh beat, the same
staleness bound a polling client lives with). Sharding never changes
the bytes, by construction: the tick edge is TWO passes with different
partitions. The decide pass groups the whole edge's (subscription,
row) work per RESOURCE and replays each resource's decides in global
subscription-establishment order — scalar-regime decides (learning
mode, pre-first-solve warmup) water-fill against live store state and
are order-sensitive across clients of one resource, so the canonical
order must not depend on the shard count; different resources touch
disjoint stores (the admission coalescer's parity argument), which is
what makes the decide pass safely parallel ACROSS RESOURCE GROUPS.
The assemble pass then partitions per SHARD: change detection against
each subscription's last pushed key, row serialization, and message
building touch only shard-owned state and run one thread per shard.
tests/test_streaming.py pins the sharded push sequence byte-identical
to the single-shard path over churn, a flip, and mixed stores.

Wire batching: pushed messages are assembled as pre-serialized bytes.
Each changed row serializes ONCE per shard per tick edge — N
subscribers of one hot row share the serialized `ResourceResponse`
submessage (keyed by the observable lease value) — and a message is
the serialized header plus the framed row bytes, handed to gRPC as-is
(proto/grpc_api.py's stream serializer passes bytes through). Terminal
redirects stay message objects; the handler ends the stream on them.

Silent refresh: each subscription is refreshed (decide, no push unless
the lease moved) on its resources' refresh-interval cadence, exactly
like a polling client. Deadlines live in a per-shard bucket wheel
(granularity = the tick interval), so a quiet tick touches only the
due bucket — never all subscriptions; with nothing due and nothing
changed the fanout walks ZERO subscriptions (pinned by test).

Ordering / exactly-once: every pushed message carries a seq — the
persist journal's sequence number when persistence is configured, else
a per-shard counter. A stream is a single writer living on exactly one
shard, so seqs are strictly increasing per stream; clients drop
seq <= the last applied and offer the last seen seq back as
`resume_seq` on reconnect. Resume does not REPLAY history (none is
retained): the reconnect request's `has` fields are the client's
baseline, and the first message carries only the rows whose current
lease differs from it.

Concurrency: establishment, unsubscribe, and termination run on the
server's event loop; no locks. The post-tick fanout also runs on the
loop (push_streams blocks it), fanning the per-shard decide +
serialize passes to worker threads when that is safe — native store
without persistence, the admission coalescer's executor rule — with
each shard's state touched by exactly one thread and all queue
enqueues applied back on the loop after the shard passes join.
"""

from __future__ import annotations

import asyncio
import logging
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Sequence, Set, Tuple

from doorman_tpu.admission.coalesce import decide_grouped
from doorman_tpu.admission.policy import Shed
from doorman_tpu.algorithms import Request
from doorman_tpu.federation.router import stable_shard
from doorman_tpu.obs import trace as trace_mod
from doorman_tpu.proto import doorman_pb2 as pb
from doorman_tpu.proto import doorman_stream_pb2 as spb

log = logging.getLogger(__name__)

__all__ = ["StreamRegistry", "StreamShard", "Subscription"]

# Outbound queue depth per stream. A consumer this far behind (the
# fanout produces at tick cadence; a healthy stream drains in
# microseconds) is reset with a redirect-to-self terminal message — the
# client reconnects and resumes from its has-baseline, which is both
# cheaper and more correct than dropping arbitrary deltas.
QUEUE_SIZE = 256

# WatchCapacityResponse.response is field 3, wire type 2 (length-
# delimited): the tag byte every framed row chunk starts with. A
# serialized message is the header fields' bytes plus any permutation
# of framed submessage chunks — proto parsers accept fields in any
# order, so concatenation IS serialization.
_ROW_TAG = b"\x1a"


def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _frame_row(payload: bytes) -> bytes:
    """One repeated `response` field chunk: tag + length + row bytes."""
    return _ROW_TAG + _varint(len(payload)) + payload


class Subscription:
    """One open WatchCapacity stream (owned by exactly one shard)."""

    __slots__ = (
        "client_id", "band", "lines", "last", "queue", "next_refresh",
        "terminated", "shard", "match_slot", "order", "stream_id",
        "worker",
    )

    def __init__(self, client_id: str, band: int,
                 lines: Dict[str, Tuple[float, int]], shard: int = 0):
        self.client_id = client_id
        self.band = band
        # Global establishment sequence (set by the registry): the
        # canonical per-resource decide order of the fanout's decide
        # pass, independent of the shard count.
        self.order = 0
        # resource_id -> (wants, priority), fixed at establishment
        # (clients change wants by re-establishing the stream).
        self.lines = lines
        # resource_id -> (capacity, safe_capacity, refresh_interval):
        # the change-detection key of the last served lease.
        self.last: Dict[str, tuple] = {}
        self.queue: "asyncio.Queue" = asyncio.Queue(maxsize=QUEUE_SIZE)
        self.next_refresh = 0.0
        self.terminated = False
        self.shard = shard
        # Device-matcher slot (server/match.py); owned by the server.
        self.match_slot: "int | None" = None
        # Frontend pool routing (doorman_tpu/frontend): a pooled
        # subscription is addressed on the push ring by stream_id and
        # owned by exactly one listener worker, pinned at
        # establishment. worker=None is the in-process path.
        self.stream_id = 0
        self.worker: "int | None" = None


class StreamShard:
    """One shard's subscriptions, queues, band counts, deadline wheel,
    and seq counter. Mutators run on the event loop; `fanout_build`
    additionally runs on a worker thread during the parallel post-tick
    fanout — safe because the loop is blocked for the fanout's duration
    and each shard is built by exactly one thread."""

    def __init__(self, registry: "StreamRegistry", index: int):
        self._registry = registry
        self._server = registry._server
        self.index = index
        # Insertion-ordered sub set: fanout order (and therefore the
        # grouped decide order) is establishment order, deterministic
        # across runs — a set's arbitrary iteration order would make
        # the sharded-vs-single-shard parity pin unfalsifiable.
        self._subs: Dict[Subscription, None] = {}
        self._band_counts: Dict[int, int] = {}
        self._seq = 0
        # Silent-refresh deadline wheel: bucket index -> subscriptions
        # whose next_refresh lands in [b*g, (b+1)*g). A tick pops only
        # the due buckets, so quiet ticks never walk the sub set.
        self._wheel: Dict[int, List[Subscription]] = {}
        self._wheel_g = max(
            float(getattr(self._server, "tick_interval", 1.0) or 1.0),
            1e-3,
        )
        # Lifetime counters (status) and per-tick counters (flight
        # recorder). The tick counters are written by this shard's
        # fanout thread and read/reset by the coordinator after the
        # fanout joins — single-writer by construction.
        self.total_messages = 0
        self.total_deltas = 0
        self.total_bytes = 0
        self.total_resets = 0
        self.tick_deltas = 0
        self.tick_bytes = 0
        self.tick_messages = 0
        self.tick_serialized = 0
        self.tick_shared = 0
        self.tick_walked = 0

    def __len__(self) -> int:
        return len(self._subs)

    def subs(self) -> List[Subscription]:
        return list(self._subs)

    def band_count(self, band: int) -> int:
        return self._band_counts.get(band, 0)

    # -- establishment -------------------------------------------------

    def subscribe(self, request, sub: Subscription) -> None:
        """Register one stream and enqueue its first message: a
        seq-stamped snapshot of every subscribed resource — or, on a
        resume (resume_seq > 0 with `has` baselines), only the rows
        whose current lease differs from what the client already
        holds."""
        now = self._server._clock()
        resume = request.resume_seq > 0
        baseline: Dict[str, float] = {
            rr.resource_id: rr.has.capacity
            for rr in request.resource
            if rr.HasField("has")
        }
        self._subs[sub] = None
        self._band_counts[sub.band] = self._band_counts.get(sub.band, 0) + 1
        rows: List[bytes] = []
        for rid in sub.lines:
            # The establishment decide carries the client-reported
            # lease as `has` — byte-for-byte what this client's next
            # poll would have carried (scalar algorithms read it).
            lease, res = self._decide(
                sub, rid, first=True, has=baseline.get(rid)
            )
            safe = res.safe_capacity()
            sub.last[rid] = (lease.has, safe, int(lease.refresh_interval))
            prev = baseline.get(rid) if resume else None
            if prev is None or lease.has != prev:
                payload = _row(rid, lease, safe).SerializeToString()
                self.tick_serialized += len(payload)
                rows.append(_frame_row(payload))
        sub.next_refresh = now + self._refresh_interval(sub)
        self.wheel_insert(sub)
        # The first message is pushed even when a resume found nothing
        # moved: it carries the current seq and proves liveness.
        self.enqueue(sub, self._message_bytes(rows, snapshot=True),
                     len(rows))

    def unsubscribe(self, sub: Subscription) -> None:
        """Drop one stream (the handler's finally; idempotent). The
        wheel entry is left to lapse — pops skip dead subs."""
        if sub in self._subs:
            del self._subs[sub]
            n = self._band_counts.get(sub.band, 0) - 1
            if n > 0:
                self._band_counts[sub.band] = n
            else:
                self._band_counts.pop(sub.band, None)

    # -- the deadline wheel --------------------------------------------

    def wheel_insert(self, sub: Subscription) -> None:
        b = int(sub.next_refresh // self._wheel_g)
        self._wheel.setdefault(b, []).append(sub)

    def pop_due(self, now: float) -> List[Subscription]:
        """Drain every subscription whose silent-refresh deadline
        passed. Cost is O(due + current bucket), independent of the
        shard's subscriber count; dead entries are skipped lazily."""
        if not self._wheel:
            return []
        nb = int(now // self._wheel_g)
        due: List[Subscription] = []
        for b in sorted(self._wheel):
            if b > nb:
                break
            pending = self._wheel.pop(b)
            if b == nb:
                keep = [s for s in pending if s.next_refresh > now]
                pending = [s for s in pending if s.next_refresh <= now]
                if keep:
                    self._wheel[b] = keep
            for sub in pending:
                if sub in self._subs and not sub.terminated:
                    due.append(sub)
        return due

    def advance_refresh(self, now: float, due: List[Subscription]) -> None:
        """Re-arm the refresh beat for the subs served as due this
        tick; the interval reads the leases the fanout just served,
        floored like a polling client's loop."""
        for sub in due:
            if sub in self._subs and not sub.terminated:
                sub.next_refresh = now + self._refresh_interval(sub)
                self.wheel_insert(sub)

    # -- the tick-edge fanout ------------------------------------------

    def build_work(
        self,
        entries: List[Tuple[Subscription, Optional[List[str]]]],
        work: List[Tuple[str, Request]],
        meta: List[Tuple[Subscription, str]],
    ) -> None:
        """Expand this shard's (subscription, rows) entries — rows=None
        re-decides every line (due refresh / check_all) — into the
        edge-global decide work list. Runs on the event loop; the
        caller owns the canonical ordering."""
        for sub, rows in entries:
            if sub.terminated or sub not in self._subs:
                continue
            self.tick_walked += 1
            rids = sub.lines if rows is None else rows
            for rid in rids:
                line = sub.lines.get(rid)
                if line is None:
                    continue
                wants, priority = line
                last = sub.last.get(rid)
                has = last[0] if last else 0.0
                work.append((
                    rid,
                    Request(sub.client_id, has, wants, 1,
                            priority=priority),
                ))
                meta.append((sub, rid))

    def assemble(
        self, tick: int,
        items: List[Tuple[Subscription, str, object, float]],
    ) -> List[Tuple[Subscription, bytes, int]]:
        """One shard's assemble pass: change-detect each decided
        (subscription, row, lease, safe) against the last pushed key
        and build the pre-serialized push messages. Returns the built
        messages; the caller enqueues them on the event loop. May run
        on a worker thread — touches only shard-owned state."""
        with trace_mod.default_tracer().span(
            "stream.shard", cat="server",
            args={"server": self._server.id, "shard": self.index,
                  "rows": len(items)},
        ):
            # Serialization sharing: identical observable leases of one
            # row serialize once per shard per tick edge.
            cache: Dict[tuple, bytes] = {}
            out_rows: Dict[Subscription, List[bytes]] = {}
            for sub, rid, lease, safe in items:
                key = (lease.has, safe, int(lease.refresh_interval))
                if key == sub.last.get(rid):
                    continue
                sub.last[rid] = key
                ck = (rid, lease.has, safe, int(lease.refresh_interval),
                      int(lease.expiry))
                chunk = cache.get(ck)
                if chunk is None:
                    payload = _row(rid, lease, safe).SerializeToString()
                    self.tick_serialized += len(payload)
                    chunk = _frame_row(payload)
                    cache[ck] = chunk
                else:
                    self.tick_shared += 1
                out_rows.setdefault(sub, []).append(chunk)
            return [
                (sub, self._message_bytes(rows, tick=tick), len(rows))
                for sub, rows in out_rows.items()
            ]

    # -- termination ---------------------------------------------------

    def terminate(self, sub: Subscription, mastership) -> None:
        """End one stream with a terminal redirect message (kept as a
        message object — the handler ends the stream on it). A full
        queue is drained first — the terminal supersedes any deltas the
        consumer never read (it will resume from its has-baseline)."""
        if sub.terminated:
            return
        sub.terminated = True
        msg = spb.WatchCapacityResponse(seq=self._next_seq())
        msg.mastership.CopyFrom(mastership)
        publisher = self._registry.publisher
        if sub.worker is not None and publisher is not None:
            # Pooled stream: the terminal rides the owning worker's
            # ring as a KIND_TERMINAL frame (the worker sends the bytes
            # and ends the stream). A dead worker can't deliver — the
            # registry's drop_worker sweep is the teardown there.
            if publisher.publish_terminal(
                sub.worker, self.index, sub.stream_id,
                msg.SerializeToString(),
            ):
                return
        while True:
            try:
                sub.queue.put_nowait(msg)
                return
            except asyncio.QueueFull:
                try:
                    sub.queue.get_nowait()
                except asyncio.QueueEmpty:  # pragma: no cover - racy only
                    pass

    def reset(self, sub: Subscription) -> None:
        """Slow-consumer reset: terminal redirect pointing at the
        CURRENT master (normally this server) — reconnect and resume.
        Confined to this shard; other shards' streams are untouched."""
        self.total_resets += 1
        self.terminate(sub, self._server._mastership())

    # -- the decide path (byte-identical to a poll) --------------------

    def _decide(self, sub: Subscription, rid: str, *, first: bool,
                has: "Optional[float]" = None):
        wants, priority = sub.lines[rid]
        if has is None:
            last = sub.last.get(rid)
            has = last[0] if last else 0.0
        lease, res = self._server._decide(
            rid, Request(sub.client_id, has, wants, 1, priority=priority)
        )
        if first:
            # The establishment decide registers a new client in the
            # row (wants write + membership bump) outside the admission
            # coalescer's tracked pass: a staged pack of this row
            # predates it (engine.FusedStaging's freshness contract).
            # Steady-state refreshes rewrite the same wants — the
            # packed fields are byte-unchanged, so they need no
            # invalidation (the same argument FusedStaging makes for
            # its one-tick drain window).
            self._server._fused_invalidate(rid)
        return lease, res

    def _refresh_interval(self, sub: Subscription) -> float:
        """The silent-refresh cadence: the shortest served refresh
        interval, floored like a polling client's loop."""
        interval = min(
            (key[2] for key in sub.last.values()), default=None
        )
        if interval is None:
            interval = self._server.tick_interval
        return max(
            float(interval), self._server.minimum_refresh_interval,
            self._server.tick_interval,
        )

    # -- plumbing ------------------------------------------------------

    def _next_seq(self) -> int:
        persist = self._server._persist
        if persist is not None:
            # The decides that built this push are journal deltas; the
            # journal seq therefore stamps the push with a durable,
            # replayable position (doc/streaming.md). max() keeps seqs
            # strictly increasing even when a message carried no
            # journaled decide (terminal redirects).
            self._seq = max(self._seq + 1, persist.journal.seq)
        else:
            self._seq += 1
        return self._seq

    def _message_bytes(self, rows: Sequence[bytes], *,
                       snapshot: bool = False, tick: int = 0) -> bytes:
        """One pushed message: the serialized header concatenated with
        the framed row chunks (serialized exactly once each)."""
        head = spb.WatchCapacityResponse(
            seq=self._next_seq(), tick=tick, snapshot=snapshot
        )
        return head.SerializeToString() + b"".join(rows)

    def enqueue(self, sub: Subscription, payload: bytes,
                n_rows: int) -> None:
        if sub.terminated:
            return
        publisher = self._registry.publisher
        if sub.worker is not None and publisher is not None:
            # Pooled stream: the SAME pre-serialized bytes ride the
            # owning worker's ring instead of the local queue (the
            # zero-re-encode seam — the pooled-parity pin in
            # tests/test_frontend.py is byte equality over this path).
            # A dead worker drops the frame; drop_worker's sweep ends
            # the stream, so the client re-establishes, never lapses.
            if not publisher.publish(
                sub.worker, self.index, sub.stream_id, payload
            ):
                return
        else:
            try:
                sub.queue.put_nowait(payload)
            except asyncio.QueueFull:
                self.reset(sub)
                return
        size = len(payload)
        self.total_messages += 1
        self.total_deltas += n_rows
        self.total_bytes += size
        self.tick_messages += 1
        self.tick_deltas += n_rows
        self.tick_bytes += size

    def take_tick_stats(self) -> dict:
        out = {
            "deltas_pushed": self.tick_deltas,
            "push_bytes": self.tick_bytes,
            "messages": self.tick_messages,
            "serialized_bytes": self.tick_serialized,
            "shared_rows": self.tick_shared,
            "subs_walked": self.tick_walked,
        }
        self.tick_deltas = self.tick_bytes = self.tick_messages = 0
        self.tick_serialized = self.tick_shared = self.tick_walked = 0
        return out

    def status(self) -> dict:
        return {
            "subscribers": len(self._subs),
            "seq": self._seq,
            "resets": self.total_resets,
            "wheel_buckets": len(self._wheel),
        }


def _row(rid: str, lease, safe: float) -> pb.ResourceResponse:
    """One pushed row, field-for-field what GetCapacity builds."""
    row = pb.ResourceResponse()
    row.resource_id = rid
    row.gets.expiry_time = int(lease.expiry)
    row.gets.refresh_interval = int(lease.refresh_interval)
    row.gets.capacity = lease.has
    row.safe_capacity = safe
    return row


class StreamRegistry:
    """All open streams of one CapacityServer, partitioned across
    `shards` StreamShards by the stable client-id hash (see module
    docstring). shards=1 is the single-shard reference path the sharded
    fanout is pinned byte-identical to."""

    def __init__(self, server, *, max_streams_per_band: int = 0,
                 shards: int = 1):
        self._server = server
        # 0 = unlimited. The cap is per wire-priority band ACROSS all
        # shards (a flood of low-band stream establishment can never
        # crowd the fanout out from under high-band subscribers,
        # however it hashes).
        self.max_streams_per_band = int(max_streams_per_band)
        self._shards = [
            StreamShard(self, i) for i in range(max(int(shards), 1))
        ]
        self._executor: "ThreadPoolExecutor | None" = None
        self.last_fanout_seconds = 0.0
        self._tick_matched = 0
        self._order = 0  # establishment sequence (canonical decide order)
        # Frontend pool seam (doorman_tpu/frontend): when a
        # RingPublisher is attached, every new subscription is pooled —
        # pinned to the worker owning its stream shard, addressed by a
        # registry-global stream_id, with pushes routed onto the ring.
        self.publisher = None
        self._stream_ids = 0
        self._by_stream_id: Dict[int, Subscription] = {}
        # Inline pool registration hook: called with the new pooled sub
        # BEFORE its snapshot publishes, so the worker core never parks
        # establishment frames. Real workers register via the Establish
        # reply instead (frontend/control.py) and this stays None.
        self.on_pooled_subscribe = None

    # -- routing -------------------------------------------------------

    @property
    def shards(self) -> List[StreamShard]:
        return self._shards

    def shard_of(self, client_id: str) -> StreamShard:
        """The owning shard: the federation router's stable blake2b
        hash of the client id, mod the shard count — deterministic
        across processes and Python versions."""
        if len(self._shards) == 1:
            return self._shards[0]
        return self._shards[stable_shard(client_id, len(self._shards))]

    def iter_subs(self) -> List[Subscription]:
        return [sub for shard in self._shards for sub in shard.subs()]

    # -- establishment -------------------------------------------------

    def check_cap(self, band: int) -> Optional[Shed]:
        """Per-band stream cap (enforced with or without the admission
        front-end; the AIMD gate is admission.check_watch)."""
        cap = self.max_streams_per_band
        if cap and sum(
            s.band_count(band) for s in self._shards
        ) >= cap:
            s = self._server
            return Shed(
                reason=(
                    f"stream cap: band {band} already holds {cap} "
                    "streams on this server"
                ),
                retry_after=max(
                    s.tick_interval, s.minimum_refresh_interval, 1.0
                ),
                band=band,
                kind="stream_cap",
            )
        return None

    def subscribe(self, request, worker: "Optional[int]" = None
                  ) -> Subscription:
        band = max((rr.priority for rr in request.resource), default=0)
        lines = {
            rr.resource_id: (rr.wants, rr.priority)
            for rr in request.resource
        }
        shard = self.shard_of(request.client_id)
        sub = Subscription(request.client_id, band, lines,
                           shard=shard.index)
        self._order += 1
        sub.order = self._order
        if self.publisher is not None:
            # Pooled routing is pinned BEFORE the first message builds:
            # the establishment snapshot already rides the ring. The
            # inline pool routes by the shard's home worker; a REAL
            # worker passes itself (`worker`) — SO_REUSEPORT hands the
            # TCP connection to an arbitrary worker, and the frames
            # must ride the ring of the worker that holds the stream.
            self._stream_ids += 1
            sub.stream_id = self._stream_ids
            sub.worker = (
                worker if worker is not None
                else self.publisher.shard_worker(shard.index)
            )
            self._by_stream_id[sub.stream_id] = sub
            if self.on_pooled_subscribe is not None:
                self.on_pooled_subscribe(sub)
        shard.subscribe(request, sub)
        return sub

    def unsubscribe(self, sub: Subscription) -> None:
        self._shards[sub.shard].unsubscribe(sub)
        if sub.stream_id:
            self._by_stream_id.pop(sub.stream_id, None)

    # -- frontend pool handoff -----------------------------------------

    def attach_publisher(self, publisher) -> None:
        """Attach the frontend pool's RingPublisher. Existing
        subscriptions stay in-process (worker=None); only streams
        established afterwards are pooled — attachment happens before
        the listener opens, so in practice all of them."""
        self.publisher = publisher

    def stream_by_id(self, stream_id: int) -> "Optional[Subscription]":
        return self._by_stream_id.get(stream_id)

    def worker_subs(self, worker: int) -> List[Subscription]:
        return [
            sub for sub in self._by_stream_id.values()
            if sub.worker == worker
        ]

    def drop_worker(self, worker: int, mastership) -> int:
        """One listener worker died: remap its stream shards to the
        survivors and end every stream it held — terminal redirects
        delivered LOCALLY (the worker that would have pumped the ring
        is gone; in the real pool the TCP teardown already reset the
        clients, in the inline pool the local queue models it). The
        clients re-establish — routed to a surviving worker by the
        reassigned map — and resume from seq: per-shard seq counters
        never reset, so the resumed stream sees no replay and no gap.
        Returns the number of streams dropped. Never silent-lapse: a
        crash is a loud terminal, not a quiet stall."""
        if self.publisher is not None:
            self.publisher.reassign(worker)
        dropped = 0
        for sub in self.worker_subs(worker):
            if not sub.terminated:
                # Clear the worker pin first so the terminal takes the
                # local-queue path (the dead worker's ring has no
                # reader to forward it).
                sub.worker = None
                self._shards[sub.shard].terminate(sub, mastership)
                dropped += 1
            self.unsubscribe(sub)
            # No handler finally / Drop RPC runs for a crashed worker:
            # release the device-matcher slot here or it leaks
            # (idempotent — a stream whose handler does come back
            # around just no-ops).
            self._server._stream_match_remove(sub)
        if dropped:
            log.info(
                "%s: frontend worker %d lost — dropped %d stream(s) "
                "with redirects", self._server.id, worker, dropped,
            )
        return dropped

    # -- the tick-edge fanout ------------------------------------------

    def on_tick(
        self,
        changed_ids: "Optional[Set[str]]",
        check_all: bool,
        matched: "Optional[Dict[Subscription, List[str]]]" = None,
    ) -> None:
        """Push deltas for one tick edge.

        `matched` is the device matcher's output — subscription ->
        exactly the changed resource ids it watches; `check_all=True`
        means no tracked source of deltas existed this tick (python
        store, config epoch move, restore) and every subscription line
        is re-decided. `changed_ids` is the legacy resource-id filter,
        used only when no matcher produced `matched` (the shards then
        walk their subs and intersect — the PR-9 shape, kept as the
        conservative fallback). A quiet tick — nothing matched,
        nothing due — walks zero subscriptions."""
        server = self._server
        now = server._clock()
        tick = server._ticks_done
        t0 = time.perf_counter()
        due_by_shard = [shard.pop_due(now) for shard in self._shards]
        plans: List[List[Tuple[Subscription, Optional[List[str]]]]] = []
        for shard, due in zip(self._shards, due_by_shard):
            if check_all:
                entries = [(sub, None) for sub in shard.subs()]
            else:
                entries = [(sub, None) for sub in due]
                due_set = set(due)
                if matched is not None:
                    for sub, rows in matched.items():
                        if sub.shard == shard.index and sub not in due_set:
                            entries.append((sub, rows))
                            self._tick_matched += len(rows)
                elif changed_ids:
                    # Legacy walk: O(shard subscribers) — only when the
                    # matcher is unavailable.
                    for sub in shard.subs():
                        if sub in due_set:
                            continue
                        rows = [r for r in sub.lines if r in changed_ids]
                        if rows:
                            entries.append((sub, rows))
            plans.append(entries)
        # Decide pass: one edge-global work list in canonical order —
        # establishment order across subscriptions, line order within
        # one — so the grouped per-resource replay is byte-identical
        # for any shard count (see the module docstring).
        flat = [e for entries in plans for e in entries]
        flat.sort(key=lambda e: e[0].order)
        work: List[Tuple[str, Request]] = []
        meta: List[Tuple[Subscription, str]] = []
        for sub, rows in flat:
            self._shards[sub.shard].build_work([(sub, rows)], work, meta)
        if not work:
            for shard, due in zip(self._shards, due_by_shard):
                shard.advance_refresh(now, due)
            if self.publisher is not None:
                # Quiet tick is still a push edge: the beat is how a
                # worker's deadline wheel tells "nothing to push" from
                # "ring stalled" (frontend/ring.py KIND_BEAT).
                self.publisher.beat()
            self.last_fanout_seconds = time.perf_counter() - t0
            return
        decided = self._decide_all(work)
        # Assemble pass: split the decided rows per owning shard (in
        # canonical order) and build each shard's messages — change
        # detection, row serialization sharing, seq stamping all touch
        # only shard-owned state, so shards assemble in parallel.
        per_shard: Dict[int, List[tuple]] = {}
        for (sub, rid), (lease, _res, safe) in zip(meta, decided):
            per_shard.setdefault(sub.shard, []).append(
                (sub, rid, lease, safe)
            )
        live = [
            (self._shards[i], items)
            for i, items in sorted(per_shard.items())
        ]
        built: List[List[Tuple[Subscription, bytes, int]]]
        if len(live) > 1 and self._parallel_ok():
            import contextvars

            pool = self._pool()
            futures = [
                pool.submit(
                    contextvars.copy_context().run,
                    shard.assemble, tick, items,
                )
                for shard, items in live
            ]
            built = [f.result() for f in futures]
        else:
            built = [
                shard.assemble(tick, items) for shard, items in live
            ]
        # Enqueues land back on the event loop (asyncio queues are not
        # thread-safe); shard order keeps the sequence deterministic.
        for (shard, _), messages in zip(live, built):
            for sub, payload, n_rows in messages:
                shard.enqueue(sub, payload, n_rows)
        for shard, due in zip(self._shards, due_by_shard):
            shard.advance_refresh(now, due)
        if self.publisher is not None:
            self.publisher.beat()
        self.last_fanout_seconds = time.perf_counter() - t0

    def _decide_all(self, work: List[Tuple[str, Request]]) -> List[tuple]:
        """The edge-global decide pass. Sequential it is exactly
        decide_grouped; when leaving the loop is safe (the native
        engine's mutex guards store writes, no loop-only journal) the
        per-resource groups fan to worker threads — different resources
        touch disjoint stores, so the parallel replay is byte-identical
        to the sequential one."""
        server = self._server
        groups: Dict[str, List[Tuple[int, Request]]] = {}
        for i, (resource_id, request) in enumerate(work):
            groups.setdefault(resource_id, []).append((i, request))
        if (
            len(self._shards) < 2
            or len(groups) < 2
            or not self._parallel_ok()
        ):
            return decide_grouped(server, work)
        import contextvars

        pool = self._pool()
        slots: List[tuple] = [None] * len(work)  # type: ignore[list-item]

        def run_group(entries: List[Tuple[int, Request]],
                      resource_id: str) -> None:
            for i, request in entries:
                lease, res = server._decide(resource_id, request)
                slots[i] = (lease, res, res.safe_capacity())

        futures = [
            pool.submit(
                contextvars.copy_context().run, run_group, entries,
                resource_id,
            )
            for resource_id, entries in groups.items()
        ]
        for f in futures:
            f.result()
        return slots

    def _parallel_ok(self) -> bool:
        """Shard fanouts may leave the event loop only when that is
        safe — the admission coalescer's executor rule: the native
        engine's mutex guards concurrent store writes, but the persist
        journal is documented loop-only."""
        return (
            self._server._native_store and self._server._persist is None
        )

    def _pool(self) -> ThreadPoolExecutor:
        if self._executor is None:
            self._executor = ThreadPoolExecutor(
                max_workers=len(self._shards),
                thread_name_prefix="stream-shard",
            )
        return self._executor

    def close(self) -> None:
        if self._executor is not None:
            self._executor.shutdown(wait=False)
            self._executor = None

    # -- termination ---------------------------------------------------

    def terminate(self, sub: Subscription, mastership) -> None:
        self._shards[sub.shard].terminate(sub, mastership)

    def terminate_all(self, mastership) -> int:
        """Mastership lost (or shutting down): every stream on every
        shard ends with a redirect so clients chase the new master —
        atomic across shards (runs on the loop with no awaits; no RPC
        can interleave a subscribe between two shards' sweeps).
        Returns streams terminated."""
        n = 0
        for shard in self._shards:
            for sub in shard.subs():
                if not sub.terminated:
                    shard.terminate(sub, mastership)
                    n += 1
        if n:
            log.info(
                "%s: terminated %d capacity stream(s) with a mastership "
                "redirect", self._server.id, n,
            )
        return n

    def reset(self, sub: Subscription) -> None:
        self._shards[sub.shard].reset(sub)

    # -- introspection -------------------------------------------------

    def __len__(self) -> int:
        return sum(len(s) for s in self._shards)

    @property
    def total_messages(self) -> int:
        return sum(s.total_messages for s in self._shards)

    @property
    def total_deltas(self) -> int:
        return sum(s.total_deltas for s in self._shards)

    @property
    def total_bytes(self) -> int:
        return sum(s.total_bytes for s in self._shards)

    @property
    def total_resets(self) -> int:
        return sum(s.total_resets for s in self._shards)

    def take_tick_stats(self) -> dict:
        """Per-tick counters for the flight recorder; resets on read.
        Σ per-shard outbound is the invariant the sharded-parity test
        holds against the single-shard path."""
        per_shard = [s.take_tick_stats() for s in self._shards]
        out = {
            "subscribers": len(self),
            "deltas_pushed": sum(s["deltas_pushed"] for s in per_shard),
            "push_bytes": sum(s["push_bytes"] for s in per_shard),
            "messages": sum(s["messages"] for s in per_shard),
            "stream_shards": len(self._shards),
            "matched_pairs": self._tick_matched,
            "serialized_bytes": sum(
                s["serialized_bytes"] for s in per_shard
            ),
            "shared_rows": sum(s["shared_rows"] for s in per_shard),
            "subs_walked": sum(s["subs_walked"] for s in per_shard),
        }
        self._tick_matched = 0
        return out

    def status(self) -> dict:
        band_counts: Dict[int, int] = {}
        for shard in self._shards:
            for band, n in shard._band_counts.items():
                band_counts[band] = band_counts.get(band, 0) + n
        return {
            # Federated deployments run one registry per root shard;
            # seqs (and therefore resume tokens) are scoped to this
            # shard's persist journal — a resume token from shard A is
            # meaningless on shard B, which is why the shard index
            # rides the status block (doc/federation.md).
            "shard": getattr(self._server, "shard", None),
            "shards": len(self._shards),
            "subscribers": len(self),
            "by_band": {
                str(b): n for b, n in sorted(band_counts.items())
            },
            "max_streams_per_band": self.max_streams_per_band,
            "seq": max(s._seq for s in self._shards),
            "messages_total": self.total_messages,
            "deltas_total": self.total_deltas,
            "bytes_total": self.total_bytes,
            "resets_total": self.total_resets,
            "last_fanout_ms": round(self.last_fanout_seconds * 1000.0, 3),
            "per_shard": [s.status() for s in self._shards],
            "frontend": (
                self.publisher.status()
                if self.publisher is not None else None
            ),
        }
