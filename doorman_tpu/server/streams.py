"""Tick-edge lease push: the WatchCapacity subscription registry.

One `StreamRegistry` per server owns every open WatchCapacity stream:
which client subscribed to which resources (and at what wants/band),
what lease each subscription last observed, and the per-stream outbound
queue the gRPC handler drains. At every tick edge the server hands the
registry the set of resources whose delivered grants moved (the tick
engine's device-extracted delta set — solver/engine.py delta tracking)
and the registry runs the SAME decide path a GetCapacity poll would run
— but only for subscribers of rows that actually changed, plus the
subscriptions due for their silent refresh beat. A push therefore
carries exactly the bytes a poll at the same instant would have
carried; change detection compares the decide RESULT against the last
pushed lease, so parity with poll-every-tick holds even when the delta
filter over-approximates (it may only ever over-approximate — a missed
resource is caught at the subscription's next refresh beat, the same
staleness bound a polling client lives with).

Ordering / exactly-once: every pushed message carries a seq — the
persist journal's sequence number when persistence is configured (the
decides that built the push are themselves journal deltas), else a
registry counter. A stream is a single writer, so seqs are strictly
increasing per stream; clients drop seq <= the last applied and offer
the last seen seq back as `resume_seq` on reconnect. Resume does not
REPLAY history (none is retained): the reconnect request's `has` fields
are the client's baseline, and the first message carries only the rows
whose current lease differs from it — byte-identical to what the
missed pushes would have converged to.

Concurrency: every registry method runs on the server's event loop
(RPC handlers and the post-tick fanout both live there); no locks. The
only cross-thread input is the tick engine's changed-rid set, drained
by the server before it calls on_tick.

Silent refresh: each subscription is refreshed (decide, no push unless
the lease moved) on its resources' refresh-interval cadence, exactly
like a polling client — so server-side lease expiry keeps being pushed
out while the stream is quiet, and learning-mode scalar decisions keep
being re-evaluated.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Dict, Optional, Set, Tuple

from doorman_tpu.admission.policy import Shed
from doorman_tpu.algorithms import Request
from doorman_tpu.proto import doorman_pb2 as pb
from doorman_tpu.proto import doorman_stream_pb2 as spb

log = logging.getLogger(__name__)

__all__ = ["StreamRegistry", "Subscription"]

# Outbound queue depth per stream. A consumer this far behind (the
# fanout produces at tick cadence; a healthy stream drains in
# microseconds) is reset with a redirect-to-self terminal message — the
# client reconnects and resumes from its has-baseline, which is both
# cheaper and more correct than dropping arbitrary deltas.
QUEUE_SIZE = 256


class Subscription:
    """One open WatchCapacity stream."""

    def __init__(self, client_id: str, band: int,
                 lines: Dict[str, Tuple[float, int]]):
        self.client_id = client_id
        self.band = band
        # resource_id -> (wants, priority), fixed at establishment
        # (clients change wants by re-establishing the stream).
        self.lines = lines
        # resource_id -> (capacity, safe_capacity, refresh_interval):
        # the change-detection key of the last served lease.
        self.last: Dict[str, tuple] = {}
        self.queue: "asyncio.Queue" = asyncio.Queue(maxsize=QUEUE_SIZE)
        self.next_refresh = 0.0
        self.terminated = False


class StreamRegistry:
    """All open streams of one CapacityServer (see module docstring)."""

    def __init__(self, server, *, max_streams_per_band: int = 0):
        self._server = server
        # 0 = unlimited. The cap is per wire-priority band so a flood of
        # low-band stream establishment can never crowd the fanout (and
        # the tick it rides) out from under high-band subscribers.
        self.max_streams_per_band = int(max_streams_per_band)
        self._subs: Set[Subscription] = set()
        self._band_counts: Dict[int, int] = {}
        self._seq = 0
        # Lifetime counters (status pages) and per-tick counters
        # (the flight recorder's subscriber/deltas/bytes fields).
        self.total_messages = 0
        self.total_deltas = 0
        self.total_bytes = 0
        self.total_resets = 0
        self._tick_deltas = 0
        self._tick_bytes = 0
        self._tick_messages = 0

    # -- establishment -------------------------------------------------

    def check_cap(self, band: int) -> Optional[Shed]:
        """Per-band stream cap (enforced with or without the admission
        front-end; the AIMD gate is admission.check_watch)."""
        cap = self.max_streams_per_band
        if cap and self._band_counts.get(band, 0) >= cap:
            s = self._server
            return Shed(
                reason=(
                    f"stream cap: band {band} already holds {cap} "
                    "streams on this server"
                ),
                retry_after=max(
                    s.tick_interval, s.minimum_refresh_interval, 1.0
                ),
                band=band,
                kind="stream_cap",
            )
        return None

    def subscribe(self, request) -> Subscription:
        """Register one stream and enqueue its first message: a
        seq-stamped snapshot of every subscribed resource — or, on a
        resume (resume_seq > 0 with `has` baselines), only the rows
        whose current lease differs from what the client already holds."""
        now = self._server._clock()
        band = max((rr.priority for rr in request.resource), default=0)
        lines = {
            rr.resource_id: (rr.wants, rr.priority)
            for rr in request.resource
        }
        sub = Subscription(request.client_id, band, lines)
        resume = request.resume_seq > 0
        baseline: Dict[str, float] = {
            rr.resource_id: rr.has.capacity
            for rr in request.resource
            if rr.HasField("has")
        }
        self._subs.add(sub)
        self._band_counts[band] = self._band_counts.get(band, 0) + 1
        rows = []
        for rid in lines:
            # The establishment decide carries the client-reported
            # lease as `has` — byte-for-byte what this client's next
            # poll would have carried (scalar algorithms read it).
            lease, res = self._decide(
                sub, rid, first=True, has=baseline.get(rid)
            )
            sub.last[rid] = self._key(lease, res)
            prev = baseline.get(rid) if resume else None
            if prev is None or lease.has != prev:
                rows.append(self._row(rid, lease, res))
        sub.next_refresh = now + self._refresh_interval(sub)
        # The first message is pushed even when a resume found nothing
        # moved: it carries the current seq and proves liveness.
        self._enqueue(sub, self._message(rows, snapshot=True))
        return sub

    def unsubscribe(self, sub: Subscription) -> None:
        """Drop one stream (the handler's finally; idempotent)."""
        if sub in self._subs:
            self._subs.discard(sub)
            n = self._band_counts.get(sub.band, 0) - 1
            if n > 0:
                self._band_counts[sub.band] = n
            else:
                self._band_counts.pop(sub.band, None)

    # -- the tick-edge fanout ------------------------------------------

    def on_tick(self, changed_ids: "Optional[Set[str]]",
                check_all: bool) -> None:
        """Push deltas for one tick edge. `changed_ids` is the resource
        ids whose grants the tick moved (the engine's delta set plus any
        resources solved outside the delta-tracked path); check_all=True
        means no tracked source of deltas existed this tick (python
        store, config epoch move, restore) — every subscription line is
        re-decided. Resources in learning mode are always checked: their
        scalar decisions move without store deliveries."""
        if not self._subs:
            return
        server = self._server
        now = server._clock()
        tick = server._ticks_done
        for sub in list(self._subs):
            if sub.terminated:
                continue
            due = now >= sub.next_refresh
            rows = []
            for rid in sub.lines:
                if (
                    not (check_all or due)
                    and (changed_ids is None or rid not in changed_ids)
                ):
                    res = server.resources.get(rid)
                    if res is None or not res.in_learning_mode:
                        continue
                lease, res = self._decide(sub, rid, first=False)
                key = self._key(lease, res)
                if key != sub.last.get(rid):
                    sub.last[rid] = key
                    rows.append(self._row(rid, lease, res))
            if due:
                sub.next_refresh = now + self._refresh_interval(sub)
            if rows:
                self._enqueue(sub, self._message(rows, tick=tick))

    # -- termination ---------------------------------------------------

    def terminate(self, sub: Subscription, mastership) -> None:
        """End one stream with a terminal redirect message. A full
        queue is drained first — the terminal supersedes any deltas the
        consumer never read (it will resume from its has-baseline)."""
        if sub.terminated:
            return
        sub.terminated = True
        msg = spb.WatchCapacityResponse(seq=self._next_seq())
        msg.mastership.CopyFrom(mastership)
        while True:
            try:
                sub.queue.put_nowait(msg)
                return
            except asyncio.QueueFull:
                try:
                    sub.queue.get_nowait()
                except asyncio.QueueEmpty:  # pragma: no cover - racy only
                    pass

    def terminate_all(self, mastership) -> int:
        """Mastership lost (or shutting down): every stream ends with a
        redirect so clients chase the new master — the streaming analog
        of the unary mastership response. Returns streams terminated."""
        n = 0
        for sub in list(self._subs):
            if not sub.terminated:
                self.terminate(sub, mastership)
                n += 1
        if n:
            log.info(
                "%s: terminated %d capacity stream(s) with a mastership "
                "redirect", self._server.id, n,
            )
        return n

    def reset(self, sub: Subscription) -> None:
        """Slow-consumer reset: terminal redirect pointing at the
        CURRENT master (normally this server) — reconnect and resume."""
        self.total_resets += 1
        self.terminate(sub, self._server._mastership())

    # -- the decide path (byte-identical to a poll) --------------------

    def _decide(self, sub: Subscription, rid: str, *, first: bool,
                has: "Optional[float]" = None):
        wants, priority = sub.lines[rid]
        if has is None:
            last = sub.last.get(rid)
            has = last[0] if last else 0.0
        lease, res = self._server._decide(
            rid, Request(sub.client_id, has, wants, 1, priority=priority)
        )
        if first:
            # The establishment decide registers a new client in the
            # row (wants write + membership bump) outside the admission
            # coalescer's tracked pass: a staged pack of this row
            # predates it (engine.FusedStaging's freshness contract).
            # Steady-state refreshes rewrite the same wants — the
            # packed fields are byte-unchanged, so they need no
            # invalidation (the same argument FusedStaging makes for
            # its one-tick drain window).
            self._server._fused_invalidate(rid)
        return lease, res

    @staticmethod
    def _key(lease, res) -> tuple:
        """Change-detection key: what a client OBSERVES of a lease.
        Expiry is deliberately excluded — it advances on every silent
        refresh, and pushing it would reduce the stream to a poll."""
        return (lease.has, res.safe_capacity(), int(lease.refresh_interval))

    @staticmethod
    def _row(rid: str, lease, res) -> pb.ResourceResponse:
        """One pushed row, field-for-field what GetCapacity builds."""
        row = pb.ResourceResponse()
        row.resource_id = rid
        row.gets.expiry_time = int(lease.expiry)
        row.gets.refresh_interval = int(lease.refresh_interval)
        row.gets.capacity = lease.has
        row.safe_capacity = res.safe_capacity()
        return row

    def _refresh_interval(self, sub: Subscription) -> float:
        """The silent-refresh cadence: the shortest served refresh
        interval, floored like a polling client's loop."""
        interval = min(
            (key[2] for key in sub.last.values()), default=None
        )
        if interval is None:
            interval = self._server.tick_interval
        return max(
            float(interval), self._server.minimum_refresh_interval,
            self._server.tick_interval,
        )

    # -- plumbing ------------------------------------------------------

    def _next_seq(self) -> int:
        persist = self._server._persist
        if persist is not None:
            # The decides that built this push are journal deltas; the
            # journal seq therefore stamps the push with a durable,
            # replayable position (doc/streaming.md). max() keeps seqs
            # strictly increasing even when a message carried no
            # journaled decide (terminal redirects).
            self._seq = max(self._seq + 1, persist.journal.seq)
        else:
            self._seq += 1
        return self._seq

    def _message(self, rows, *, snapshot: bool = False,
                 tick: int = 0) -> spb.WatchCapacityResponse:
        msg = spb.WatchCapacityResponse(
            seq=self._next_seq(), tick=tick, snapshot=snapshot
        )
        for row in rows:
            msg.response.append(row)
        return msg

    def _enqueue(self, sub: Subscription, msg) -> None:
        if sub.terminated:
            return
        try:
            sub.queue.put_nowait(msg)
        except asyncio.QueueFull:
            self.reset(sub)
            return
        n = len(msg.response)
        size = msg.ByteSize()
        self.total_messages += 1
        self.total_deltas += n
        self.total_bytes += size
        self._tick_messages += 1
        self._tick_deltas += n
        self._tick_bytes += size

    # -- introspection -------------------------------------------------

    def __len__(self) -> int:
        return len(self._subs)

    def take_tick_stats(self) -> dict:
        """Per-tick counters for the flight recorder; resets on read."""
        out = {
            "subscribers": len(self._subs),
            "deltas_pushed": self._tick_deltas,
            "push_bytes": self._tick_bytes,
            "messages": self._tick_messages,
        }
        self._tick_deltas = self._tick_bytes = self._tick_messages = 0
        return out

    def status(self) -> dict:
        return {
            # Federated deployments run one registry per root shard;
            # seqs (and therefore resume tokens) are scoped to this
            # shard's persist journal — a resume token from shard A is
            # meaningless on shard B, which is why the shard index
            # rides the status block (doc/federation.md).
            "shard": getattr(self._server, "shard", None),
            "subscribers": len(self._subs),
            "by_band": {
                str(b): n for b, n in sorted(self._band_counts.items())
            },
            "max_streams_per_band": self.max_streams_per_band,
            "seq": self._seq,
            "messages_total": self.total_messages,
            "deltas_total": self.total_deltas,
            "bytes_total": self.total_bytes,
            "resets_total": self.total_resets,
        }
