"""Capacity server: config, election, RPC handlers, batched tick loop."""
