"""Resource configuration: YAML <-> ResourceRepository, validation, template
matching.

Mirrors the reference behavior (capability parity, not code):
  - validation rules: /root/reference/go/server/doorman/server.go:384-434
    (every glob well-formed; any present algorithm needs lease_length >=
    refresh_interval >= 1s; an entry for "*" with an algorithm must exist and
    be last)
  - template matching: server.go:626-649 (exact identifier match first, then
    first glob match in repository order)
  - YAML form: /root/reference/doc/configuration.md + the proto JSON naming
    (snake_case field names, algorithm kind as enum name string).
"""

from __future__ import annotations

import fnmatch
from typing import Optional

import yaml
from google.protobuf import json_format

from doorman_tpu.proto import doorman_pb2 as pb


class ConfigError(ValueError):
    """Raised for an invalid ResourceRepository or config document."""


def parse_yaml_config(text: str) -> pb.ResourceRepository:
    """Parse a YAML (or JSON) document into a validated ResourceRepository.

    Accepts snake_case field names (matching the proto) as well as
    lowerCamelCase (proto-JSON default).
    """
    try:
        doc = yaml.safe_load(text)
    except yaml.YAMLError as e:
        raise ConfigError(f"malformed YAML: {e}") from e
    if doc is None:
        raise ConfigError("empty config document")
    if not isinstance(doc, dict):
        raise ConfigError("config root must be a mapping")
    repo = pb.ResourceRepository()
    try:
        json_format.ParseDict(doc, repo)
    except json_format.ParseError as e:
        raise ConfigError(f"bad config structure: {e}") from e
    validate_repository(repo)
    return repo


def repository_to_yaml(repo: pb.ResourceRepository) -> str:
    doc = json_format.MessageToDict(repo, preserving_proto_field_name=True)
    return yaml.safe_dump(doc, sort_keys=False)


def _glob_well_formed(glob: str) -> bool:
    # fnmatch never errors, so reject by hand the patterns Go's filepath.Match
    # calls ErrBadPattern: an unterminated character class, or a trailing
    # escape. Inside a class, a ']' directly after '[' (or '[!'/'[^') is a
    # literal member, and any further '[' is literal too.
    i, n = 0, len(glob)
    while i < n:
        ch = glob[i]
        if ch == "\\":
            if i + 1 >= n:
                return False
            i += 2
        elif ch == "[":
            j = i + 1
            if j < n and glob[j] in "!^":
                j += 1
            if j < n and glob[j] == "]":  # literal ']' as first member
                j += 1
            while j < n and glob[j] != "]":
                j += 2 if glob[j] == "\\" else 1
            if j >= n:
                return False
            i = j + 1
        else:
            i += 1
    return True


def validate_algorithm(algo: pb.Algorithm) -> None:
    if algo.refresh_interval < 1:
        raise ConfigError("invalid refresh interval, must be at least 1 second")
    if algo.lease_length < 1:
        raise ConfigError("invalid lease length, must be at least 1 second")
    if algo.lease_length < algo.refresh_interval:
        raise ConfigError("lease length must be larger than the refresh interval")
    # A `variant` parameter must name a known refinement of its wire
    # kind (algorithms.scalar.VARIANT_FACTORIES): a typo would silently
    # select the base lane, and — because algo_kind_for feeds the
    # solver's config mirror — flip the device lane set on a later fix,
    # so fail the config epoch loudly instead.
    from doorman_tpu.algorithms.scalar import VARIANT_FACTORIES, get_parameter

    variant = get_parameter(algo, "variant")
    if variant is not None and (algo.kind, variant) not in VARIANT_FACTORIES:
        known = sorted(
            v for (k, v) in VARIANT_FACTORIES if k == algo.kind
        )
        raise ConfigError(
            f"unknown variant {variant!r} for algorithm "
            f"{pb.Algorithm.Kind.Name(algo.kind)}"
            + (f" (known: {', '.join(known)})" if known else
               " (this kind has no variants)")
        )


def validate_repository(repo: pb.ResourceRepository) -> None:
    """Validate a ResourceRepository; raises ConfigError when invalid."""
    groups = set()
    for grp in repo.groups:
        if not grp.name:
            raise ConfigError("capacity group without a name")
        if grp.name in groups:
            raise ConfigError(f"duplicate capacity group {grp.name!r}")
        if grp.capacity < 0:
            raise ConfigError(
                f"capacity group {grp.name!r} has negative capacity"
            )
        groups.add(grp.name)
    star_found = False
    for i, tpl in enumerate(repo.resources):
        glob = tpl.identifier_glob
        if not _glob_well_formed(glob):
            raise ConfigError(f"malformed glob: {glob!r}")
        # proto3 has no algorithm-presence bit on a message field beyond
        # being unset-equals-default; treat an all-default Algorithm on a
        # non-star template as "absent" only if it was never set.
        has_algo = tpl.HasField("algorithm")
        if has_algo:
            validate_algorithm(tpl.algorithm)
        if tpl.HasField("capacity_group"):
            if tpl.capacity_group not in groups:
                raise ConfigError(
                    f"template {glob!r} references undefined capacity "
                    f"group {tpl.capacity_group!r}"
                )
            if (
                not has_algo
                or tpl.algorithm.kind != pb.Algorithm.PRIORITY_BANDS
            ):
                raise ConfigError(
                    f"template {glob!r}: capacity_group requires the "
                    "PRIORITY_BANDS algorithm (groups are enforced by "
                    "the batched priority solve)"
                )
        if glob == "*":
            if not has_algo:
                raise ConfigError('the entry for "*" must specify an algorithm')
            if i + 1 != len(repo.resources):
                raise ConfigError('the entry for "*" must be the last one')
            star_found = True
    if not star_found:
        raise ConfigError('the resource repository must contain an entry for "*"')


def find_template(
    repo: pb.ResourceRepository, resource_id: str
) -> Optional[pb.ResourceTemplate]:
    """Find the template for a resource id: exact match first, then first
    glob match in repository order. Returns None only for an (invalid)
    repository without a "*" entry."""
    for tpl in repo.resources:
        if tpl.identifier_glob == resource_id:
            return tpl
    for tpl in repo.resources:
        if fnmatch.fnmatchcase(resource_id, tpl.identifier_glob):
            return tpl
    return None


def validate_get_capacity_request(req: pb.GetCapacityRequest) -> Optional[str]:
    """Returns an error string for an invalid request, else None
    (mirrors server.go:357-381)."""
    if not req.client_id:
        return "client_id cannot be empty"
    if _has_control_chars(req.client_id):
        return "client_id cannot contain control characters"
    for r in req.resource:
        if not r.resource_id:
            return "resource_id cannot be empty"
        if _has_control_chars(r.resource_id):
            return "resource_id cannot contain control characters"
        if r.wants < 0:
            return "capacity must be positive"
    return None


def _has_control_chars(s: str) -> bool:
    # Control characters in ids could forge the server's internal band
    # sub-lease keys (server._BAND_SEP) or break C-string interning in
    # the native store engine.
    return any(c < " " for c in s)


def validate_release_capacity_request(
    req: pb.ReleaseCapacityRequest,
) -> Optional[str]:
    if not req.client_id:
        return "client_id cannot be empty"
    if _has_control_chars(req.client_id):
        return "client_id cannot contain control characters"
    return None


def validate_get_server_capacity_request(
    req: pb.GetServerCapacityRequest,
) -> Optional[str]:
    """Validation for the intermediate-server RPC (mirrors the subclient
    checks exercised by reference server_test.go:483-553)."""
    if not req.server_id:
        return "server_id cannot be empty"
    if _has_control_chars(req.server_id):
        return "server_id cannot contain control characters"
    for r in req.resource:
        if not r.resource_id:
            return "resource_id cannot be empty"
        if _has_control_chars(r.resource_id):
            return "resource_id cannot contain control characters"
        for band in r.wants:
            if band.wants < 0:
                return "capacity must be positive"
            if band.num_clients < 1:
                return "num_clients must be positive"
    return None
