"""Device-side changed-row -> subscriber matching for the stream fanout.

The PR-9 fanout walked every subscription per tick edge and intersected
its lines with the changed-resource set in Python — O(subscribers) of
interpreter time even when one row moved. This module keeps the
row->subscriber incidence DEVICE-resident so a tick edge pays
O(changed rows x affected subscribers) instead: the engine's
device-extracted compact changed-rid set (solver/engine.py delta
tracking) is intersected with a subscription incidence structure on
device, and only the matched (subscriber-slot, row) pairs download.

Layout: a CSR-like padded-extent table. Every subscribed engine rid
owns a contiguous extent of the `indices` array holding the subscriber
slots watching it (-1 padding up to the extent's capacity); `row_of`
carries the owning rid per position so one boolean mask — "position
holds a live slot AND its row changed" — selects the matched pairs in
a single vectorized pass. Extents carry headroom so steady
subscribe/unsubscribe churn stages as point scatters through the same
placement chokepoint the tick engines use (engine.place); only an
extent overflowing its capacity (or a new rid) repacks the table.

Match cost: the matched-pair count M is known HOST-side before any
device work (the extent lengths are mirrored), so the gather launches
at a bucketed static size and the download carries exactly the matched
pairs — no device->host sync decides a shape, which is what lets the
"match" phase survive doormanlint's call-graph-deep host-sync audit
(the only sync is landing the pairs, lapped as "download" like any
delivery byte).

Host mirror: every structure is mirrored in numpy and the device side
is a pure cache of it — a box without jax (or a python-store server)
runs `match` from the mirror with identical results, so the fanout
never depends on an accelerator being present.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Sequence, Tuple

import numpy as np

from doorman_tpu.obs.phases import PhaseRecorder
from doorman_tpu.solver.engine import PHASES, ceil_to
from doorman_tpu.utils import dispatch as dispatch_mod

log = logging.getLogger(__name__)

__all__ = ["SubscriptionMatcher"]

# Extent headroom: a rid's extent is sized for its current watcher
# count plus slack, so steady subscribe/unsubscribe churn updates in
# place (point scatters) instead of repacking the table.
_EXTENT_PAD = 8


def _pow2(n: int, floor: int = 64) -> int:
    """Geometric shape bucket: the jitted match/scatter executables key
    on array shapes, and a linearly-growing bucket (ceil_to) would
    recompile through hundreds of sizes while a subscriber population
    ramps; powers of two bound the recompile count at log2(max)."""
    out = floor
    while out < n:
        out <<= 1
    return out


class SubscriptionMatcher:
    """Row -> subscriber-slot incidence with device-side intersection.

    Slots are dense ints allocated here (free-listed); the caller owns
    the slot -> subscription map. All mutators run on the server's
    event loop (the same serialization the stream registry relies on);
    `match` runs wherever the fanout runs — the device arrays are only
    ever replaced, never mutated in place, and the host mirror is the
    source of truth.
    """

    component = "stream"

    def __init__(self, *, device=None, use_device: bool = True):
        self._device = device
        self._use_device = use_device
        self._jax_ok: "bool | None" = None if use_device else False
        # slot allocation
        self._free: List[int] = []
        self._n_slots = 0
        self._slot_rids: Dict[int, Tuple[int, ...]] = {}
        # incidence: rid -> ordered subscriber slots (source of truth)
        self._members: Dict[int, List[int]] = {}
        # packed mirror: rid -> [start, capacity] extents over _indices_h
        self._ext: Dict[int, List[int]] = {}
        self._indices_h = np.full(1, -1, np.int32)  # last = sentinel
        self._row_of_h = np.full(1, -1, np.int32)
        self._rpad = 1
        self._rebuild = True
        self._dirty: List[int] = []  # positions to re-scatter
        # device cache of the mirror
        self._indices_d = None
        self._row_of_d = None
        self._fns: Dict[tuple, object] = {}
        # counters (status / flight recorder)
        self.matched_total = 0
        self.rebuilds = 0
        self.scatters = 0
        self.phase_s: Dict[str, float] = {name: 0.0 for name in PHASES}

    # -- membership (event-loop only) ----------------------------------

    def add(self, rids: Sequence[int]) -> int:
        """Register one subscriber over `rids`; returns its slot."""
        slot = self._free.pop() if self._free else self._n_slots
        if slot == self._n_slots:
            self._n_slots += 1
        rids = tuple(int(r) for r in rids)
        self._slot_rids[slot] = rids
        for rid in rids:
            members = self._members.setdefault(rid, [])
            members.append(slot)
            ext = self._ext.get(rid)
            if ext is None or len(members) > ext[1]:
                self._rebuild = True
            elif not self._rebuild:
                pos = ext[0] + len(members) - 1
                self._indices_h[pos] = slot
                self._dirty.append(pos)
        return slot

    def remove(self, slot: int) -> None:
        """Drop one subscriber's incidence rows (idempotent)."""
        rids = self._slot_rids.pop(slot, None)
        if rids is None:
            return
        self._free.append(slot)
        for rid in rids:
            members = self._members.get(rid)
            if not members or slot not in members:
                continue
            i = members.index(slot)
            members[i] = members[-1]
            members.pop()
            if not members:
                del self._members[rid]
            if self._rebuild:
                continue
            ext = self._ext.get(rid)
            if ext is None:
                continue
            # Mirror the swap-delete in the packed extent: the removed
            # position takes the tail slot and the tail clears.
            tail = ext[0] + len(members)
            self._indices_h[ext[0] + i] = (
                members[i] if i < len(members) else -1
            )
            self._indices_h[tail] = -1
            self._dirty.append(ext[0] + i)
            self._dirty.append(tail)

    def watchers(self, rid: int) -> int:
        return len(self._members.get(int(rid), ()))

    def __len__(self) -> int:
        return len(self._slot_rids)

    # -- matching ------------------------------------------------------

    def match(self, changed_rids: Sequence[int]) -> np.ndarray:
        """Intersect the changed-rid set with the incidence structure;
        returns [M, 2] int32 (subscriber_slot, rid) pairs. M is exact —
        padding never leaks to the caller."""
        work = [
            int(r) for r in changed_rids if self._members.get(int(r))
        ]
        total = sum(len(self._members[r]) for r in work)
        if total == 0:
            return np.zeros((0, 2), np.int32)
        ph = PhaseRecorder(self.component, self.phase_s)
        pairs = None
        if self._device_ok():
            try:
                pairs = self._match_device(work, total, ph)
            except Exception:
                # A device fault must never take down the fanout; the
                # mirror serves this match and the next one retries.
                log.exception("device match failed; host mirror serves")
                self._indices_d = None
        if pairs is None:
            pairs = self._match_host(work)
            ph.lap("match")
        self.matched_total += len(pairs)
        return pairs

    def _match_host(self, work: List[int]) -> np.ndarray:
        parts = [
            np.stack(
                [
                    np.asarray(self._members[r], np.int32),
                    np.full(len(self._members[r]), r, np.int32),
                ],
                axis=1,
            )
            for r in work
        ]
        return np.concatenate(parts) if parts else np.zeros((0, 2), np.int32)

    def _match_device(self, work: List[int], total: int,
                      ph: PhaseRecorder) -> np.ndarray:
        self._sync_device()
        ph.lap("staging")  # incidence scatters / (re)placement
        cpad = _pow2(len(work))
        changed = np.full(cpad, -1, np.int32)
        changed[: len(work)] = work
        cap = _pow2(total)
        fn = self._match_fn(cap, cpad)
        out = fn(self._indices_d, self._row_of_d, self._put(changed))
        dispatch_mod.count_dispatch()  # the masked-gather launch
        ph.lap("match")
        # Landing the matched pairs is the match's one device->host
        # sync (counted; the pair count was host-known before launch).
        dispatch_mod.count_host_sync()
        pairs = np.asarray(out)
        ph.lap("download")
        return pairs[pairs[:, 0] >= 0]

    # -- device plumbing -----------------------------------------------

    def _device_ok(self) -> bool:
        if self._jax_ok is None:
            try:
                import jax  # noqa: F401

                self._jax_ok = True
            except Exception:  # pragma: no cover - jax ships in the image
                self._jax_ok = False
        return self._jax_ok

    def _put(self, arr):
        from doorman_tpu.solver.engine import place

        return place(arr, device=self._device)

    def _repack(self) -> None:
        """Rebuild the packed mirror: deterministic rid-major layout,
        per-rid extents with headroom, one sentinel tail position the
        gather's fill index points at."""
        self._ext = {}
        offset = 0
        order = sorted(self._members)
        for rid in order:
            cap = ceil_to(len(self._members[rid]) + _EXTENT_PAD, 8)
            self._ext[rid] = [offset, cap]
            offset += cap
        size = _pow2(max(offset, 1), 256) + 1  # +1: sentinel
        indices = np.full(size, -1, np.int32)
        row_of = np.full(size, -1, np.int32)
        for rid in order:
            start, cap = self._ext[rid]
            members = self._members[rid]
            indices[start : start + len(members)] = members
            row_of[start : start + cap] = rid
        self._indices_h, self._row_of_h = indices, row_of
        self._rpad = _pow2(max(order, default=0) + 1, 256)
        self._rebuild = False
        self._dirty = []
        self._indices_d = self._row_of_d = None
        self.rebuilds += 1

    def _sync_device(self) -> None:
        """Bring the device cache up to the mirror: a repacked (or
        first) table places whole; steady churn scatters only the dirty
        positions — the same staged-dirty idiom as the tick engines'
        upload path."""
        if self._rebuild:
            self._repack()
        if self._indices_d is None:
            self._indices_d = self._put(self._indices_h)
            self._row_of_d = self._put(self._row_of_h)
            self._dirty = []
            return
        if not self._dirty:
            return
        dirty = np.unique(np.asarray(self._dirty, np.int64))
        self._dirty = []
        dpad = _pow2(len(dirty))
        # Padding scatters write the sentinel position with -1: a no-op
        # by construction (the sentinel is always -1).
        pos = np.full(dpad, len(self._indices_h) - 1, np.int64)
        val = np.full(dpad, -1, np.int32)
        pos[: len(dirty)] = dirty
        val[: len(dirty)] = self._indices_h[dirty]
        self._indices_d = self._scatter_fn(dpad)(
            self._indices_d, self._put(pos), self._put(val)
        )
        dispatch_mod.count_dispatch()  # the point-scatter launch
        self.scatters += 1

    def _scatter_fn(self, dpad: int):
        key = ("scatter", dpad)
        fn = self._fns.get(key)
        if fn is None:
            import jax

            # The incidence table is permanently device-resident:
            # donating it through each point-scatter updates it in
            # place (the `self._indices_d = fn(self._indices_d, ...)`
            # rebind at the call site is the donation-safe pattern the
            # lint's device-sync-taint rule checks) instead of
            # allocating a fresh table per subscribe/unsubscribe burst.
            fn = jax.jit(
                lambda ind, pos, val: ind.at[pos].set(val),
                donate_argnums=(0,),
            )
            self._fns[key] = fn
        return fn

    def _match_fn(self, cap: int, cpad: int):
        key = ("match", cap, cpad, len(self._indices_h), self._rpad)
        fn = self._fns.get(key)
        if fn is None:
            import jax
            import jax.numpy as jnp

            rpad = self._rpad
            sentinel = len(self._indices_h) - 1

            def match(indices, row_of, changed):
                # Changed-rid set -> row mask (padding rids are -1 and
                # drop); a position matches when it holds a live slot
                # of a changed row. fill_value points every padding
                # gather at the sentinel (-1, -1) pair, filtered on
                # the host after landing.
                rmask = (
                    jnp.zeros((rpad,), jnp.bool_)
                    .at[changed]
                    .set(True, mode="drop")
                )
                mask = (indices >= 0) & rmask[
                    jnp.clip(row_of, 0, rpad - 1)
                ]
                idx = jnp.nonzero(mask, size=cap, fill_value=sentinel)[0]
                return jnp.stack([indices[idx], row_of[idx]], axis=1)

            fn = jax.jit(match)
            self._fns[key] = fn
        return fn

    # -- introspection -------------------------------------------------

    def status(self) -> dict:
        return {
            "slots": len(self._slot_rids),
            "rows": len(self._members),
            "packed_size": int(len(self._indices_h)),
            "matched_total": int(self.matched_total),
            "rebuilds": int(self.rebuilds),
            "scatters": int(self.scatters),
            "device": bool(self._indices_d is not None),
        }
