"""Master election.

Capability parity with reference go/server/election/election.go:29-172:
an Election is something a server runs; it reports mastership changes and
the identity of the current master. Two implementations:

  * TrivialElection — the participant wins immediately (single-server
    deployments, tests).
  * KVElection — the reference's etcd flow (TTL'd lock key: acquire with
    set-if-absent, renew every ttl/3, watch broadcasts the holder)
    generalized over an abstract LeaseKV so the failover state machine is
    testable without an etcd cluster. EtcdKV speaks the etcd v2 HTTP API
    when an etcd endpoint is actually available; InMemoryKV backs tests and
    multi-server single-process setups.
"""

from __future__ import annotations

import abc
import asyncio
import json
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import AsyncIterator, Awaitable, Callable, Dict, Optional, Tuple

IsMasterCallback = Callable[[bool], Awaitable[None]]
CurrentMasterCallback = Callable[[str], Awaitable[None]]


class Election(abc.ABC):
    """A master election. `run` starts campaigning and returns immediately;
    outcomes are delivered through the callbacks (mirrors the reference's
    IsMaster()/Current() channels)."""

    @abc.abstractmethod
    async def run(
        self,
        id: str,
        on_is_master: IsMasterCallback,
        on_current: CurrentMasterCallback,
    ) -> None:
        ...

    async def stop(self) -> None:
        pass


class TrivialElection(Election):
    """The participant immediately wins (reference election.go:51-73)."""

    def __str__(self) -> str:
        return "no election, acting as the master"

    async def run(self, id, on_is_master, on_current) -> None:
        await on_is_master(True)
        await on_current(id)


class LeaseKV(abc.ABC):
    """A tiny TTL'd-key store: just enough of etcd for the election."""

    @abc.abstractmethod
    async def acquire(self, key: str, value: str, ttl: float) -> bool:
        """Set key=value with ttl iff the key does not exist (or has
        expired). Returns True on success."""

    @abc.abstractmethod
    async def refresh(self, key: str, value: str, ttl: float) -> bool:
        """Extend the ttl iff the key still holds `value`."""

    @abc.abstractmethod
    async def get(self, key: str) -> Optional[str]:
        """Current live value of the key, or None."""


class InMemoryKV(LeaseKV):
    """Process-local LeaseKV for tests and single-process multi-server
    topologies. Supports fault injection via `expire`."""

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self._data: Dict[str, Tuple[str, float]] = {}

    def _live(self, key: str) -> Optional[str]:
        entry = self._data.get(key)
        if entry is None:
            return None
        value, expiry = entry
        if expiry <= self._clock():
            del self._data[key]
            return None
        return value

    async def acquire(self, key, value, ttl) -> bool:
        if self._live(key) is not None:
            return False
        self._data[key] = (value, self._clock() + ttl)
        return True

    async def refresh(self, key, value, ttl) -> bool:
        if self._live(key) != value:
            return False
        self._data[key] = (value, self._clock() + ttl)
        return True

    async def get(self, key) -> Optional[str]:
        return self._live(key)

    def expire(self, key: str) -> None:
        """Fault injection: drop the lock as if its TTL lapsed."""
        self._data.pop(key, None)


class EtcdKV(LeaseKV):
    """etcd v2 HTTP API LeaseKV (reference election.go:112-171 uses the v2
    client). Blocking HTTP is pushed to the default executor; this is a
    control-plane path where latency tolerance is seconds."""

    def __init__(self, endpoints: list[str]):
        if not endpoints:
            raise ValueError("EtcdKV needs at least one endpoint")
        self._endpoints = [e.rstrip("/") for e in endpoints]

    async def _request(
        self, method: str, key: str, params: Optional[dict] = None
    ) -> Optional[dict]:
        def call() -> Optional[dict]:
            for endpoint in self._endpoints:
                url = f"{endpoint}/v2/keys{key}"
                data = None
                if params is not None:
                    data = urllib.parse.urlencode(params).encode()
                req = urllib.request.Request(url, data=data, method=method)
                try:
                    with urllib.request.urlopen(req, timeout=5) as resp:
                        return json.load(resp)
                except urllib.error.HTTPError as e:
                    try:
                        return json.load(e)
                    except Exception:
                        return None
                except OSError:
                    continue
            return None

        return await asyncio.get_running_loop().run_in_executor(None, call)

    async def acquire(self, key, value, ttl) -> bool:
        out = await self._request(
            "PUT", key,
            {"value": value, "ttl": int(ttl), "prevExist": "false"},
        )
        return bool(out) and "errorCode" not in out

    async def refresh(self, key, value, ttl) -> bool:
        out = await self._request(
            "PUT", key,
            {
                "value": value,
                "ttl": int(ttl),
                "prevExist": "true",
                "prevValue": value,
            },
        )
        return bool(out) and "errorCode" not in out

    async def get(self, key) -> Optional[str]:
        out = await self._request("GET", key)
        if not out or "errorCode" in out:
            return None
        return out.get("node", {}).get("value")


class KVElection(Election):
    """TTL-lock election over a LeaseKV (reference election.go:89-172):
    campaign with acquire, renew every ttl/3, report loss when a renewal
    fails; a watcher polls the key and broadcasts the current master."""

    def __init__(self, kv: LeaseKV, lock: str, ttl: float = 10.0):
        self._kv = kv
        self._lock = lock
        self._ttl = ttl
        self._tasks: list[asyncio.Task] = []

    def __str__(self) -> str:
        return f"kv lock: {self._lock} (ttl {self._ttl}s)"

    async def run(self, id, on_is_master, on_current) -> None:
        self._tasks.append(
            asyncio.create_task(self._campaign(id, on_is_master))
        )
        self._tasks.append(asyncio.create_task(self._watch(on_current)))

    async def stop(self) -> None:
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            try:
                await t
            except (asyncio.CancelledError, Exception):
                pass
        self._tasks.clear()

    async def _campaign(self, id: str, on_is_master: IsMasterCallback) -> None:
        while True:
            if not await self._kv.acquire(self._lock, id, self._ttl):
                await asyncio.sleep(self._ttl)
                continue
            await on_is_master(True)
            while True:
                await asyncio.sleep(self._ttl / 3)
                if not await self._kv.refresh(self._lock, id, self._ttl):
                    await on_is_master(False)
                    break

    async def _watch(self, on_current: CurrentMasterCallback) -> None:
        last: Optional[str] = None
        while True:
            current = await self._kv.get(self._lock)
            value = current or ""
            if value != last:
                last = value
                await on_current(value)
            await asyncio.sleep(min(1.0, self._ttl / 3))
