"""Master election.

Capability parity with reference go/server/election/election.go:29-172:
an Election is something a server runs; it reports mastership changes and
the identity of the current master. Two implementations:

  * TrivialElection — the participant wins immediately (single-server
    deployments, tests).
  * KVElection — the reference's etcd flow (TTL'd lock key: acquire with
    set-if-absent, renew every ttl/3, watch broadcasts the holder)
    generalized over an abstract LeaseKV so the failover state machine is
    testable without an etcd cluster. EtcdKV speaks the etcd v3 gateway
    (shared client server/etcd.py — the same API generation the config
    source uses) when an etcd endpoint is actually available; InMemoryKV
    backs tests and multi-server single-process setups.
"""

from __future__ import annotations

import abc
import asyncio
import logging
import time
from typing import Awaitable, Callable, Dict, Optional, Tuple

from doorman_tpu.server.etcd import EtcdGateway

log = logging.getLogger(__name__)

IsMasterCallback = Callable[[bool], Awaitable[None]]
CurrentMasterCallback = Callable[[str], Awaitable[None]]


def shard_lock_key(lock: str, shard: int) -> str:
    """The per-shard election lease key of a federated deployment:
    shard k's candidates campaign for `<lock>/shard<k>` instead of the
    single root lock, so each shard runs its OWN mastership (N
    concurrent masters, one per shard, off one etcd namespace) and a
    shard's failover never disturbs the others. Shard -1 (or any
    negative) means "not federated" and returns the lock unchanged."""
    if shard < 0:
        return lock
    return f"{lock.rstrip('/')}/shard{int(shard)}"


class Election(abc.ABC):
    """A master election. `run` starts campaigning and returns immediately;
    outcomes are delivered through the callbacks (mirrors the reference's
    IsMaster()/Current() channels)."""

    @abc.abstractmethod
    async def run(
        self,
        id: str,
        on_is_master: IsMasterCallback,
        on_current: CurrentMasterCallback,
    ) -> None:
        ...

    async def stop(self) -> None:
        pass


class TrivialElection(Election):
    """The participant immediately wins (reference election.go:51-73)."""

    def __str__(self) -> str:
        return "no election, acting as the master"

    async def run(self, id, on_is_master, on_current) -> None:
        await on_is_master(True)
        await on_current(id)


class LeaseKV(abc.ABC):
    """A tiny TTL'd-key store: just enough of etcd for the election."""

    @abc.abstractmethod
    async def acquire(self, key: str, value: str, ttl: float) -> bool:
        """Set key=value with ttl iff the key does not exist (or has
        expired). Returns True on success."""

    @abc.abstractmethod
    async def refresh(self, key: str, value: str, ttl: float) -> bool:
        """Extend the ttl iff the key still holds `value`."""

    @abc.abstractmethod
    async def get(self, key: str) -> Optional[str]:
        """Current live value of the key, or None."""

    async def wait_for_change(self, key: str, timeout: float) -> None:
        """Block until the key (probably) changed, or `timeout`. The
        default is a plain sleep (polling); KVs with a real watch (etcd)
        override it so the election's current-master broadcast follows
        changes instantly, like the reference's watcher goroutine
        (election.go:141-170)."""
        await asyncio.sleep(timeout)


class InMemoryKV(LeaseKV):
    """Process-local LeaseKV for tests and single-process multi-server
    topologies. Supports fault injection via `expire`."""

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self._data: Dict[str, Tuple[str, float]] = {}

    def _live(self, key: str) -> Optional[str]:
        entry = self._data.get(key)
        if entry is None:
            return None
        value, expiry = entry
        if expiry <= self._clock():
            del self._data[key]
            return None
        return value

    async def acquire(self, key, value, ttl) -> bool:
        if self._live(key) is not None:
            return False
        self._data[key] = (value, self._clock() + ttl)
        return True

    async def refresh(self, key, value, ttl) -> bool:
        if self._live(key) != value:
            return False
        self._data[key] = (value, self._clock() + ttl)
        return True

    async def get(self, key) -> Optional[str]:
        return self._live(key)

    def expire(self, key: str) -> None:
        """Fault injection: drop the lock as if its TTL lapsed."""
        self._data.pop(key, None)


class EtcdKV(LeaseKV):
    """etcd v3 LeaseKV over the shared gateway client (server/etcd.py).

    The reference election used its era's v2 client with a TTL'd key
    (election.go:112-171); the v3 idiom for the same lock is a lease:
    acquire = lease grant + transactional create (put iff the key does
    not exist), refresh = lease keepalive (the key dies with the lease),
    get = range read. Blocking HTTP runs in the default executor with a
    short per-request timeout: renewal failure must be observed well
    inside the lock TTL, or a partitioned master keeps acting as master
    after a standby wins (the v2 client's 5s timeout had the same
    role)."""

    # Cap for any single gateway HTTP request (the gateway splits it
    # across endpoints on failover). Mastership-loss detection must fit
    # inside KVElection's renewal cadence (ttl/3 with ttl defaulting to
    # 10s), not the gateway's lenient config-watch default — so each
    # OPERATION also gets an overall budget, sized to the number of
    # sequential RPCs it issues (refresh: a 0.5*ttl window covering a
    # 0.32*ttl first attempt plus one transient-failure retry, see
    # refresh(); acquire, which is not on the loss-detection path, gets
    # 3x REQUEST_TIMEOUT for its get + lease-grant + transactional-put
    # sequence). Budgeting each request off the operation's shared
    # deadline keeps the sum inside the budget instead of stacking
    # per-request timeouts past the lock TTL and re-opening the
    # split-brain window.
    REQUEST_TIMEOUT = 5.0

    def __init__(self, endpoints: list[str],
                 gateway: Optional[EtcdGateway] = None):
        """`gateway` substitutes a pre-built gateway client (the chaos
        harness injects a fault-wrapping one); default builds the
        shared EtcdGateway over `endpoints`."""
        self._gw = gateway or EtcdGateway(endpoints)
        self._leases: Dict[str, int] = {}  # lock key -> held lease id
        self._fast_watches = 0  # consecutive instant watch returns

    def _per_request(self, budget: float) -> Callable[[], float]:
        """Per-HTTP-request timeouts drawn from one operation deadline:
        each call gets the remaining budget (capped at REQUEST_TIMEOUT,
        floored so a nearly-exhausted deadline still issues ONE fast
        request rather than one that cannot succeed at all — the floor
        is sized per endpoint because the gateway splits it across its
        failover list). After that one floored request the closure
        RAISES: the caller's wait_for has already abandoned the
        executor thread by then, and an unbounded floor would let that
        orphan keep hammering etcd endpoints with doomed requests for
        the rest of its sequence during a partition."""
        # Wall clock by design (here and below): these budgets pace real
        # etcd sockets. Chaos virtualizes time ABOVE this seam, at the
        # election-KV / gateway injectors, so replays never reach these.
        end = time.monotonic() + budget  # doorman: allow[seeded-determinism]
        floor = 0.1 * len(self._gw.endpoints)
        floored = [False]

        def t() -> float:
            remaining = end - time.monotonic()  # doorman: allow[seeded-determinism]
            if remaining <= 0:
                if floored[0]:
                    raise TimeoutError(
                        "etcd operation budget exhausted "
                        f"({budget:.1f}s); abandoning the sequence"
                    )
                floored[0] = True
                return floor
            return max(min(self.REQUEST_TIMEOUT, remaining), floor)

        return t

    async def _call(self, fn, budget: float):
        try:
            # Slack over the inner budget: requests that hit the
            # deadline floor should resolve (or fail) on their own and
            # surface their real outcome, not be abandoned mid-flight.
            return await asyncio.wait_for(
                asyncio.get_running_loop().run_in_executor(None, fn),
                budget + min(1.0, budget / 4),
            )
        except Exception as e:
            # Failures are expected during partitions, but silence here
            # would make a misconfigured endpoint list undiagnosable —
            # the campaign loop would just never win, quietly.
            log.warning("etcd election request failed: %r", e)
            return None

    def _revoke_quietly(self, lease_id: int) -> bool:
        """Best-effort lease revoke at the full REQUEST_TIMEOUT — OFF
        any operation budget, because cleanup matters most precisely
        when the budget is already spent. Returns True when etcd
        confirmed the revoke (callers use this to decide whether their
        backstop must stay armed); on False the TTL is the backstop."""
        try:
            self._gw.lease_revoke(lease_id, timeout=self.REQUEST_TIMEOUT)
            return True
        except Exception:
            return False

    def _spawn_revoke(self, lease_id: "int | None") -> None:
        """Best-effort background revoke of a lease whose operation we
        abandoned (asyncio.wait_for cannot cancel the executor thread,
        and the thread's etcd side effects — a granted lease, a
        just-extended TTL, even a lock acquired after we gave up on it
        — would otherwise pin a stale key for a full TTL with nobody
        renewing it)."""
        if not lease_id:
            return
        try:
            asyncio.get_running_loop().run_in_executor(
                None, lambda: self._revoke_quietly(lease_id)
            )
        except RuntimeError:
            pass  # loop shutting down

    async def acquire(self, key, value, ttl) -> bool:
        budget = 3.0 * self.REQUEST_TIMEOUT
        t = self._per_request(budget)
        # Shared with the executor thread: `lease` is the granted lease
        # (if any), `abandoned` is set when the caller stops waiting.
        # Every interleaving must end with an unrenewed lock revoked:
        #   - timeout before the grant: caller sees lease=None (no-op),
        #     the thread later finds abandoned=True after its put and
        #     self-revokes;
        #   - timeout after the grant: the caller revokes the recorded
        #     lease AND the thread self-revokes on its abandoned check
        #     (double revoke of a dead lease is harmless);
        #   - clean race loss: the thread revokes inline and clears
        #     `lease`, so the caller does not issue a redundant revoke.
        state: Dict[str, object] = {"lease": None, "abandoned": False}

        def attempt() -> Optional[int]:
            # Cheap existence probe first: the standby's campaign loop
            # runs for the deployment's lifetime and the lock is almost
            # always held — don't churn lease grants on every cycle.
            if self._gw.get(key, timeout=t()) is not None:
                return None
            lease_id = self._gw.lease_grant(ttl, timeout=t())
            state["lease"] = lease_id
            try:
                won = self._gw.put_if_absent(
                    key, value, lease_id, timeout=t()
                )
            except Exception:
                # The put may have COMMITTED in etcd even though the
                # response was lost: revoke so a lock nobody will renew
                # cannot survive, then surface the failure. `lease`
                # stays recorded when the revoke fails so the caller's
                # _spawn_revoke backstop still fires.
                if self._revoke_quietly(lease_id):
                    state["lease"] = None
                raise
            if state["abandoned"] or not won:
                if self._revoke_quietly(lease_id):
                    state["lease"] = None
                return None
            return lease_id

        try:
            lease_id = await self._call(attempt, budget)
        except asyncio.CancelledError:
            # stop() during an in-flight campaign: the executor thread
            # may still win the lock after we are gone. Mark it
            # abandoned (the thread self-revokes on its check) and
            # backstop any already-granted lease ourselves.
            state["abandoned"] = True
            self._spawn_revoke(state["lease"])
            raise
        if lease_id is None:
            # We are about to report "not master": no lock created by
            # the (possibly still-running) thread may survive unrenewed.
            state["abandoned"] = True
            self._spawn_revoke(state["lease"])
            return False
        self._leases[key] = lease_id
        return True

    async def refresh(self, key, value, ttl) -> bool:
        lease_id = self._leases.get(key)
        if lease_id is None:
            return False
        # The loss-detection path: sleep(ttl/3) + this operation must
        # conclude well before the lock TTL lapses and a standby wins;
        # the WHOLE operation (slack included) fits a 0.5*ttl window so
        # the worst case stays ~0.83*ttl. Within that window a single
        # TRANSIENT failure — an executor thread starved by a
        # concurrent XLA compile, one dropped etcd round-trip — retries
        # instead of reading as mastership loss (small-TTL elections
        # flapped under load without this). The FIRST attempt gets the
        # lion's share (0.32*ttl, +_call's budget/4 slack = 0.4*ttl —
        # the previous single-attempt tolerance, so a slow-but-healthy
        # etcd still succeeds first try); the retry runs in whatever
        # window remains, which is nearly everything when the first
        # attempt failed fast. DEFINITE losses (lease TTL 0, key not
        # ours) never retry.
        deadline = time.monotonic() + 0.5 * ttl  # doorman: allow[seeded-determinism]
        budget = min(self.REQUEST_TIMEOUT, 0.32 * ttl)

        outcome: "bool | None" = None
        for attempt in range(2):
            t = self._per_request(budget)

            def renew() -> "bool | None":
                if self._gw.lease_keepalive(lease_id, timeout=t()) <= 0:
                    return False  # lease gone: definite loss
                # The LeaseKV contract: extend iff the key still holds
                # OUR value. A lease can outlive the key (operator
                # `etcdctl del` to force a new election, or an
                # overwrite): renewing on the lease alone would leave
                # two masters.
                try:
                    held = self._gw.get(key, timeout=t())
                except Exception:
                    return None  # can't verify ownership: transient
                ours = held is not None and held.decode() == value
                if not ours:
                    # The keepalive above just re-extended the lease to
                    # a full TTL; abandoning it now would pin a stale
                    # lock key for that long with nobody renewing — a
                    # full-TTL leaderless window. Release it so
                    # re-election is immediate.
                    self._revoke_quietly(lease_id)
                return ours

            try:
                outcome = await self._call(renew, budget)
            except asyncio.CancelledError:
                # stop() mid-renewal: the thread's keepalive may have
                # just extended the lease to a full TTL; do not leave
                # it pinned by a master that no longer exists.
                self._spawn_revoke(lease_id)
                self._leases.pop(key, None)
                raise
            if outcome is not None:
                break
            remaining = deadline - time.monotonic()  # doorman: allow[seeded-determinism]
            if remaining <= 0.05 * ttl:
                break  # no meaningful retry window left
            budget = min(self.REQUEST_TIMEOUT, remaining / 1.25)
        ok = bool(outcome)
        if not ok:
            # Mastership is lost; a fresh acquire grants a fresh lease.
            # The thread may still be mid-renewal (timeout), or its own
            # step-down revoke may have failed: backstop-revoke on every
            # failure (revoking a dead lease is harmless) so the lock is
            # not pinned by a master that has already stepped down.
            self._spawn_revoke(lease_id)
            self._leases.pop(key, None)
            return False
        return True

    async def get(self, key) -> Optional[str]:
        value = await self._call(
            lambda: self._gw.get(key, timeout=self.REQUEST_TIMEOUT),
            self.REQUEST_TIMEOUT,
        )
        return value.decode() if value is not None else None

    async def wait_for_change(self, key, timeout) -> None:
        """Real etcd watch: returns as soon as the lock key changes, so
        mastership broadcasts propagate in RPC time rather than a poll
        interval. Falls back to a sleep when the watch cannot be
        established (partition), and rate-limits consecutive instant
        returns — an endpoint whose /v3/watch answers immediately with
        an error body or a closed stream reports "success" per the
        gateway's lenient contract, and without a floor the watch loop
        would hammer etcd back-to-back (the polling default this
        replaced was bounded to one get per interval)."""
        start = time.monotonic()  # doorman: allow[seeded-determinism]
        ok = False
        try:
            ok = await asyncio.get_running_loop().run_in_executor(
                None,
                lambda: self._gw.wait_for_change(key, timeout=timeout),
            )
        except Exception:
            ok = False
        if not ok:
            self._fast_watches = 0
            await asyncio.sleep(min(timeout, 1.0))
            return
        if time.monotonic() - start < 0.05:  # doorman: allow[seeded-determinism]
            # A genuine change can return this fast once or twice in a
            # row (re-election storm); only a degenerate watch does so
            # indefinitely. Escalate to the full poll interval then.
            self._fast_watches += 1
            await asyncio.sleep(
                min(timeout, 1.0)
                if self._fast_watches >= 5
                else 0.05
            )
        else:
            self._fast_watches = 0


class KVElection(Election):
    """TTL-lock election over a LeaseKV (reference election.go:89-172):
    campaign with acquire, renew every ttl/3, report loss when a renewal
    fails; a watcher polls the key and broadcasts the current master."""

    def __init__(self, kv: LeaseKV, lock: str, ttl: float = 10.0):
        self._kv = kv
        self._lock = lock
        self._ttl = ttl
        self._tasks: list[asyncio.Task] = []

    def __str__(self) -> str:
        return f"kv lock: {self._lock} (ttl {self._ttl}s)"

    async def run(self, id, on_is_master, on_current) -> None:
        self._tasks.append(
            asyncio.create_task(self._campaign(id, on_is_master))
        )
        self._tasks.append(asyncio.create_task(self._watch(on_current)))

    async def stop(self) -> None:
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            try:
                await t
            except (asyncio.CancelledError, Exception):
                pass
        self._tasks.clear()

    async def _campaign(self, id: str, on_is_master: IsMasterCallback) -> None:
        while True:
            if not await self._kv.acquire(self._lock, id, self._ttl):
                await asyncio.sleep(self._ttl)
                continue
            await on_is_master(True)
            while True:
                await asyncio.sleep(self._ttl / 3)
                if not await self._kv.refresh(self._lock, id, self._ttl):
                    await on_is_master(False)
                    break

    async def _watch(self, on_current: CurrentMasterCallback) -> None:
        last: Optional[str] = None
        while True:
            current = await self._kv.get(self._lock)
            value = current or ""
            if value != last:
                last = value
                await on_current(value)
            # A real watch (etcd) returns the moment the lock changes;
            # the plain-KV default sleeps the poll interval (reference
            # watcher goroutine, election.go:141-170).
            await self._kv.wait_for_change(
                self._lock, min(1.0, self._ttl / 3)
            )
