"""Shared etcd v3 HTTP/JSON gateway client.

One etcd dialect for the whole framework: both the config source
(server/sources.py, reference go/configuration/configuration.go:56-105)
and the election lock (server/election.py, reference
go/server/election/election.go:89-172) speak the v3 gateway exposed by
every etcd >= 3.4 (`/v3/kv/*`, `/v3/lease/*`, `/v3/watch`). The
reference used the v2 client API of its era; v2 is gone from modern
etcd builds, so the TPU framework standardizes on v3.

This image has no etcd client library, so the gateway is urllib over
the JSON transcoding endpoint; callers run it in an executor thread
(control-plane path, latency tolerance is seconds). Integration-tested
against an in-process fake speaking this exact HTTP surface
(tests/fake_etcd.py) plus live failover scenarios in
tests/test_etcd_integration.py.
"""

from __future__ import annotations

import base64
import json
import time
import urllib.request
from typing import List, Optional, Tuple


def _b64(data: "str | bytes") -> str:
    if isinstance(data, str):
        data = data.encode()
    return base64.b64encode(data).decode()


def prefix_range_end(prefix: "str | bytes") -> bytes:
    """The exclusive range end covering every key under `prefix` (the
    etcd v3 prefix idiom: last byte + 1, trimming trailing 0xff)."""
    if isinstance(prefix, str):
        prefix = prefix.encode()
    end = bytearray(prefix)
    while end:
        if end[-1] < 0xFF:
            end[-1] += 1
            return bytes(end)
        end.pop()
    return b"\x00"  # prefix was all 0xff: range to the keyspace end


class EtcdGateway:
    """Minimal etcd v3 gateway client: kv get/put/txn, leases, watch."""

    def __init__(self, endpoints: List[str]):
        if not endpoints:
            raise ValueError("etcd gateway needs at least one endpoint")
        self.endpoints = [
            (e if "://" in e else f"http://{e}").rstrip("/")
            for e in endpoints
        ]
        # Where wait_for_change starts its endpoint walk; advanced past
        # endpoints that fail to establish a watch (benign int race when
        # shared across executor threads).
        self._watch_endpoint = 0

    def _failover_budgets(self, timeout: float):
        """Yield (endpoint, per_endpoint_timeout) pairs such that the
        WHOLE failover sequence fits in `timeout`: the remaining budget
        is split evenly across the endpoints not yet tried, so a
        partitioned endpoint (which eats its slice to the last
        millisecond) still leaves the healthy ones a real share, while
        one that fails fast (connection refused) barely dents the
        budget and later endpoints inherit nearly all of it."""
        deadline = time.monotonic() + timeout  # doorman: allow[seeded-determinism]
        for i, endpoint in enumerate(self.endpoints):
            per = (deadline - time.monotonic()) / (len(self.endpoints) - i)  # doorman: allow[seeded-determinism]
            if per <= 0:
                return
            yield endpoint, per

    # The allow[seeded-determinism] marks in this file are deliberate:
    # failover deadlines pace real HTTP requests; chaos replaces the
    # whole gateway (FakeEtcd / injectors), never this layer's clock.
    def _post(self, path: str, payload: dict, timeout: float = 30.0) -> dict:
        data = json.dumps(payload).encode()
        last_err: Exception = RuntimeError("no endpoints")
        for endpoint, per in self._failover_budgets(timeout):
            try:
                req = urllib.request.Request(
                    endpoint + path,
                    data=data,
                    headers={"Content-Type": "application/json"},
                )
                with urllib.request.urlopen(req, timeout=per) as resp:
                    return json.loads(resp.read().decode())
            except Exception as e:  # try the next endpoint
                last_err = e
        raise last_err

    # -- kv ------------------------------------------------------------

    def get(self, key: str, timeout: float = 30.0) -> Optional[bytes]:
        out = self._post("/v3/kv/range", {"key": _b64(key)}, timeout)
        kvs = out.get("kvs", [])
        if not kvs:
            return None
        return base64.b64decode(kvs[0]["value"])

    def put(
        self,
        key: str,
        value: "str | bytes",
        lease_id: int = 0,
        timeout: float = 30.0,
    ) -> None:
        payload = {"key": _b64(key), "value": _b64(value)}
        if lease_id:
            payload["lease"] = str(lease_id)
        self._post("/v3/kv/put", payload, timeout)

    def get_prefix(
        self, prefix: str, timeout: float = 30.0
    ) -> List[Tuple[str, bytes]]:
        """All (key, value) pairs under `prefix`, key-sorted (the v3
        range read the persistence backend's chunked journal uses)."""
        out = self._post(
            "/v3/kv/range",
            {
                "key": _b64(prefix),
                "range_end": _b64(prefix_range_end(prefix)),
                # Server default caps a range at its page size; the
                # snapshot/journal keyspace is pruned to stay well under
                # any realistic page, but ask for no cap explicitly.
                "limit": "0",
            },
            timeout,
        )
        pairs = [
            (
                base64.b64decode(kv["key"]).decode(),
                base64.b64decode(kv.get("value", "")),
            )
            for kv in out.get("kvs", [])
        ]
        return sorted(pairs)

    def delete_prefix(self, prefix: str, timeout: float = 30.0) -> int:
        """Delete every key under `prefix`; returns the deleted count."""
        out = self._post(
            "/v3/kv/deleterange",
            {
                "key": _b64(prefix),
                "range_end": _b64(prefix_range_end(prefix)),
            },
            timeout,
        )
        return int(out.get("deleted", 0))

    def put_if_absent(
        self,
        key: str,
        value: "str | bytes",
        lease_id: int = 0,
        timeout: float = 30.0,
    ) -> bool:
        """Transactional create: put iff the key does not exist
        (compare create_revision == 0, the v3 idiom for the v2
        PrevNoExist acquire the reference election used,
        election.go:112-117). Returns True when the put happened."""
        put_op = {"key": _b64(key), "value": _b64(value)}
        if lease_id:
            put_op["lease"] = str(lease_id)
        out = self._post(
            "/v3/kv/txn",
            {
                "compare": [
                    {
                        "key": _b64(key),
                        "target": "CREATE",
                        "result": "EQUAL",
                        "create_revision": "0",
                    }
                ],
                "success": [{"request_put": put_op}],
                "failure": [],
            },
            timeout,
        )
        return bool(out.get("succeeded"))

    # -- leases ---------------------------------------------------------

    def lease_grant(self, ttl: float, timeout: float = 30.0) -> int:
        out = self._post(
            "/v3/lease/grant", {"TTL": str(max(int(ttl), 1))}, timeout
        )
        return int(out["ID"])

    def lease_keepalive(self, lease_id: int, timeout: float = 30.0) -> float:
        """Refresh the lease; returns the new TTL (0 or negative means
        the lease is gone and the lock key with it)."""
        out = self._post(
            "/v3/lease/keepalive", {"ID": str(lease_id)}, timeout
        )
        result = out.get("result", out)
        return float(result.get("TTL", 0))

    def lease_revoke(self, lease_id: int, timeout: float = 30.0) -> None:
        self._post("/v3/lease/revoke", {"ID": str(lease_id)}, timeout)

    # -- watch ----------------------------------------------------------

    def wait_for_change(self, key: str, timeout: float = 60.0) -> bool:
        """Block until the key changes (or timeout); one-shot watch.

        /v3/watch is a never-closing newline-delimited JSON stream: the
        first frame acknowledges watch creation, each later frame carries
        events. Read frame-by-frame and return on the first event frame.

        Returns True when a watch was actually established (an event
        arrived, the stream closed cleanly, or it idled past the read
        timeout after the creation ack) — the caller keeps fast polling.
        Returns False when every endpoint failed before establishing a
        watch — the caller should escalate its backoff."""
        payload = {"create_request": {"key": _b64(key)}}
        # Unlike _post, each endpoint gets the FULL remaining budget:
        # splitting it would shrink the idle window of a perfectly
        # healthy watch to timeout/n, multiplying the caller's re-watch
        # + get-poll churn by the endpoint count. Failover instead works
        # across calls: an endpoint that fails before establishing a
        # watch is skipped on the next call (the caller loops), so one
        # burned cycle moves the watch to a healthy endpoint for good.
        deadline = time.monotonic() + timeout  # doorman: allow[seeded-determinism]
        n = len(self.endpoints)
        start = self._watch_endpoint  # snapshot: the loop mutates it
        for j in range(n):
            per = deadline - time.monotonic()  # doorman: allow[seeded-determinism]
            if per <= 0:
                break
            i = (start + j) % n
            endpoint = self.endpoints[i]
            established = False
            try:
                req = urllib.request.Request(
                    endpoint + "/v3/watch",
                    data=json.dumps(payload).encode(),
                    headers={"Content-Type": "application/json"},
                )
                with urllib.request.urlopen(req, timeout=per) as resp:
                    while True:
                        line = resp.readline()
                        if not line:
                            break  # stream closed
                        try:
                            frame = json.loads(line.decode())
                        except ValueError:
                            break  # not a watch stream (proxy error?)
                        established = True  # got a frame (creation ack)
                        result = frame.get("result", frame)
                        if result.get("events"):
                            self._watch_endpoint = i
                            return True  # the key changed
                        # else: keep waiting for an event frame
            except Exception:
                pass  # timeout or transport failure; classified below
            if established:
                # Idle timeout, or a clean close after the creation
                # ack: a live watch existed, just no change within
                # `timeout`.
                self._watch_endpoint = i
                return True
            # The endpoint never produced a watch frame — including a
            # connectable endpoint whose stream closes instantly with
            # an empty or non-JSON body (degenerate proxy). Pinning
            # such an endpoint would make it permanently sticky; start
            # the next call (and the next iteration) past it.
            self._watch_endpoint = (i + 1) % n
        return False
